"""Benchmark: Transformer-base training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = achieved model FLOPs utilization / 0.35 (the BASELINE.md
target: >=35% MFU for Transformer-base on v5e; >1.0 beats the target).

Model: Transformer-base WMT16 config (reference:
tests/unittests/dist_transformer.py ModelHyperParams — d_model 512,
d_inner 2048, 6+6 layers, 8 heads), trained with bf16 AMP, full step
(fwd + autodiff + Adam) as one XLA computation.
"""

from __future__ import annotations

import json
import sys

from bench_common import (
    AllBatchesOOM,
    attach_metrics,
    compile_with_oom_backoff,
    enable_bench_metrics,
    log,
    measured_mfu,
    mfu,
    run_windows,
)

import os

BATCH = int(os.environ.get("PT_BENCH_BATCH", "64"))
SEQ = int(os.environ.get("PT_BENCH_SEQ", "256"))
VOCAB = 10000


def analytic_flops_per_step(cfg, batch, s, t):
    """Training FLOPs (fwd+bwd) per step: 6*flops_matmul_fwd with attention
    term; embedding lookups excluded."""
    d, di, L, h = cfg.d_model, cfg.d_inner, cfg.n_layer, cfg.n_head
    # per-layer matmul flops (fwd, mults*2):
    # qkv+out proj: 4 * 2*t*d*d ; ffn: 2 * 2*t*d*di ; attention: 2 * 2*h*t*t*(d/h)
    def layer_tokens(tok, t_kv):
        proj = 4 * 2 * tok * d * d
        ffn = 2 * 2 * tok * d * di
        attn = 2 * 2 * tok * t_kv * d
        return proj + ffn + attn

    enc = L * layer_tokens(batch * s, s)
    # decoder: self attn over t, cross attn over s (extra k/v proj + attn)
    dec_self = L * layer_tokens(batch * t, t)
    dec_cross = L * (2 * 2 * batch * t * d * d + 2 * 2 * batch * t * s * d)
    logits = 2 * batch * t * d * VOCAB
    fwd = enc + dec_self + dec_cross + logits
    return 3 * fwd  # bwd ~= 2x fwd


def main():
    # metrics-only telemetry: the registry snapshot rides every BENCH
    # row's `metrics` field (PT_BENCH_METRICS=0 opts out)
    enable_bench_metrics()
    import jax

    # Persistent XLA compilation cache: repeat runs (same program/shapes)
    # skip the multi-minute TPU compile entirely.
    jax.config.update("jax_compilation_cache_dir", "/tmp/pt_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as T

    backend = jax.default_backend()
    log(f"backend: {backend}, devices: {jax.devices()}")

    cfg = T.TransformerConfig(
        src_vocab_size=VOCAB,
        trg_vocab_size=VOCAB,
        max_length=SEQ + 2,
        d_model=512,
        d_inner=2048,
        n_head=8,
        n_layer=6,
        dropout=0.1,
    )
    use_scan = os.environ.get("PT_BENCH_SCAN", "0") == "1"
    scan_unroll = int(os.environ.get("PT_BENCH_SCAN_UNROLL", "1"))
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        model = (T.build_scan(cfg, unroll=scan_unroll) if use_scan
                 else T.build(cfg))
        fluid.optimizer.Adam(1e-4).minimize(model["loss"])
    log(f"layer mode: {'scan' if use_scan else 'unrolled'}")
    main_prog._amp = True  # bf16 matmuls, f32 master weights

    def make_exe():
        e = fluid.Executor()
        e.run(startup)
        return e

    try:
        exe, batch = compile_with_oom_backoff(
            make_exe,
            lambda e, b: e.run(main_prog,
                               feed=T.make_batch(cfg, b, SEQ, SEQ, seed=0),
                               fetch_list=[model["loss"]]),
            BATCH, floor=min(4, BATCH))
    except AllBatchesOOM:
        print(json.dumps(attach_metrics({"metric": "transformer_base_train_tokens_per_sec", "value": 0,
                          "unit": "tokens/sec", "vs_baseline": 0.0})))
        return

    # steady-state: feeds pre-staged on device, best-of-3 windows with one
    # sync per window (shared protocol, bench_common.run_windows; the
    # tunnel adds +-15% bursty host noise, BASELINE.md methodology)
    import jax as _jax

    feeds = [
        {k: _jax.device_put(v) for k, v in T.make_batch(cfg, batch, SEQ, SEQ,
                                                        seed=s).items()}
        for s in range(4)
    ]
    steps = 30
    best, mean = run_windows(exe, main_prog, model["loss"], feeds, steps)

    tokens_per_step = batch * SEQ  # target tokens (reference convention)
    tokens_per_sec = tokens_per_step * steps / best
    flops = analytic_flops_per_step(cfg, batch, SEQ, SEQ)
    mfu_best = mfu(flops, steps, best)
    mfu_mean = mfu(flops, steps, mean)
    # measured twin (roofline.py): XLA cost-analysis flops from the
    # compile report over the same best window — null when telemetry or
    # the report is off
    mfu_measured = measured_mfu(main_prog, best, steps)
    log(f"tokens/sec={tokens_per_sec:.0f}, analytic TFLOP/step={flops/1e12:.2f}, "
        f"MFU={mfu_best:.3f}, measured MFU={mfu_measured}")

    # Secondary metrics ride along in FRESH processes: two co-resident
    # compiled programs contaminate each other's HBM/timing (see
    # BASELINE.md methodology). Free this process's HBM first — donated
    # state, staged feeds, compiled executables all pin device memory
    # the children would otherwise share the chip with.
    def _rider(argv, env_extra):
        import subprocess

        try:
            env = {**os.environ, "PT_BENCH_RESNET": "0",
                   "PT_BENCH_LONGCTX": "0", "PT_BENCH_WARMSTART": "0",
                   "PT_BENCH_PIPELINE": "0", "PT_BENCH_SERVING": "0",
                   **env_extra}
            out = subprocess.run(argv, capture_output=True, text=True,
                                 timeout=900, env=env)
            if out.returncode != 0:
                log(f"rider {argv[-1]} rc={out.returncode}, "
                    f"stderr tail: {out.stderr[-500:]}")
            parsed = None
            for line in out.stdout.splitlines():
                line = line.strip()
                if line.startswith("{"):
                    try:
                        parsed = json.loads(line)
                    except ValueError:
                        pass  # non-JSON line that happens to start with {
            if isinstance(parsed, dict):
                # strip the (null) nested rider keys a child bench.py emits
                for k in ("resnet50", "long_context_t1024",
                          "long_context_t4096", "long_context_t8192",
                          "se_resnext50",
                          "bert_base", "deepfm", "ssd300", "warm_start",
                          "pipeline", "serving"):
                    parsed.pop(k, None)
            return parsed
        except Exception as e:  # never let a rider kill the headline
            log(f"rider bench failed: {type(e).__name__}: {e}")
            return None

    resnet = None
    families = {}
    here = os.path.dirname(os.path.abspath(__file__))
    want_resnet = os.environ.get("PT_BENCH_RESNET", "1") == "1"
    want_longctx = os.environ.get("PT_BENCH_LONGCTX", "1") == "1"
    want_families = os.environ.get("PT_BENCH_FAMILIES", "1") == "1"
    want_warmstart = os.environ.get("PT_BENCH_WARMSTART", "1") == "1"
    want_pipeline = os.environ.get("PT_BENCH_PIPELINE", "1") == "1"
    want_serving = os.environ.get("PT_BENCH_SERVING", "1") == "1"
    if (want_resnet or want_longctx or want_families or want_warmstart
            or want_pipeline or want_serving):
        del feeds
        fluid.executor.global_scope().clear()
        exe.close()
        jax.clear_caches()
    if want_resnet:
        resnet = _rider(
            [sys.executable, os.path.join(here, "bench_resnet.py")], {})
        log(f"resnet50: {resnet}")
    longctx_rows = {}
    if want_longctx:
        # long-context sweep at constant total tokens/step; t>=4096 rides
        # the in-kernel-causal flash path (no [t, t] tensor anywhere;
        # decoder-self dead blocks skipped) — VERDICT r4 item 2
        for t, bt in (("1024", "8"), ("4096", "2"), ("8192", "1")):
            row = _rider(
                [sys.executable, os.path.join(here, "bench.py")],
                {"PT_BENCH_BATCH": bt, "PT_BENCH_SEQ": t,
                 "PT_BENCH_FAMILIES": "0"})
            if row is not None:
                row["metric"] = f"transformer_longctx_t{t}_tokens_per_sec"
            longctx_rows[t] = row
            log(f"long-context t={t}: {row}")
    longctx = longctx_rows.get("1024")
    longctx4k = longctx_rows.get("4096")
    longctx8k = longctx_rows.get("8192")
    warm_start = None
    if want_warmstart:
        # cold-vs-warm start through the persistent compile cache: two
        # fresh children against one fresh cache dir; the second must
        # resolve every executable from disk (zero fresh XLA compiles)
        warm_start = _rider(
            [sys.executable, os.path.join(here, "bench_warmstart.py")], {})
        log(f"warm_start: {warm_start}")
    serving_row = None
    if want_serving:
        # continuous-batching decode: tokens/s + per-token latency
        # quantiles under a concurrency sweep through the serving
        # engine's prefill/decode split (zero fresh compiles after
        # warmup is the correctness rider)
        serving_row = _rider(
            [sys.executable, os.path.join(here, "bench_serving.py")], {})
        log(f"serving: {serving_row}")
    pipeline_row = None
    if want_pipeline:
        # sync vs pipelined trainer steady-state step time + the final
        # boundedness verdict mix (input/dispatch must be ~zero with
        # prefetch + sampled phases on)
        pipeline_row = _rider(
            [sys.executable, os.path.join(here, "bench_pipeline.py")], {})
        log(f"pipeline: {pipeline_row}")
    if want_families:
        # remaining BASELINE.md rows, one fresh process per family
        for fam, env in (
            ("se_resnext", {"PT_BENCH_BATCH": "128"}),
            ("bert", {"PT_BENCH_BATCH": "64", "PT_BENCH_SEQ": "128"}),
            ("deepfm", {"PT_BENCH_BATCH": "4096"}),
            ("ssd300", {"PT_BENCH_BATCH": "32"}),
        ):
            families[fam] = _rider(
                [sys.executable, os.path.join(here, "bench_family.py")],
                {"PT_BENCH_FAMILY": fam, "PT_BENCH_FAMILIES": "0", **env})
            log(f"{fam}: {families[fam]}")

    print(json.dumps(attach_metrics({
        "metric": "transformer_base_train_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(mfu_best / 0.35, 3),
        "value_mean": round(tokens_per_step * steps / mean, 1),
        "mfu_best": round(mfu_best, 4),
        "mfu_mean": round(mfu_mean, 4),
        "measured_mfu": mfu_measured,
        "resnet50": resnet,
        "long_context_t1024": longctx,
        "long_context_t4096": longctx4k,
        "long_context_t8192": longctx8k,
        "se_resnext50": families.get("se_resnext"),
        "bert_base": families.get("bert"),
        "deepfm": families.get("deepfm"),
        "ssd300": families.get("ssd300"),
        "warm_start": warm_start,
        "pipeline": pipeline_row,
        "serving": serving_row,
    })))


if __name__ == "__main__":
    main()
