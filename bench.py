"""Benchmark: Transformer-base training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = achieved model FLOPs utilization / 0.35 (the BASELINE.md
target: >=35% MFU for Transformer-base on v5e; >1.0 beats the target).

Model: Transformer-base WMT16 config (reference:
tests/unittests/dist_transformer.py ModelHyperParams — d_model 512,
d_inner 2048, 6+6 layers, 8 heads), trained with bf16 AMP, full step
(fwd + autodiff + Adam) as one XLA computation.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

V5E_PEAK_BF16 = 197e12  # FLOP/s per v5e chip

import os

BATCH = int(os.environ.get("PT_BENCH_BATCH", "64"))
SEQ = int(os.environ.get("PT_BENCH_SEQ", "256"))
VOCAB = 10000


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def analytic_flops_per_step(cfg, batch, s, t):
    """Training FLOPs (fwd+bwd) per step: 6*flops_matmul_fwd with attention
    term; embedding lookups excluded."""
    d, di, L, h = cfg.d_model, cfg.d_inner, cfg.n_layer, cfg.n_head
    # per-layer matmul flops (fwd, mults*2):
    # qkv+out proj: 4 * 2*t*d*d ; ffn: 2 * 2*t*d*di ; attention: 2 * 2*h*t*t*(d/h)
    def layer_tokens(tok, t_kv):
        proj = 4 * 2 * tok * d * d
        ffn = 2 * 2 * tok * d * di
        attn = 2 * 2 * tok * t_kv * d
        return proj + ffn + attn

    enc = L * layer_tokens(batch * s, s)
    # decoder: self attn over t, cross attn over s (extra k/v proj + attn)
    dec_self = L * layer_tokens(batch * t, t)
    dec_cross = L * (2 * 2 * batch * t * d * d + 2 * 2 * batch * t * s * d)
    logits = 2 * batch * t * d * VOCAB
    fwd = enc + dec_self + dec_cross + logits
    return 3 * fwd  # bwd ~= 2x fwd


def main():
    import jax

    # Persistent XLA compilation cache: repeat runs (same program/shapes)
    # skip the multi-minute TPU compile entirely.
    jax.config.update("jax_compilation_cache_dir", "/tmp/pt_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as T

    backend = jax.default_backend()
    log(f"backend: {backend}, devices: {jax.devices()}")

    cfg = T.TransformerConfig(
        src_vocab_size=VOCAB,
        trg_vocab_size=VOCAB,
        max_length=SEQ + 2,
        d_model=512,
        d_inner=2048,
        n_head=8,
        n_layer=6,
        dropout=0.1,
    )
    use_scan = os.environ.get("PT_BENCH_SCAN", "0") == "1"
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        model = T.build_scan(cfg) if use_scan else T.build(cfg)
        fluid.optimizer.Adam(1e-4).minimize(model["loss"])
    log(f"layer mode: {'scan' if use_scan else 'unrolled'}")
    main_prog._amp = True  # bf16 matmuls, f32 master weights

    exe = fluid.Executor()
    exe.run(startup)

    batch = BATCH
    while batch >= 4:
        try:
            feed = T.make_batch(cfg, batch, SEQ, SEQ, seed=0)
            t0 = time.time()
            exe.run(main_prog, feed=feed, fetch_list=[model["loss"]])
            log(f"compile+first step: {time.time() - t0:.1f}s (batch={batch})")
            break
        except Exception as e:
            # Only resource exhaustion triggers the halved-batch retry; any
            # other error is a real bug and must surface, not read as perf 0.
            msg = f"{type(e).__name__}: {e}"
            if "RESOURCE_EXHAUSTED" not in msg and "Out of memory" not in msg:
                raise
            log(f"batch {batch} OOM; halving")
            batch //= 2
            exe = fluid.Executor()
            exe.run(startup)
    else:
        print(json.dumps({"metric": "transformer_base_train", "value": 0,
                          "unit": "tokens/sec", "vs_baseline": 0.0}))
        return

    # steady-state timing: feeds pre-staged on device, no per-step host sync
    import jax as _jax

    feeds = [
        {k: _jax.device_put(v) for k, v in T.make_batch(cfg, batch, SEQ, SEQ,
                                                        seed=s).items()}
        for s in range(4)
    ]
    for f in feeds[:2]:
        exe.run(main_prog, feed=f, fetch_list=[model["loss"]])
    # 3x 30-step windows. The tunnel adds bursty host-side noise (measured
    # +-15% between otherwise identical windows), so the BEST window is the
    # honest estimate of device throughput and stays the headline `value`;
    # the mean over all windows is reported alongside so both estimators
    # are visible in the driver artifact (methodology documented in
    # BASELINE.md "Measurement methodology").
    steps = 30
    windows = []
    loss_v = None
    for w in range(3):
        t0 = time.time()
        loss = None
        for i in range(steps):
            loss = exe.run(main_prog, feed=feeds[i % 4],
                           fetch_list=[model["loss"]], return_numpy=False)
        loss_v = float(np.asarray(loss[0]))  # sync once per window
        elapsed = time.time() - t0
        log(f"window {w}: {steps} steps in {elapsed:.2f}s, "
            f"loss={loss_v:.3f}")
        windows.append(elapsed)
    best = min(windows)
    mean = sum(windows) / len(windows)

    tokens_per_step = batch * SEQ  # target tokens (reference convention)
    tokens_per_sec = tokens_per_step * steps / best
    flops = analytic_flops_per_step(cfg, batch, SEQ, SEQ)
    mfu = (flops * steps / best) / V5E_PEAK_BF16
    mfu_mean = (flops * steps / mean) / V5E_PEAK_BF16
    log(f"tokens/sec={tokens_per_sec:.0f}, analytic TFLOP/step={flops/1e12:.2f}, MFU={mfu:.3f}")

    # Secondary metrics ride along in FRESH processes: two co-resident
    # compiled programs contaminate each other's HBM/timing (see
    # BASELINE.md methodology). Free this process's HBM first — donated
    # state, staged feeds, compiled executables all pin device memory
    # the children would otherwise share the chip with.
    def _rider(argv, env_extra):
        import subprocess

        try:
            env = {**os.environ, "PT_BENCH_RESNET": "0",
                   "PT_BENCH_LONGCTX": "0", **env_extra}
            out = subprocess.run(argv, capture_output=True, text=True,
                                 timeout=900, env=env)
            if out.returncode != 0:
                log(f"rider {argv[-1]} rc={out.returncode}, "
                    f"stderr tail: {out.stderr[-500:]}")
            parsed = None
            for line in out.stdout.splitlines():
                line = line.strip()
                if line.startswith("{"):
                    try:
                        parsed = json.loads(line)
                    except ValueError:
                        pass  # non-JSON line that happens to start with {
            if isinstance(parsed, dict):
                parsed.pop("resnet50", None)
                parsed.pop("long_context_t1024", None)
            return parsed
        except Exception as e:  # never let a rider kill the headline
            log(f"rider bench failed: {type(e).__name__}: {e}")
            return None

    resnet = longctx = None
    here = os.path.dirname(os.path.abspath(__file__))
    want_resnet = os.environ.get("PT_BENCH_RESNET", "1") == "1"
    want_longctx = os.environ.get("PT_BENCH_LONGCTX", "1") == "1"
    if want_resnet or want_longctx:
        del feeds
        fluid.executor.global_scope().clear()
        exe.close()
        jax.clear_caches()
    if want_resnet:
        resnet = _rider(
            [sys.executable, os.path.join(here, "bench_resnet.py")], {})
        log(f"resnet50: {resnet}")
    if want_longctx:
        longctx = _rider(
            [sys.executable, os.path.join(here, "bench.py")],
            {"PT_BENCH_BATCH": "8", "PT_BENCH_SEQ": "1024"})
        if longctx is not None:
            longctx["metric"] = "transformer_longctx_t1024_tokens_per_sec"
        log(f"long-context t=1024: {longctx}")

    print(json.dumps({
        "metric": "transformer_base_train_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(mfu / 0.35, 3),
        "value_mean": round(tokens_per_step * steps / mean, 1),
        "mfu_best": round(mfu, 4),
        "mfu_mean": round(mfu_mean, 4),
        "resnet50": resnet,
        "long_context_t1024": longctx,
    }))


if __name__ == "__main__":
    main()
