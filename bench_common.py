"""Shared measurement protocol for the bench_* scripts.

ONE copy of the tunnel-noise methodology (BASELINE.md "Measurement
methodology"): feeds pre-staged on device, 3x30-step windows with a
single host sync per window, best window = headline device-throughput
estimate, mean reported alongside. All bench entrypoints import these so
a protocol change cannot skew one family's numbers against another's.
"""

from __future__ import annotations

import sys
import time

import numpy as np

# THE peak the analytic-MFU rows divide by — defined once, in the
# roofline plane (its TPU backend-peaks entry), re-exported here so
# every bench_* script keeps importing it from bench_common.
from paddle_tpu.roofline import V5E_PEAK_BF16  # noqa: F401


def mfu(flops_per_step: float, steps: int, seconds: float) -> float:
    """Analytic model-FLOPs utilization: the ONE copy of the arithmetic
    every bench row used to hand-roll (bench.py, bench_family.py x2,
    bench_resnet.py) — ``flops_per_step * steps / seconds`` achieved
    FLOP/s over the v5e bf16 peak."""
    return (float(flops_per_step) * steps / seconds) / V5E_PEAK_BF16


def measured_mfu(program, window_seconds: float, steps: int):
    """MEASURED MFU for a bench row, from the roofline plane: builds an
    estimate-source device profile (XLA cost-analysis flops from the
    program's compile report over the measured window seconds) and
    returns its ``measured_mfu`` — None when telemetry is off or no
    compile report carries flops (the row's field is then null, same
    backward-compatible rider contract as ``metrics``)."""
    try:
        from paddle_tpu import monitor, roofline

        if not monitor.enabled():
            return None
        prof = roofline.estimate_profile(
            program, device_seconds=float(window_seconds),
            steps=int(steps))
        v = prof.get("measured_mfu")
        return None if v is None else round(v, 4)
    except Exception as e:
        log(f"measured-MFU profile skipped: {type(e).__name__}: {e}")
        return None


def _is_oom(exc) -> bool:
    from paddle_tpu import monitor

    return monitor.is_oom_error(exc)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def enable_bench_metrics() -> bool:
    """Metrics-only telemetry for bench processes (PT_BENCH_METRICS=0
    opts out): counters/gauges/step records WITHOUT the step_phases
    plane, whose honest device timing would put a block_until_ready
    inside every timed window. Counter mutations are lock-guarded dict
    writes — noise-floor next to a training step.

    Also points ``compile_report_dir`` at a scratch dir so every fresh
    compile records its XLA cost analysis — the flops source for the
    rows' ``measured_mfu`` field. The report's extra AOT compile lands
    at warmup (cache misses), never inside a timed window;
    PT_BENCH_PROFILE=0 opts out of just this half."""
    import os

    if os.environ.get("PT_BENCH_METRICS", "1") != "1":
        return False
    from paddle_tpu import flags

    new = {"telemetry": True, "step_phases": False}
    if (os.environ.get("PT_BENCH_PROFILE", "1") == "1"
            and not flags.get_flag("compile_report_dir")):
        # a user-configured report dir (PT_FLAGS_compile_report_dir)
        # wins — only an UNSET flag gets the self-reaping scratch dir
        import atexit
        import shutil
        import tempfile

        d = tempfile.mkdtemp(prefix="pt_bench_cr_")
        # scratch dir, one per bench process: reap it at exit or a
        # bench.py invocation (~9 fresh subprocesses) leaks 9 of them
        atexit.register(shutil.rmtree, d, ignore_errors=True)
        new["compile_report_dir"] = d
    flags.set_flags(new)
    return True


def attach_metrics(row: dict) -> dict:
    """Snapshot the metrics registry into the BENCH row's ``metrics``
    field so a perf regression is attributable after the fact (cache
    hit/miss mix, feed bytes, retry counts, ...). Backward-compatible
    rider: the field is simply absent when telemetry is off, and a
    snapshot failure never loses the row. Empty instruments are dropped
    to keep rows readable."""
    try:
        from paddle_tpu import monitor

        if monitor.enabled():
            snap = monitor.snapshot()
            row["metrics"] = {name: m for name, m in snap.items()
                              if m["values"]}
    except Exception as e:
        log(f"metrics snapshot skipped: {type(e).__name__}: {e}")
    return row


def run_windows(exe, program, loss, feeds, steps=30, n_windows=3,
                multi=None):
    """Returns (best, mean) window seconds.

    ``multi`` (default on; PT_BENCH_MULTI=0 disables) runs each window
    as ONE compiled multi-step program (Executor.run_steps — the
    RunFromDataset-style hot loop). Measured round 4 (after fixing a
    first-draft bias that re-staged the stacked feeds inside the timed
    window): ResNet-50 +3% (2497 -> 2574 img/s, MFU 0.311 -> 0.321),
    transformer and DeepFM equal to step-wise within noise — the
    compiled loop removes the per-step tunnel dispatch jitter without
    disturbing donation aliasing."""
    if multi is None:
        import os

        multi = os.environ.get("PT_BENCH_MULTI", "1") == "1"
    if multi:
        # Freeze the feed buffers ONCE (owning non-writeable copies) so
        # run_steps' staging cache may legally key on identity —
        # mutable numpy feeds are re-staged every call, which would put
        # the device_put stack back inside the timed window. Owning
        # copies, not views: a frozen view is still mutable through its
        # base, so the executor refuses to cache it.
        frozen = []
        for fd in feeds:
            ffd = {}
            for k, v in fd.items():
                if isinstance(v, np.ndarray):
                    v = v.copy()
                    v.flags.writeable = False
                ffd[k] = v
            frozen.append(ffd)
        feeds = frozen
        # warmup = one full-size window so only ONE multi-step executable
        # is compiled (steps is a static arg). The windowed program +
        # stacked feeds cost more HBM than the single-step program the
        # OOM backoff validated, so an OOM here falls back to the
        # step-wise protocol instead of crashing the bench.
        try:
            exe.run_steps(program, feed_list=feeds, steps=steps,
                          fetch_list=[loss])
        except Exception as e:
            if not _is_oom(e):
                raise
            # Compile-time OOM leaves the donated state untouched, so the
            # step-wise fallback works; an execution-time OOM after state
            # donation drops the consumed params from the scope and the
            # fallback's first run raises "not initialized" — surface
            # that clearly instead of a confusing cascade.
            log("multi-step window OOM; falling back to step-wise windows")
            multi = False
            try:
                exe.run(program, feed=feeds[0], fetch_list=[loss])
            except RuntimeError as e2:
                if "not initialized" in str(e2):
                    raise RuntimeError(
                        "multi-step window OOM consumed the donated "
                        "training state; rerun the startup program or "
                        "set PT_BENCH_MULTI=0"
                    ) from e
                raise
    if multi:
        windows = []
        for w in range(n_windows):
            t0 = time.perf_counter()
            out = exe.run_steps(program, feed_list=feeds, steps=steps,
                                fetch_list=[loss])
            loss_v = float(np.asarray(out[0]))
            elapsed = time.perf_counter() - t0
            log(f"window {w}: {steps} steps in {elapsed:.2f}s, "
                f"loss={loss_v:.3f}")
            windows.append(elapsed)
        return min(windows), sum(windows) / len(windows)
    for fd in feeds[:2]:
        exe.run(program, feed=fd, fetch_list=[loss])
    windows = []
    for w in range(n_windows):
        t0 = time.perf_counter()
        out = None
        for i in range(steps):
            out = exe.run(program, feed=feeds[i % len(feeds)],
                          fetch_list=[loss], return_numpy=False)
        loss_v = float(np.asarray(out[0]))  # sync once per window
        elapsed = time.perf_counter() - t0
        log(f"window {w}: {steps} steps in {elapsed:.2f}s, loss={loss_v:.3f}")
        windows.append(elapsed)
    return min(windows), sum(windows) / len(windows)


class AllBatchesOOM(RuntimeError):
    """Every batch size down to the floor hit device OOM."""


def compile_with_oom_backoff(make_exe, run_first, batch, floor=8):
    """Compile + run the first step, halving ``batch`` on device OOM.
    Returns (executor, batch). Any non-OOM error surfaces — it is a real
    bug, not a perf 0; total exhaustion raises AllBatchesOOM so callers
    can emit their documented perf-0 JSON record."""
    while batch >= floor:
        try:
            exe = make_exe()
            t0 = time.perf_counter()
            run_first(exe, batch)
            log(f"compile+first step: {time.perf_counter() - t0:.1f}s "
                f"(batch={batch})")
            return exe, batch
        except Exception as e:
            if not _is_oom(e):
                raise
            log(f"batch {batch} OOM; halving")
            batch //= 2
    raise AllBatchesOOM("all batch sizes OOM")
