"""Benchmark rider: SE-ResNeXt-50 / BERT-base / DeepFM on one TPU chip.

One family per process (PT_BENCH_FAMILY in {se_resnext, bert, deepfm,
ssd300}):
co-resident compiled programs contaminate each other's HBM/timing, so
bench.py spawns this as a fresh subprocess per family, same as
bench_resnet.py (methodology in BASELINE.md). Prints ONE JSON line.

Configs match the BASELINE.md target table:
- se_resnext: SE-ResNeXt-50 ImageNet-shape b=128 bf16 AMP + momentum
  (reference: benchmark/fluid/models/se_resnext.py); shares ResNet-50's
  >=35% MFU target row, so vs_baseline = MFU / 0.35.
- bert: BERT-base pretraining (MLM+NSP heads), b=64 s=128 bf16 AMP +
  Adam; the baseline row has no committed target, vs_baseline reports
  MFU / 0.35 for comparability with the transformer rows.
- deepfm: CTR-scale DeepFM (26 fields, 1M-row tables, 16-dim factors,
  400x400x400 tower) b=4096 + Adam with DENSE embedding grads. MFU is
  meaningless for a gather-dominated model; the metric is examples/sec
  (the reference's own fluid_benchmark.py unit) and no vs_baseline is
  claimed. Measured round 4 (device traces, /tmp/perf): the XLA dense
  scatter-add dominates at ~10.8 ms for 106k updated rows (~100 ns/row
  serialized RMW — the v5e-without-SparseCore primitive floor; layout
  constraints and lane-packing experiments did not move it), so the
  dense path (13.7 ms/step) runs 3.4x faster than the row-sparse
  sort/unique path (46 ms/step) on one chip. The sparse path remains
  the multi-chip sharded-table capability (parallel/embedding.py);
  PT_BENCH_DEEPFM_SPARSE=1 benches it.
- ssd300: real-scale detection — full VGG16-SSD300 (6 feature maps,
  exactly 8732 priors, 21 classes, 50-row dense-padded gt) b=32 bf16
  AMP + momentum. Metric is images/sec (no committed target; the row
  validates the dense-padded detection design under load — BASELINE.md
  "SSD-300 at realistic scale").
"""

from __future__ import annotations

import json
import os

import numpy as np

from bench_common import (
    AllBatchesOOM,
    attach_metrics,
    compile_with_oom_backoff,
    enable_bench_metrics,
    log,
    measured_mfu,
    mfu,
    run_windows,
)

FAMILY = os.environ.get("PT_BENCH_FAMILY", "se_resnext")


def se_resnext50_fwd_flops_per_image() -> float:
    """Analytic conv+fc FLOPs (2*MACs) for SE-ResNeXt-50 at 224x224,
    computed from the architecture in models/se_resnext.py (grouped 3x3s
    divide MACs by cardinality; SE fc pairs included)."""
    total = 0.0

    def conv(hw, cin, cout, k, stride=1, groups=1):
        nonlocal total
        out_hw = hw // stride
        total += 2.0 * out_hw * out_hw * cout * (cin // groups) * k * k
        return out_hw

    hw = conv(224, 3, 64, 7, 2)            # stem -> 112
    hw //= 2                               # maxpool -> 56
    cin = 64
    for block, (n, filters) in enumerate(
            zip([3, 4, 6, 3], [128, 256, 512, 1024])):
        for i in range(n):
            stride = 2 if i == 0 and block != 0 else 1
            conv(hw, cin, filters, 1)
            new_hw = conv(hw, filters, filters, 3, stride, groups=32)
            conv(new_hw, filters, filters * 2, 1)
            # SE: global pool + 2 fcs (per image, not per pixel)
            total += 2.0 * (filters * 2) * (filters * 2 // 16) * 2
            if not (cin == filters * 2 and stride == 1):
                conv(hw, cin, filters * 2, 1, stride)
            hw = new_hw
            cin = filters * 2
    total += 2.0 * cin * 1000              # fc head
    return total


def bert_train_flops_per_step(cfg, batch, t) -> float:
    """fwd+bwd matmul FLOPs for the BERT-base pretraining step (encoder
    + MLM transform/projection; NSP head negligible)."""
    d, di, L = cfg.d_model, cfg.d_inner, cfg.n_layer
    tok = batch * t
    per_layer = 4 * 2 * tok * d * d + 2 * 2 * tok * d * di \
        + 2 * 2 * tok * t * d
    head = 2 * tok * d * d + 2 * tok * d * cfg.vocab_size
    return 3.0 * (L * per_layer + head)


def main():
    # metrics-only telemetry: the registry snapshot rides every BENCH
    # row's `metrics` field (PT_BENCH_METRICS=0 opts out)
    enable_bench_metrics()
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/pt_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import paddle_tpu as fluid

    log(f"backend: {jax.default_backend()}, devices: {jax.devices()}, "
        f"family: {FAMILY}")
    steps = 30

    if FAMILY == "se_resnext":
        from paddle_tpu.models import se_resnext

        batch = int(os.environ.get("PT_BENCH_BATCH", "128"))
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            model = se_resnext.get_model(data_shape=(3, 224, 224),
                                         class_dim=1000, depth=50)
            fluid.optimizer.Momentum(0.1, momentum=0.9).minimize(
                model["loss"])
        main_prog._amp = True

        def feed(b, s):
            r = np.random.RandomState(s)
            return {"data": r.normal(0, 1, (b, 3, 224, 224)).astype(
                        np.float32),
                    "label": r.randint(0, 1000, (b, 1)).astype(np.int64)}

        def make_exe():
            exe = fluid.Executor()
            exe.run(startup)
            return exe

        try:
            exe, batch = compile_with_oom_backoff(
                make_exe, lambda e, b: e.run(main_prog, feed=feed(b, 0),
                                             fetch_list=[model["loss"]]), batch)
        except AllBatchesOOM:
            print(json.dumps(attach_metrics({"metric": "se_resnext50_train_images_per_sec", "value": 0,
                              "unit": "images/sec", "vs_baseline": 0.0})))
            return
        feeds = [{k: jax.device_put(v) for k, v in feed(batch, s).items()}
                 for s in range(4)]
        best, mean = run_windows(exe, main_prog, model["loss"], feeds, steps)
        ips, ips_mean = batch * steps / best, batch * steps / mean
        train_flops = 3.0 * se_resnext50_fwd_flops_per_image()
        mfu_best = mfu(batch * train_flops, steps, best)
        mfu_mean = mfu(batch * train_flops, steps, mean)
        log(f"images/sec={ips:.1f}, train GFLOP/image="
            f"{train_flops / 1e9:.2f}, MFU={mfu_best:.3f}")
        print(json.dumps(attach_metrics({
            "metric": "se_resnext50_train_images_per_sec",
            "value": round(ips, 1), "unit": "images/sec",
            "vs_baseline": round(mfu_best / 0.35, 3),
            "value_mean": round(ips_mean, 1),
            "mfu_best": round(mfu_best, 4), "mfu_mean": round(mfu_mean, 4),
            "measured_mfu": measured_mfu(main_prog, best, steps),
        })))

    elif FAMILY == "bert":
        from paddle_tpu.models import bert

        batch = int(os.environ.get("PT_BENCH_BATCH", "64"))
        seq = int(os.environ.get("PT_BENCH_SEQ", "128"))
        cfg = bert.base()
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            model = bert.build(cfg)
            fluid.optimizer.Adam(1e-4).minimize(model["loss"])
        main_prog._amp = True

        def make_exe():
            exe = fluid.Executor()
            exe.run(startup)
            return exe

        try:
            exe, batch = compile_with_oom_backoff(
                make_exe,
                lambda e, b: e.run(main_prog,
                                   feed=bert.make_batch(cfg, b, seq, seed=0),
                                   fetch_list=[model["loss"]]), batch)
        except AllBatchesOOM:
            print(json.dumps(attach_metrics({"metric": "bert_base_pretrain_tokens_per_sec",
                              "value": 0, "unit": "tokens/sec",
                              "vs_baseline": 0.0})))
            return
        feeds = [{k: jax.device_put(v)
                  for k, v in bert.make_batch(cfg, batch, seq, seed=s).items()}
                 for s in range(4)]
        best, mean = run_windows(exe, main_prog, model["loss"], feeds, steps)
        tps, tps_mean = (batch * seq * steps / best,
                         batch * seq * steps / mean)
        flops = bert_train_flops_per_step(cfg, batch, seq)
        mfu_best = mfu(flops, steps, best)
        mfu_mean = mfu(flops, steps, mean)
        log(f"tokens/sec={tps:.0f}, analytic TFLOP/step={flops / 1e12:.2f}, "
            f"MFU={mfu_best:.3f}")
        print(json.dumps(attach_metrics({
            "metric": "bert_base_pretrain_tokens_per_sec",
            "value": round(tps, 1), "unit": "tokens/sec",
            "vs_baseline": round(mfu_best / 0.35, 3),
            "value_mean": round(tps_mean, 1),
            "mfu_best": round(mfu_best, 4), "mfu_mean": round(mfu_mean, 4),
            "measured_mfu": measured_mfu(main_prog, best, steps),
        })))

    elif FAMILY == "deepfm":
        from paddle_tpu.models import deepfm

        batch = int(os.environ.get("PT_BENCH_BATCH", "4096"))
        sparse = os.environ.get("PT_BENCH_DEEPFM_SPARSE", "0") == "1"
        cfg = deepfm.DeepFMConfig(num_fields=26, vocab_size=1_000_000,
                                  embed_dim=16, hidden=(400, 400, 400))
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            model = deepfm.build(cfg, is_distributed=False,
                                 is_sparse=sparse)
            fluid.optimizer.Adam(1e-3).minimize(model["loss"])

        def make_exe():
            exe = fluid.Executor()
            exe.run(startup)
            return exe

        try:
            exe, batch = compile_with_oom_backoff(
                make_exe,
                lambda e, b: e.run(main_prog,
                                   feed=deepfm.make_batch(cfg, b, seed=0),
                                   fetch_list=[model["loss"]]), batch,
                floor=256)
        except AllBatchesOOM:
            print(json.dumps(attach_metrics({"metric": "deepfm_train_examples_per_sec",
                              "value": 0, "unit": "examples/sec"})))
            return
        feeds = [{k: jax.device_put(v)
                  for k, v in deepfm.make_batch(cfg, batch, seed=s).items()}
                 for s in range(4)]
        best, mean = run_windows(exe, main_prog, model["loss"], feeds, steps)
        eps, eps_mean = batch * steps / best, batch * steps / mean
        log(f"examples/sec={eps:.0f}")
        print(json.dumps(attach_metrics({
            "metric": "deepfm_train_examples_per_sec",
            "value": round(eps, 1), "unit": "examples/sec",
            "value_mean": round(eps_mean, 1),
        })))

    elif FAMILY == "ssd300":
        from paddle_tpu.models import ssd

        batch = int(os.environ.get("PT_BENCH_BATCH", "32"))
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            model = ssd.get_ssd300_model(num_classes=21, gt_capacity=50)
            fluid.optimizer.Momentum(0.001, momentum=0.9).minimize(
                model["loss"])
        main_prog._amp = True

        def feed(b, s):
            r = np.random.RandomState(s)
            imgs = r.normal(0, 1, (b, 3, 300, 300)).astype(np.float32)
            boxes = np.zeros((b, 50, 4), np.float32)
            labels = np.zeros((b, 50), np.int64)
            for i in range(b):
                n_obj = r.randint(1, 12)
                cx, cy = r.uniform(0.2, 0.8, (2, n_obj))
                w, h = r.uniform(0.1, 0.5, (2, n_obj))
                boxes[i, :n_obj, 0] = np.clip(cx - w / 2, 0, 1)
                boxes[i, :n_obj, 1] = np.clip(cy - h / 2, 0, 1)
                boxes[i, :n_obj, 2] = np.clip(cx + w / 2, 0, 1)
                boxes[i, :n_obj, 3] = np.clip(cy + h / 2, 0, 1)
                labels[i, :n_obj] = r.randint(1, 21, n_obj)
            return {"image": imgs, "gt_box": boxes, "gt_label": labels}

        def make_exe():
            exe = fluid.Executor()
            exe.run(startup)
            return exe

        try:
            exe, batch = compile_with_oom_backoff(
                make_exe, lambda e, b: e.run(main_prog, feed=feed(b, 0),
                                             fetch_list=[model["loss"]]),
                batch)
        except AllBatchesOOM:
            print(json.dumps(attach_metrics({"metric": "ssd300_train_images_per_sec",
                              "value": 0, "unit": "images/sec"})))
            return
        feeds = [{k: jax.device_put(v) for k, v in feed(batch, s).items()}
                 for s in range(4)]
        best, mean = run_windows(exe, main_prog, model["loss"], feeds,
                                 steps)
        ips, ips_mean = batch * steps / best, batch * steps / mean
        log(f"images/sec={ips:.1f}")
        print(json.dumps(attach_metrics({
            "metric": "ssd300_train_images_per_sec",
            "value": round(ips, 1), "unit": "images/sec",
            "value_mean": round(ips_mean, 1),
        })))

    else:
        raise SystemExit(f"unknown PT_BENCH_FAMILY '{FAMILY}'")


if __name__ == "__main__":
    main()
