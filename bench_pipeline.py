"""Benchmark rider: synchronous vs pipelined steady-state step time.

Drives the SAME trainer workload twice through `contrib.Trainer`:

- **sync** — the pre-PR-10 configuration: per-step phase attribution
  (`step_phases_every_n=1`, a `block_until_ready` every step) and
  synchronous `DataFeeder` staging (`prefetch_depth=0`).
- **pipelined** — the async steady-state default: sampled phases
  (`step_phases_every_n=8`), `DeviceLoader` device-feed prefetch
  (batch N+1's `device_put` overlaps batch N's device phase) and
  overlapped fetch (`LazyFetches`).

Steady state is the LAST epoch (epoch 0 pays the compile + warmup).
Prints ONE JSON line in the driver format: ``value`` is the pipelined
steady-state ms/step, ``vs_baseline`` is ``sync / pipelined`` (>1.0 =
the pipeline beats the synchronous path). The pipelined run's final
boundedness verdict mix rides along — acceptance is `input_bound` +
`dispatch_bound` ~zero at steady state — and the full metrics snapshot
lands in the row's ``metrics`` field.

Env knobs: ``PT_BENCH_BATCH`` (default 256), ``PT_BENCH_WIDTH``
(hidden width, default 1024), ``PT_BENCH_PIPE_STEPS`` (steps/epoch,
default 30), ``PT_BENCH_CPU=1`` to force the CPU backend (must be set
in Python before first device use — the hosted-TPU plugin overrides
JAX_PLATFORMS).
"""

from __future__ import annotations

import json
import os
import time

BATCH = int(os.environ.get("PT_BENCH_BATCH", "256"))
WIDTH = int(os.environ.get("PT_BENCH_WIDTH", "1024"))
STEPS = int(os.environ.get("PT_BENCH_PIPE_STEPS", "30"))
EPOCHS = 3


def _configure_platform():
    if os.environ.get("PT_BENCH_CPU", "0") != "1":
        return
    import jax

    jax.config.update("jax_platforms", "cpu")


def run_mode(pipelined: bool):
    """One trainer run; returns (ms/step over the last epoch, verdict
    mix at the end of the run)."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import flags, layers, monitor
    from paddle_tpu.contrib import BeginEpochEvent, EndEpochEvent, Trainer

    monitor.reset()
    flags.set_flags({
        "telemetry": True,
        "step_phases": True,
        "step_phases_every_n": 8 if pipelined else 1,
        "prefetch_depth": 2 if pipelined else 0,
    })

    def train_func():
        x = layers.data("x", shape=[WIDTH], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = x
        for _ in range(4):
            h = layers.fc(h, WIDTH, act="relu")
        logits = layers.fc(h, 16)
        return [layers.mean(
            layers.softmax_with_cross_entropy(logits, label))]

    def reader():
        # a realistic host pipeline: generate + normalize (the synthetic
        # stand-in for decode/augment) per batch. Sync mode pays this
        # serially on the step loop; the pipelined mode overlaps it in
        # the prefetch worker.
        def gen():
            rng = np.random.RandomState(0)
            for _ in range(STEPS):
                x = rng.randn(BATCH, WIDTH)
                x = (x - x.mean(axis=1, keepdims=True)) / (
                    x.std(axis=1, keepdims=True) + 1e-6)
                yield list(zip(
                    x.astype(np.float32),
                    rng.randint(0, 16, BATCH).astype(np.int64)))

        return gen

    marks = []

    def handler(event):
        if isinstance(event, (BeginEpochEvent, EndEpochEvent)):
            marks.append((type(event).__name__, event.epoch,
                          time.perf_counter()))

    trainer = Trainer(train_func, lambda: fluid.optimizer.SGD(0.05),
                      fluid.CPUPlace())
    trainer.train(EPOCHS, handler, reader(), ["x", "label"],
                  log_time_attribution=False)
    last = EPOCHS - 1
    t0 = next(t for k, e, t in marks if k == "BeginEpochEvent"
              and e == last)
    t1 = next(t for k, e, t in marks if k == "EndEpochEvent" and e == last)
    ms_per_step = (t1 - t0) * 1e3 / STEPS
    c = monitor.counter("pt_step_bound_total")
    mix = {v: int(c.value(labels={"verdict": v}))
           for v in monitor.BOUND_VERDICTS}
    return ms_per_step, mix


def main():
    _configure_platform()
    from bench_common import attach_metrics, log

    sync_ms, sync_mix = run_mode(pipelined=False)
    log(f"sync: {sync_ms:.3f} ms/step, verdicts {sync_mix}")
    pipe_ms, pipe_mix = run_mode(pipelined=True)
    log(f"pipelined: {pipe_ms:.3f} ms/step, verdicts {pipe_mix}")
    overhead_verdicts = pipe_mix["input_bound"] + pipe_mix["dispatch_bound"]
    print(json.dumps(attach_metrics({
        "metric": "pipeline_steady_step_ms",
        "value": round(pipe_ms, 3),
        "unit": "ms/step",
        "vs_baseline": round(sync_ms / pipe_ms, 3) if pipe_ms else 0.0,
        "sync_ms_per_step": round(sync_ms, 3),
        "pipelined_ms_per_step": round(pipe_ms, 3),
        "sync_verdicts": sync_mix,
        "pipelined_verdicts": pipe_mix,
        "pipelined_overhead_verdicts": overhead_verdicts,
    })))


if __name__ == "__main__":
    main()
