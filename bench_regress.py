"""Bench-trajectory regression gate.

Compares a fresh bench row (bench.py's driver-format JSON, headline +
nested family rows) against the committed BENCH_r*.json history and
exits nonzero when any family's throughput regressed: a metric fails
when its ``value_mean`` (falling back to ``value``) drops more than the
family tolerance below the TRAILING BEST across the history rounds.

Two metric classes are gated. Higher-is-better throughput metrics —
rows whose ``unit`` contains ``/sec`` (tokens/sec, images/sec,
examples/sec) — fail when they drop below the trailing best. A small
explicit allowlist of lower-is-better latency metrics
(``LATENCY_TOLERANCE``: serving TTFT / queue-wait p95) fail when they
rise above the trailing best (the MINIMUM across history). All other
lower-is-better riders (warm-start seconds, pipeline step times) are
reported informationally but never gate: their CPU-vs-TPU variance is
not a regression signal.

Usage:
    python bench_regress.py                  # newest BENCH_r*.json vs
                                             # the earlier rounds
    python bench_regress.py --row fresh.json # a fresh row vs ALL rounds
    python bench_regress.py --tolerance 0.2  # loosen every family

``--row`` accepts either a bare bench row or the driver wrapper
(``{"parsed": {...}}``). Exit code: 0 = no gated metric regressed,
1 = regression(s) found, 2 = usage/history errors.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

# Per-family tolerance: fraction below the trailing best that still
# passes. 0.10 is the measured round-to-round noise envelope of the
# committed history (worst healthy ratio: deepfm r05/r04 = 0.979);
# widen a family here — not globally — when its methodology says so.
DEFAULT_TOLERANCE = 0.10
FAMILY_TOLERANCE: Dict[str, float] = {
    # the serving decode loop is host-scheduler-paced (one Python tick
    # per emitted token), so its throughput carries more host jitter
    # than the compiled train-step families; first appears in r06 and
    # gates under the union-baseline rules from its first committed
    # round onward
    "serving_decode_tokens_per_sec": 0.15,
    # the degraded-mode serving row (bench_serving.py: the same sweep
    # under a seeded serve.decode delay fault at 1% of steps) measures
    # resilience overhead; the injected delays add sampling noise on
    # top of the host jitter, so it gets the widest envelope
    "serving_degraded_tokens_per_sec": 0.20,
    # the fleet row (bench_serving.py: N routed replicas vs one at the
    # same offered load) layers router scheduling + supervisor loop
    # threads on top of the host-paced decode, so it inherits the
    # degraded row's envelope
    "serving_fleet_tokens_per_sec": 0.20,
}

# Lower-is-better latency families (explicit allowlist — a unit of
# "ms" alone does NOT gate): fraction ABOVE the trailing best (the
# minimum across history) that still passes. The serving latency
# riders are host-timed tail percentiles over a small request sample,
# so they carry far more noise than the throughput means — hence the
# wide 50% envelope; tighten per-family once the committed history
# shows a stable floor.
LATENCY_TOLERANCE: Dict[str, float] = {
    "serving_ttft_ms_p95": 0.50,
    "serving_queue_wait_ms_p95": 0.50,
    "serving_fleet_token_ms_p99": 0.50,
}

# Deliberately dropped families: a gated metric carried by ANY history
# round must reappear in every fresh row (a crashed bench subprocess
# must not pass the gate by producing no number — even if one bad
# round already committed without it); retiring a family is an
# explicit entry here, not a silent disappearance.
RETIRED_METRICS: frozenset = frozenset()


def flatten_row(parsed: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """{metric: {"value", "unit"}} over a driver row: the headline plus
    every nested family/rider row carrying a numeric ``value`` (the
    ``metrics`` registry snapshot is skipped)."""
    out: Dict[str, Dict[str, Any]] = {}

    def visit(row):
        if not isinstance(row, dict):
            return
        name = row.get("metric")
        val = row.get("value_mean", row.get("value"))
        if isinstance(name, str) and isinstance(val, (int, float)):
            out[name] = {"value": float(val),
                         "unit": str(row.get("unit", ""))}
        for k, v in row.items():
            if k != "metrics" and isinstance(v, dict):
                visit(v)

    visit(parsed)
    return out


def _load_round(path: str) -> Optional[Dict[str, Any]]:
    with open(path) as f:
        doc = json.load(f)
    parsed = doc.get("parsed", doc) if isinstance(doc, dict) else None
    if not isinstance(parsed, dict):
        return None
    return flatten_row(parsed)


def load_history(paths: List[str]) -> List[Tuple[str, Dict[str, Any]]]:
    """[(round_name, flat_row)] in path-sorted (round) order, skipping
    rounds whose JSON carries no parseable row (a crashed bench run
    records rc/tail but parsed: null)."""
    hist = []
    for p in sorted(paths):
        try:
            flat = _load_round(p)
        except (OSError, ValueError) as e:
            print(f"bench_regress: skipping unreadable {p}: {e}",
                  file=sys.stderr)
            continue
        if flat:
            hist.append((os.path.basename(p), flat))
    return hist


def gated(unit: str) -> bool:
    """Whether a metric's unit marks it higher-is-better throughput."""
    return "/sec" in unit


def gated_latency(metric: str) -> bool:
    """Whether a metric is on the lower-is-better latency allowlist."""
    return metric in LATENCY_TOLERANCE


def check(fresh: Dict[str, Dict[str, Any]],
          history: List[Tuple[str, Dict[str, Any]]],
          tolerance: float = DEFAULT_TOLERANCE) -> List[Dict[str, Any]]:
    """Regression findings for ``fresh`` against the trailing best of
    ``history``: one record per gated metric whose value fell more than
    the (per-family) tolerance below the best historical value. Metrics
    with no history (a brand-new family) never gate — but a gated
    metric carried by ANY history round and absent from ``fresh`` is
    itself a finding (`missing: true`): a family whose bench
    subprocess crashed outright must not pass the gate by producing no
    number, and one bad committed round must not erode the guarantee
    for every later run. Deliberate removals go in
    ``RETIRED_METRICS``."""
    findings = []
    # latest carrier per gated metric across the whole history
    carriers: Dict[str, Tuple[str, Dict[str, Any]]] = {}
    for rname, flat in history:
        for metric, cell in flat.items():
            if gated(cell.get("unit", "")) or gated_latency(metric):
                carriers[metric] = (rname, cell)
    for metric, (rname, cell) in sorted(carriers.items()):
        if metric not in fresh and metric not in RETIRED_METRICS:
            findings.append({
                "metric": metric,
                "value": None,
                "unit": cell["unit"],
                "best": cell["value"],
                "best_round": rname,
                "ratio": 0.0,
                "tolerance": LATENCY_TOLERANCE.get(
                    metric, FAMILY_TOLERANCE.get(metric, tolerance)),
                "missing": True,
            })
    for metric, cell in sorted(fresh.items()):
        if not gated(cell.get("unit", "")):
            continue
        best = best_round = None
        for rname, flat in history:
            prev = flat.get(metric)
            if prev is None or not gated(prev.get("unit", "")):
                continue
            if best is None or prev["value"] > best:
                best, best_round = prev["value"], rname
        if best is None or best <= 0:
            continue
        tol = FAMILY_TOLERANCE.get(metric, tolerance)
        ratio = cell["value"] / best
        if ratio < 1.0 - tol:
            findings.append({
                "metric": metric,
                "value": cell["value"],
                "unit": cell["unit"],
                "best": best,
                "best_round": best_round,
                "ratio": round(ratio, 4),
                "tolerance": tol,
            })
    # lower-is-better latency allowlist: "best" is the MINIMUM across
    # history; a fresh value more than 1+tol times the best fails
    for metric, cell in sorted(fresh.items()):
        if not gated_latency(metric):
            continue
        best = best_round = None
        for rname, flat in history:
            prev = flat.get(metric)
            if prev is None:
                continue
            if best is None or prev["value"] < best:
                best, best_round = prev["value"], rname
        if best is None or best <= 0:
            continue
        tol = LATENCY_TOLERANCE[metric]
        ratio = cell["value"] / best
        if ratio > 1.0 + tol:
            findings.append({
                "metric": metric,
                "value": cell["value"],
                "unit": cell["unit"],
                "best": best,
                "best_round": best_round,
                "ratio": round(ratio, 4),
                "tolerance": tol,
                "direction": "above",
            })
    return findings


def main(argv=None) -> int:
    here = os.path.dirname(os.path.abspath(__file__))
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--row", default=None,
                    help="fresh bench row JSON (bare row or driver "
                         "{'parsed': ...} wrapper); default: the newest "
                         "history round, gated against the earlier ones")
    ap.add_argument("--history", default=os.path.join(here, "BENCH_r*.json"),
                    help="glob of history rounds (default: the repo's "
                         "BENCH_r*.json)")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="allowed fraction below the trailing best "
                         f"(default {DEFAULT_TOLERANCE})")
    args = ap.parse_args(argv)

    history = load_history(glob.glob(args.history))
    if args.row is not None:
        try:
            fresh = _load_round(args.row)
        except (OSError, ValueError) as e:
            print(f"bench_regress: cannot read --row {args.row}: {e}",
                  file=sys.stderr)
            return 2
        if not fresh:
            print(f"bench_regress: --row {args.row} has no parseable "
                  f"bench row", file=sys.stderr)
            return 2
        fresh_name = os.path.basename(args.row)
    else:
        if len(history) < 2:
            print("bench_regress: need >= 2 history rounds (or --row) "
                  "to gate anything", file=sys.stderr)
            return 2
        fresh_name, fresh = history[-1]
        history = history[:-1]
    if not history:
        print("bench_regress: no history rounds to compare against",
              file=sys.stderr)
        return 2

    findings = check(fresh, history, tolerance=args.tolerance)
    verdict = {
        "row": fresh_name,
        "rounds": [name for name, _ in history],
        "gated_metrics": sorted(m for m, c in fresh.items()
                                if gated(c.get("unit", ""))
                                or gated_latency(m)),
        "regressions": findings,
        "ok": not findings,
    }
    print(json.dumps(verdict, indent=1, sort_keys=True))
    if findings:
        for f in findings:
            if f.get("missing"):
                print(f"REGRESSION {f['metric']}: MISSING from the "
                      f"fresh row (was {f['best']:.1f} {f['unit']} in "
                      f"{f['best_round']}) — did the family's bench "
                      f"subprocess crash?", file=sys.stderr)
            elif f.get("direction") == "above":
                print(f"REGRESSION {f['metric']}: {f['value']:.1f} "
                      f"{f['unit']} is {f['ratio']:.1%} of the "
                      f"trailing best (lowest) {f['best']:.1f} "
                      f"({f['best_round']}; tolerance "
                      f"+{f['tolerance']:.0%})", file=sys.stderr)
            else:
                print(f"REGRESSION {f['metric']}: {f['value']:.1f} "
                      f"{f['unit']} is {f['ratio']:.1%} of the "
                      f"trailing best {f['best']:.1f} "
                      f"({f['best_round']}; tolerance "
                      f"{f['tolerance']:.0%})", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
