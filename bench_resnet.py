"""Benchmark: ResNet-50 ImageNet-shape training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = achieved model FLOPs utilization / 0.35 (BASELINE.md target:
>=35% MFU for ResNet-50 on v5e). Model definition:
paddle_tpu/models/resnet.py (reference: benchmark/fluid/models/resnet.py:171),
synthetic ImageNet input (reference: benchmark/fluid/imagenet_reader.py),
bf16 AMP convs, full train step (fwd + autodiff + momentum) in one XLA
computation.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

V5E_PEAK_BF16 = 197e12  # FLOP/s per v5e chip

BATCH = 128
SHAPE = (3, 224, 224)
CLASSES = 1000


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def resnet50_fwd_flops_per_image() -> float:
    """Analytic conv+fc FLOPs (2*MACs) for ResNet-50 at 224x224 (~4.1e9,
    the standard figure). Computed from the architecture so the number is
    auditable rather than folklore."""
    total = 0.0

    def conv(hw, cin, cout, k, stride=1):
        nonlocal total
        out_hw = hw // stride
        total += 2.0 * out_hw * out_hw * cout * cin * k * k
        return out_hw

    hw = conv(224, 3, 64, 7, 2)     # conv1 -> 112
    hw //= 2                        # maxpool -> 56
    cin = 64
    for filters, blocks, first_stride in (
        (64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2),
    ):
        for b in range(blocks):
            stride = first_stride if b == 0 else 1
            # bottleneck: 1x1 reduce, 3x3, 1x1 expand (+ projection on b==0)
            conv(hw, cin, filters, 1)
            new_hw = conv(hw, filters, filters, 3, stride)
            conv(new_hw, filters, filters * 4, 1)
            if b == 0:
                conv(hw, cin, filters * 4, 1, stride)
            hw = new_hw
            cin = filters * 4
    total += 2.0 * cin * CLASSES    # fc
    return total


def main():
    import jax

    # Persistent XLA compilation cache: repeat runs (same program/shapes)
    # skip the multi-minute TPU compile entirely.
    jax.config.update("jax_compilation_cache_dir", "/tmp/pt_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import paddle_tpu as fluid
    from paddle_tpu.dataset import imagenet
    from paddle_tpu.models import resnet

    log(f"backend: {jax.default_backend()}, devices: {jax.devices()}")

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        model = resnet.get_model(data_shape=SHAPE, class_dim=CLASSES,
                                 depth=50)
        fluid.optimizer.Momentum(0.1, momentum=0.9).minimize(model["loss"])
    main_prog._amp = True  # bf16 convs/matmuls, f32 master weights

    exe = fluid.Executor()
    exe.run(startup)

    batch = BATCH
    while batch >= 8:
        try:
            feed = next(iter(imagenet.batched(batch, 1)()))
            t0 = time.time()
            exe.run(main_prog, feed=feed, fetch_list=[model["loss"]])
            log(f"compile+first step: {time.time() - t0:.1f}s (batch={batch})")
            break
        except Exception as e:
            msg = f"{type(e).__name__}: {e}"
            if "RESOURCE_EXHAUSTED" not in msg and "Out of memory" not in msg:
                raise
            log(f"batch {batch} OOM; halving")
            batch //= 2
            exe = fluid.Executor()
            exe.run(startup)
    else:
        print(json.dumps({"metric": "resnet50_train", "value": 0,
                          "unit": "images/sec", "vs_baseline": 0.0}))
        return

    feeds = [
        {k: jax.device_put(v) for k, v in fd.items()}
        for fd in imagenet.batched(batch, 4, seed=33)()
    ]
    for fd in feeds[:2]:
        exe.run(main_prog, feed=fd, fetch_list=[model["loss"]])
    # 3x 30-step windows; best window is the headline (tunnel noise, see
    # BASELINE.md "Measurement methodology"), mean reported alongside.
    steps = 30
    windows = []
    for w in range(3):
        t0 = time.time()
        loss = None
        for i in range(steps):
            loss = exe.run(main_prog, feed=feeds[i % 4],
                           fetch_list=[model["loss"]], return_numpy=False)
        loss_v = float(np.asarray(loss[0]))  # sync once per window
        elapsed = time.time() - t0
        log(f"window {w}: {steps} steps in {elapsed:.2f}s, loss={loss_v:.3f}")
        windows.append(elapsed)
    best = min(windows)
    mean = sum(windows) / len(windows)

    images_per_sec = batch * steps / best
    images_per_sec_mean = batch * steps / mean
    train_flops = 3.0 * resnet50_fwd_flops_per_image()  # bwd ~= 2x fwd

    def to_mfu(ips):
        return ips * train_flops / V5E_PEAK_BF16

    mfu = to_mfu(images_per_sec)
    log(f"images/sec={images_per_sec:.1f}, "
        f"train GFLOP/image={train_flops / 1e9:.2f}, MFU={mfu:.3f}")

    print(json.dumps({
        "metric": "resnet50_train_images_per_sec",
        "value": round(images_per_sec, 1),
        "unit": "images/sec",
        "vs_baseline": round(mfu / 0.35, 3),
        "value_mean": round(images_per_sec_mean, 1),
        "mfu_best": round(mfu, 4),
        "mfu_mean": round(to_mfu(images_per_sec_mean), 4),
    }))


if __name__ == "__main__":
    main()
