"""Benchmark: ResNet-50 ImageNet-shape training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = achieved model FLOPs utilization / 0.35 (BASELINE.md target:
>=35% MFU for ResNet-50 on v5e). Model definition:
paddle_tpu/models/resnet.py (reference: benchmark/fluid/models/resnet.py:171),
synthetic ImageNet input (reference: benchmark/fluid/imagenet_reader.py),
bf16 AMP convs, full train step (fwd + autodiff + momentum) in one XLA
computation.
"""

from __future__ import annotations

import json

from bench_common import (
    AllBatchesOOM,
    attach_metrics,
    compile_with_oom_backoff,
    enable_bench_metrics,
    log,
    measured_mfu,
    mfu,
    run_windows,
)

BATCH = 128
SHAPE = (3, 224, 224)
CLASSES = 1000


def resnet50_fwd_flops_per_image() -> float:
    """Analytic conv+fc FLOPs (2*MACs) for ResNet-50 at 224x224 (~4.1e9,
    the standard figure). Computed from the architecture so the number is
    auditable rather than folklore."""
    total = 0.0

    def conv(hw, cin, cout, k, stride=1):
        nonlocal total
        out_hw = hw // stride
        total += 2.0 * out_hw * out_hw * cout * cin * k * k
        return out_hw

    hw = conv(224, 3, 64, 7, 2)     # conv1 -> 112
    hw //= 2                        # maxpool -> 56
    cin = 64
    for filters, blocks, first_stride in (
        (64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2),
    ):
        for b in range(blocks):
            stride = first_stride if b == 0 else 1
            # bottleneck: 1x1 reduce, 3x3, 1x1 expand (+ projection on b==0)
            conv(hw, cin, filters, 1)
            new_hw = conv(hw, filters, filters, 3, stride)
            conv(new_hw, filters, filters * 4, 1)
            if b == 0:
                conv(hw, cin, filters * 4, 1, stride)
            hw = new_hw
            cin = filters * 4
    total += 2.0 * cin * CLASSES    # fc
    return total


def main():
    # metrics-only telemetry: the registry snapshot rides every BENCH
    # row's `metrics` field (PT_BENCH_METRICS=0 opts out)
    enable_bench_metrics()
    import jax

    # Persistent XLA compilation cache: repeat runs (same program/shapes)
    # skip the multi-minute TPU compile entirely.
    jax.config.update("jax_compilation_cache_dir", "/tmp/pt_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import paddle_tpu as fluid
    from paddle_tpu.dataset import imagenet
    from paddle_tpu.models import resnet

    log(f"backend: {jax.default_backend()}, devices: {jax.devices()}")

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        model = resnet.get_model(data_shape=SHAPE, class_dim=CLASSES,
                                 depth=50)
        fluid.optimizer.Momentum(0.1, momentum=0.9).minimize(model["loss"])
    main_prog._amp = True  # bf16 convs/matmuls, f32 master weights

    def make_exe():
        e = fluid.Executor()
        e.run(startup)
        return e

    try:
        exe, batch = compile_with_oom_backoff(
            make_exe,
            lambda e, b: e.run(main_prog,
                               feed=next(iter(imagenet.batched(b, 1)())),
                               fetch_list=[model["loss"]]),
            BATCH, floor=8)
    except AllBatchesOOM:
        print(json.dumps(attach_metrics({"metric": "resnet50_train_images_per_sec", "value": 0,
                          "unit": "images/sec", "vs_baseline": 0.0})))
        return

    feeds = [
        {k: jax.device_put(v) for k, v in fd.items()}
        for fd in imagenet.batched(batch, 4, seed=33)()
    ]
    # best-of-3 windows, one sync per window (bench_common.run_windows;
    # tunnel-noise methodology in BASELINE.md)
    steps = 30
    best, mean = run_windows(exe, main_prog, model["loss"], feeds, steps)

    images_per_sec = batch * steps / best
    images_per_sec_mean = batch * steps / mean
    train_flops = 3.0 * resnet50_fwd_flops_per_image()  # bwd ~= 2x fwd

    mfu_best = mfu(batch * train_flops, steps, best)
    log(f"images/sec={images_per_sec:.1f}, "
        f"train GFLOP/image={train_flops / 1e9:.2f}, MFU={mfu_best:.3f}")

    print(json.dumps(attach_metrics({
        "metric": "resnet50_train_images_per_sec",
        "value": round(images_per_sec, 1),
        "unit": "images/sec",
        "vs_baseline": round(mfu_best / 0.35, 3),
        "value_mean": round(images_per_sec_mean, 1),
        "mfu_best": round(mfu_best, 4),
        "mfu_mean": round(mfu(batch * train_flops, steps, mean), 4),
        "measured_mfu": measured_mfu(main_prog, best, steps),
    })))


if __name__ == "__main__":
    main()
