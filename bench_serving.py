"""Benchmark rider: serving-plane throughput + latency under a
concurrency sweep (serving.py ServingEngine — continuous batching over
the on-device KV cache).

For each concurrency level C the harness spins the SAME engine geometry
(``slots`` batch slots), submits 2*C requests with C in flight, and
drives the scheduler loop, timing every decode step on the host: each
emitted token's latency is its step's wall time, so p50/p95/p99
per-token latency and time-to-first-token come from real dispatch->host
measurements, not histogram interpolation.

Prints ONE JSON line in the driver format: ``value`` is tokens/s at
full concurrency, ``vs_baseline`` is the continuous-batching speedup
over solo decode divided by slots/2 (target: batching S slots must beat
solo throughput by at least S/2; >1.0 beats it). The solo row, the full
sweep, and the decode-loop executor-cache accounting (zero fresh
compiles after warmup is the acceptance bar) ride along.

A **degraded-mode** row rides along: the full-concurrency sweep is
re-run under a seeded chaos plan delaying 1% of decode steps by 5x the
healthy p50 (``serve.decode:delay(...)@p0.01``) — tokens/s + p99 under
fault-injection overhead is tracked by bench_regress.py
(``serving_degraded_tokens_per_sec``), so resilience cost is measured,
not guessed.

Every sweep row also carries TTFT and queue-wait p50/p95 (from the
request plane's per-request phase decomposition), and the headline
emits ``serving_ttft_ms_p95`` / ``serving_queue_wait_ms_p95`` as
lower-is-better latency riders that bench_regress.py gates under
``LATENCY_TOLERANCE`` — a latency family carried by history but
missing from a fresh row is itself a finding.

A **fleet** row rides along (fleet_serving.py): a ServingFleet of
``PT_BENCH_SERVE_REPLICAS`` routed replicas vs a fleet of ONE at the
SAME offered load (closed loop at replicas*slots in-flight over
2*replicas*slots requests, refusals retried so every fleet size
completes the identical work and the walls compare sustainable rate) —
aggregate tokens/s (``serving_fleet_tokens_per_sec``, gated by
bench_regress.py at the degraded-row envelope), per-token p99
(``serving_fleet_token_ms_p99``, a lower-is-better latency rider),
``shed`` = bounded-queue refusal events before retry (the backpressure
signal; ``shed_rate`` = refusals per offered request, can exceed 1),
and ``vs_single`` — the fleet's tokens/s over the single replica's at
the same offered load: the measured multiple of single-replica
sustainable throughput the fleet absorbs. The fleet section
arms a temporary persistent compile cache so replicas 2..N spin up
through the disk-tier warm start (the autoscaler's path) instead of
recompiling.

CPU-measured caveat: on one shared host every replica's loop thread
dispatches through the same cores and interpreter lock, so
``vs_single`` < 1 is EXPECTED here — the throughput multiple is a
device-parallel signal and must be re-measured on TPU hardware where
each replica owns its devices. The CPU-valid absorption signal is the
refusal comparison: the N-replica fleet takes the offered load with
``shed == 0`` while the fleet of one spins on backpressure
(``single.shed`` large) for the SAME load.

Env knobs: ``PT_BENCH_CPU=1`` forces the CPU backend;
``PT_BENCH_SERVE_SIZE=tiny|base`` picks the model (tiny for CPU smokes);
``PT_BENCH_SERVE_SLOTS`` (default 8), ``PT_BENCH_SERVE_SRC`` source
length (default 32), ``PT_BENCH_SERVE_NEW`` max new tokens per request
(default 24); ``PT_BENCH_SERVE_DEGRADED=0`` skips the degraded row;
``PT_BENCH_SERVE_REPLICAS`` (default 3) sizes the fleet row and
``PT_BENCH_SERVE_FLEET=0`` skips it.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

SLOTS = int(os.environ.get("PT_BENCH_SERVE_SLOTS", "8"))
SRC_LEN = int(os.environ.get("PT_BENCH_SERVE_SRC", "32"))
MAX_NEW = int(os.environ.get("PT_BENCH_SERVE_NEW", "24"))
SIZE = os.environ.get("PT_BENCH_SERVE_SIZE", "base")
REPLICAS = int(os.environ.get("PT_BENCH_SERVE_REPLICAS", "3"))


def log(msg):
    print(f"[bench_serving] {msg}", file=sys.stderr, flush=True)


def _configure_platform():
    if os.environ.get("PT_BENCH_CPU", "0") != "1":
        return
    import jax

    jax.config.update("jax_platforms", "cpu")


def _cfg():
    from paddle_tpu.models import transformer as T

    if SIZE == "tiny":
        return T.TransformerConfig(
            src_vocab_size=512, trg_vocab_size=512,
            max_length=max(64, SRC_LEN + MAX_NEW + 2),
            d_model=64, d_inner=128, n_head=4, n_layer=2,
            dropout=0.0, label_smooth_eps=0.0)
    return T.TransformerConfig(
        src_vocab_size=10000, trg_vocab_size=10000,
        max_length=max(256, SRC_LEN + MAX_NEW + 2),
        d_model=512, d_inner=2048, n_head=8, n_layer=6,
        dropout=0.0, label_smooth_eps=0.0)


def _sweep_level(cfg, scope, concurrency, n_requests, monitor):
    """Drive one concurrency level; returns the measured row."""
    from paddle_tpu import serving

    eng = serving.ServingEngine(cfg, scope, slots=SLOTS, src_len=SRC_LEN,
                                max_len=SRC_LEN + MAX_NEW + 1,
                                queue_depth=max(64, n_requests))
    rng = np.random.RandomState(17)
    srcs = [rng.randint(2, cfg.src_vocab_size, (SRC_LEN,)).astype(np.int64)
            for _ in range(n_requests)]
    # warmup: compile prefill + decode before the timed window
    w = eng.submit(srcs[0], max_new_tokens=2)
    eng.run_until_idle()
    assert w.done
    misses0 = monitor.counter("pt_executor_cache_misses_total").value()

    inflight = []
    pending = list(srcs)
    token_lat = []
    ttft = []
    t0 = time.perf_counter()
    tokens = 0
    while pending or eng.busy():
        while pending and len([r for r in inflight if not r.done]) \
                < concurrency:
            inflight.append(eng.submit(pending.pop(0),
                                       max_new_tokens=MAX_NEW))
        ts = time.perf_counter()
        emitted = eng.step()
        dt = time.perf_counter() - ts
        tokens += emitted
        token_lat.extend([dt] * emitted)
    wall = time.perf_counter() - t0
    fresh = monitor.counter(
        "pt_executor_cache_misses_total").value() - misses0
    ttft = [r.ttft_s for r in inflight if r.ttft_s is not None]
    qwait = [r.queue_wait_s for r in inflight if r.queue_wait_s is not None]
    done = sum(1 for r in inflight if r.outcome in ("completed", "length"))
    eng.close()
    lat = np.asarray(token_lat) if token_lat else np.asarray([0.0])

    def _pct(xs, q):
        return round(float(np.percentile(xs, q)) * 1e3, 3) if xs else None

    return {
        "concurrency": concurrency,
        "requests": done,
        "tokens": tokens,
        "tokens_per_sec": round(tokens / wall, 2) if wall else 0.0,
        "token_ms_p50": round(float(np.percentile(lat, 50)) * 1e3, 3),
        "token_ms_p95": round(float(np.percentile(lat, 95)) * 1e3, 3),
        "token_ms_p99": round(float(np.percentile(lat, 99)) * 1e3, 3),
        "ttft_ms_p50": _pct(ttft, 50),
        "ttft_ms_p95": _pct(ttft, 95),
        "queue_wait_ms_p50": _pct(qwait, 50),
        "queue_wait_ms_p95": _pct(qwait, 95),
        "fresh_compiles_after_warmup": int(fresh),
    }


def _fleet_level(cfg, scope, replicas, concurrency, n_requests):
    """Drive one fleet size at a fixed offered load (closed loop with
    ``concurrency`` requests in flight); returns the measured row.

    The engines run on their own supervisor loop threads, so per-token
    latency here is each request's accumulated device decode wall
    divided by its token count (the request plane's phase attribution),
    not a host-stepped dispatch wall like the single-engine sweep."""
    from paddle_tpu import fleet_serving

    fleet = fleet_serving.ServingFleet(
        cfg, scope, replicas=replicas, slots=SLOTS, src_len=SRC_LEN,
        max_len=SRC_LEN + MAX_NEW + 1, queue_depth=SLOTS)
    rng = np.random.RandomState(23)
    srcs = [rng.randint(2, cfg.src_vocab_size, (SRC_LEN,)).astype(np.int64)
            for _ in range(n_requests)]
    try:
        # warmup: one request per replica compiles (or disk-loads) every
        # replica's prefill + decode in parallel before the timed window
        warm = [fleet.submit(srcs[i % len(srcs)], max_new_tokens=2)
                for i in range(replicas)]
        for w in warm:
            w.result(timeout=1200)

        inflight = []
        pending = list(srcs)
        shed = 0
        t0 = time.perf_counter()
        while pending or any(not fr.done for fr in inflight):
            while (pending
                   and sum(1 for fr in inflight if not fr.done)
                   < concurrency):
                src = pending.pop(0)
                try:
                    inflight.append(fleet.submit(src,
                                                 max_new_tokens=MAX_NEW))
                except Exception:
                    # bounded queues refused: offered > sustainable.
                    # Count the backpressure event and retry next tick
                    # (closed loop with retry — every fleet size serves
                    # the SAME completed load, so the walls are the
                    # sustainable-throughput comparison)
                    shed += 1
                    pending.insert(0, src)
                    break
            time.sleep(0.001)
        wall = time.perf_counter() - t0
        tokens = 0
        token_lat = []
        for fr in inflight:
            n = len(fr.tokens)
            tokens += n
            if n and fr._sr.decode_s > 0.0:
                token_lat.extend([fr._sr.decode_s / n] * n)
        done = sum(1 for fr in inflight
                   if fr.outcome in ("completed", "length"))
        stats = fleet.stats()
    finally:
        fleet.close()
    lat = np.asarray(token_lat) if token_lat else np.asarray([0.0])
    offered = len(srcs)
    return {
        "replicas": replicas,
        "offered_requests": offered,
        "offered_concurrency": concurrency,
        "requests": done,
        "tokens": tokens,
        "tokens_per_sec": round(tokens / wall, 2) if wall else 0.0,
        "token_ms_p50": round(float(np.percentile(lat, 50)) * 1e3, 3),
        "token_ms_p99": round(float(np.percentile(lat, 99)) * 1e3, 3),
        # refusal EVENTS off the bounded queues (retried, so the load
        # still completes); the rate is refusals per offered request
        # and exceeds 1 when a fleet size has to retry-spin hard
        "shed": shed,
        "shed_rate": round(shed / offered, 3),
        "failovers": stats["failovers"],
    }


def main():
    _configure_platform()
    import jax

    import paddle_tpu as fluid
    from paddle_tpu import flags, monitor
    from paddle_tpu.models import transformer as T

    flags.set_flags({"telemetry": True})
    log(f"backend: {jax.default_backend()}, size={SIZE}, slots={SLOTS}, "
        f"src={SRC_LEN}, new={MAX_NEW}")
    cfg = _cfg()
    scope = fluid.Scope()
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        T.build(cfg, is_test=True)
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)

    levels = sorted({1, max(2, SLOTS // 2), SLOTS})
    sweep = {}
    for c in levels:
        row = _sweep_level(cfg, scope, c, max(2 * c, c + 1), monitor)
        sweep[f"c{c}"] = row
        log(f"concurrency {c}: {row}")
    solo = sweep[f"c{levels[0]}"]
    full = sweep[f"c{SLOTS}"]
    speedup = (full["tokens_per_sec"] / solo["tokens_per_sec"]
               if solo["tokens_per_sec"] else 0.0)

    # degraded mode: the same full-concurrency level under a seeded
    # chaos plan delaying 1% of decode steps by 5x the healthy p50 —
    # the resilience-overhead row bench_regress gates
    degraded = None
    if os.environ.get("PT_BENCH_SERVE_DEGRADED", "1") == "1":
        from paddle_tpu import faults

        delay_s = round(max(0.002, full["token_ms_p50"] / 1e3 * 5.0), 4)
        faults.arm(f"serve.decode:delay({delay_s})@p0.01", seed=1234)
        try:
            row = _sweep_level(cfg, scope, SLOTS, 2 * SLOTS, monitor)
        finally:
            faults.disarm()
        log(f"degraded (delay {delay_s}s @ 1% of decode steps): {row}")
        degraded = {
            "metric": "serving_degraded_tokens_per_sec",
            "value": row["tokens_per_sec"],
            "unit": "tokens/sec",
            "token_ms_p99": row["token_ms_p99"],
            "delay_s": delay_s,
            "fault_rate": 0.01,
            "vs_healthy": (round(row["tokens_per_sec"]
                                 / full["tokens_per_sec"], 3)
                           if full["tokens_per_sec"] else 0.0),
        }
    # fleet row: N routed replicas vs ONE at the same offered load,
    # behind a temporary persistent compile cache so replicas 2..N (and
    # the fleet-of-one rerun) warm-start from disk instead of paying N
    # fresh XLA compiles
    fleet_row = None
    if os.environ.get("PT_BENCH_SERVE_FLEET", "1") == "1" and REPLICAS > 1:
        import shutil
        import tempfile

        conc = REPLICAS * SLOTS
        n_req = 2 * conc
        cc_dir = tempfile.mkdtemp(prefix="pt_bench_fleet_cc_")
        old_cc = flags.get_flag("compile_cache_dir")
        flags.set_flags({"compile_cache_dir": cc_dir})
        try:
            multi = _fleet_level(cfg, scope, REPLICAS, conc, n_req)
            log(f"fleet x{REPLICAS}: {multi}")
            single = _fleet_level(cfg, scope, 1, conc, n_req)
            log(f"fleet x1 (same offered load): {single}")
        finally:
            flags.set_flags({"compile_cache_dir": old_cc})
            shutil.rmtree(cc_dir, ignore_errors=True)
        fleet_row = {
            "metric": "serving_fleet_tokens_per_sec",
            "value": multi["tokens_per_sec"],
            "unit": "tokens/sec",
            **{k: multi[k] for k in (
                "replicas", "offered_requests", "offered_concurrency",
                "requests", "token_ms_p50", "token_ms_p99", "shed",
                "shed_rate", "failovers")},
            # both fleet sizes complete the SAME offered load (refusals
            # retried), so the tokens/s ratio is the measured multiple
            # of single-replica sustainable throughput the fleet
            # absorbs — meaningful on device-parallel hardware; on a
            # shared CPU host the replicas contend for the same cores,
            # vs_single < 1 is expected, and the absorption evidence is
            # shed == 0 here vs single["shed"] backpressure spins
            "vs_single": (round(multi["tokens_per_sec"]
                                / single["tokens_per_sec"], 3)
                          if single["tokens_per_sec"] else 0.0),
            "single": {k: v for k, v in single.items()},
        }

    print(json.dumps({
        "metric": "serving_decode_tokens_per_sec",
        "value": full["tokens_per_sec"],
        "unit": "tokens/sec",
        # target: batching SLOTS slots beats solo decode by >= SLOTS/2
        "vs_baseline": round(speedup / (SLOTS / 2.0), 3),
        "slots": SLOTS,
        "src_len": SRC_LEN,
        "max_new_tokens": MAX_NEW,
        "model": SIZE,
        "batching_speedup": round(speedup, 3),
        "solo_tokens_per_sec": solo["tokens_per_sec"],
        "token_ms_p50": full["token_ms_p50"],
        "token_ms_p95": full["token_ms_p95"],
        "token_ms_p99": full["token_ms_p99"],
        "ttft_ms_p50": full["ttft_ms_p50"],
        "ttft_ms_p95": full["ttft_ms_p95"],
        "queue_wait_ms_p50": full["queue_wait_ms_p50"],
        "queue_wait_ms_p95": full["queue_wait_ms_p95"],
        "fresh_compiles_after_warmup": full["fresh_compiles_after_warmup"],
        # lower-is-better latency riders bench_regress gates under
        # LATENCY_TOLERANCE (full-concurrency level; omitted when the
        # level produced no samples so missing-row detection can fire)
        "latency": {
            name: {"metric": name, "value": val, "unit": "ms",
                   "concurrency": SLOTS}
            for name, val in (
                ("serving_ttft_ms_p95", full["ttft_ms_p95"]),
                ("serving_queue_wait_ms_p95", full["queue_wait_ms_p95"]),
                ("serving_fleet_token_ms_p99",
                 fleet_row["token_ms_p99"] if fleet_row else None),
            ) if val is not None
        },
        "degraded": degraded,
        "fleet": fleet_row,
        "sweep": sweep,
    }))


if __name__ == "__main__":
    main()
