"""Benchmark rider: serving-plane throughput + latency under a
concurrency sweep (serving.py ServingEngine — continuous batching over
the on-device KV cache).

For each concurrency level C the harness spins the SAME engine geometry
(``slots`` batch slots), submits 2*C requests with C in flight, and
drives the scheduler loop, timing every decode step on the host: each
emitted token's latency is its step's wall time, so p50/p95/p99
per-token latency and time-to-first-token come from real dispatch->host
measurements, not histogram interpolation.

Prints ONE JSON line in the driver format: ``value`` is tokens/s at
full concurrency, ``vs_baseline`` is the continuous-batching speedup
over solo decode divided by slots/2 (target: batching S slots must beat
solo throughput by at least S/2; >1.0 beats it). The solo row, the full
sweep, and the decode-loop executor-cache accounting (zero fresh
compiles after warmup is the acceptance bar) ride along.

A **degraded-mode** row rides along: the full-concurrency sweep is
re-run under a seeded chaos plan delaying 1% of decode steps by 5x the
healthy p50 (``serve.decode:delay(...)@p0.01``) — tokens/s + p99 under
fault-injection overhead is tracked by bench_regress.py
(``serving_degraded_tokens_per_sec``), so resilience cost is measured,
not guessed.

Every sweep row also carries TTFT and queue-wait p50/p95 (from the
request plane's per-request phase decomposition), and the headline
emits ``serving_ttft_ms_p95`` / ``serving_queue_wait_ms_p95`` as
lower-is-better latency riders that bench_regress.py gates under
``LATENCY_TOLERANCE`` — a latency family carried by history but
missing from a fresh row is itself a finding.

Env knobs: ``PT_BENCH_CPU=1`` forces the CPU backend;
``PT_BENCH_SERVE_SIZE=tiny|base`` picks the model (tiny for CPU smokes);
``PT_BENCH_SERVE_SLOTS`` (default 8), ``PT_BENCH_SERVE_SRC`` source
length (default 32), ``PT_BENCH_SERVE_NEW`` max new tokens per request
(default 24); ``PT_BENCH_SERVE_DEGRADED=0`` skips the degraded row.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

SLOTS = int(os.environ.get("PT_BENCH_SERVE_SLOTS", "8"))
SRC_LEN = int(os.environ.get("PT_BENCH_SERVE_SRC", "32"))
MAX_NEW = int(os.environ.get("PT_BENCH_SERVE_NEW", "24"))
SIZE = os.environ.get("PT_BENCH_SERVE_SIZE", "base")


def log(msg):
    print(f"[bench_serving] {msg}", file=sys.stderr, flush=True)


def _configure_platform():
    if os.environ.get("PT_BENCH_CPU", "0") != "1":
        return
    import jax

    jax.config.update("jax_platforms", "cpu")


def _cfg():
    from paddle_tpu.models import transformer as T

    if SIZE == "tiny":
        return T.TransformerConfig(
            src_vocab_size=512, trg_vocab_size=512,
            max_length=max(64, SRC_LEN + MAX_NEW + 2),
            d_model=64, d_inner=128, n_head=4, n_layer=2,
            dropout=0.0, label_smooth_eps=0.0)
    return T.TransformerConfig(
        src_vocab_size=10000, trg_vocab_size=10000,
        max_length=max(256, SRC_LEN + MAX_NEW + 2),
        d_model=512, d_inner=2048, n_head=8, n_layer=6,
        dropout=0.0, label_smooth_eps=0.0)


def _sweep_level(cfg, scope, concurrency, n_requests, monitor):
    """Drive one concurrency level; returns the measured row."""
    from paddle_tpu import serving

    eng = serving.ServingEngine(cfg, scope, slots=SLOTS, src_len=SRC_LEN,
                                max_len=SRC_LEN + MAX_NEW + 1,
                                queue_depth=max(64, n_requests))
    rng = np.random.RandomState(17)
    srcs = [rng.randint(2, cfg.src_vocab_size, (SRC_LEN,)).astype(np.int64)
            for _ in range(n_requests)]
    # warmup: compile prefill + decode before the timed window
    w = eng.submit(srcs[0], max_new_tokens=2)
    eng.run_until_idle()
    assert w.done
    misses0 = monitor.counter("pt_executor_cache_misses_total").value()

    inflight = []
    pending = list(srcs)
    token_lat = []
    ttft = []
    t0 = time.perf_counter()
    tokens = 0
    while pending or eng.busy():
        while pending and len([r for r in inflight if not r.done]) \
                < concurrency:
            inflight.append(eng.submit(pending.pop(0),
                                       max_new_tokens=MAX_NEW))
        ts = time.perf_counter()
        emitted = eng.step()
        dt = time.perf_counter() - ts
        tokens += emitted
        token_lat.extend([dt] * emitted)
    wall = time.perf_counter() - t0
    fresh = monitor.counter(
        "pt_executor_cache_misses_total").value() - misses0
    ttft = [r.ttft_s for r in inflight if r.ttft_s is not None]
    qwait = [r.queue_wait_s for r in inflight if r.queue_wait_s is not None]
    done = sum(1 for r in inflight if r.outcome in ("completed", "length"))
    eng.close()
    lat = np.asarray(token_lat) if token_lat else np.asarray([0.0])

    def _pct(xs, q):
        return round(float(np.percentile(xs, q)) * 1e3, 3) if xs else None

    return {
        "concurrency": concurrency,
        "requests": done,
        "tokens": tokens,
        "tokens_per_sec": round(tokens / wall, 2) if wall else 0.0,
        "token_ms_p50": round(float(np.percentile(lat, 50)) * 1e3, 3),
        "token_ms_p95": round(float(np.percentile(lat, 95)) * 1e3, 3),
        "token_ms_p99": round(float(np.percentile(lat, 99)) * 1e3, 3),
        "ttft_ms_p50": _pct(ttft, 50),
        "ttft_ms_p95": _pct(ttft, 95),
        "queue_wait_ms_p50": _pct(qwait, 50),
        "queue_wait_ms_p95": _pct(qwait, 95),
        "fresh_compiles_after_warmup": int(fresh),
    }


def main():
    _configure_platform()
    import jax

    import paddle_tpu as fluid
    from paddle_tpu import flags, monitor
    from paddle_tpu.models import transformer as T

    flags.set_flags({"telemetry": True})
    log(f"backend: {jax.default_backend()}, size={SIZE}, slots={SLOTS}, "
        f"src={SRC_LEN}, new={MAX_NEW}")
    cfg = _cfg()
    scope = fluid.Scope()
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        T.build(cfg, is_test=True)
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)

    levels = sorted({1, max(2, SLOTS // 2), SLOTS})
    sweep = {}
    for c in levels:
        row = _sweep_level(cfg, scope, c, max(2 * c, c + 1), monitor)
        sweep[f"c{c}"] = row
        log(f"concurrency {c}: {row}")
    solo = sweep[f"c{levels[0]}"]
    full = sweep[f"c{SLOTS}"]
    speedup = (full["tokens_per_sec"] / solo["tokens_per_sec"]
               if solo["tokens_per_sec"] else 0.0)

    # degraded mode: the same full-concurrency level under a seeded
    # chaos plan delaying 1% of decode steps by 5x the healthy p50 —
    # the resilience-overhead row bench_regress gates
    degraded = None
    if os.environ.get("PT_BENCH_SERVE_DEGRADED", "1") == "1":
        from paddle_tpu import faults

        delay_s = round(max(0.002, full["token_ms_p50"] / 1e3 * 5.0), 4)
        faults.arm(f"serve.decode:delay({delay_s})@p0.01", seed=1234)
        try:
            row = _sweep_level(cfg, scope, SLOTS, 2 * SLOTS, monitor)
        finally:
            faults.disarm()
        log(f"degraded (delay {delay_s}s @ 1% of decode steps): {row}")
        degraded = {
            "metric": "serving_degraded_tokens_per_sec",
            "value": row["tokens_per_sec"],
            "unit": "tokens/sec",
            "token_ms_p99": row["token_ms_p99"],
            "delay_s": delay_s,
            "fault_rate": 0.01,
            "vs_healthy": (round(row["tokens_per_sec"]
                                 / full["tokens_per_sec"], 3)
                           if full["tokens_per_sec"] else 0.0),
        }
    print(json.dumps({
        "metric": "serving_decode_tokens_per_sec",
        "value": full["tokens_per_sec"],
        "unit": "tokens/sec",
        # target: batching SLOTS slots beats solo decode by >= SLOTS/2
        "vs_baseline": round(speedup / (SLOTS / 2.0), 3),
        "slots": SLOTS,
        "src_len": SRC_LEN,
        "max_new_tokens": MAX_NEW,
        "model": SIZE,
        "batching_speedup": round(speedup, 3),
        "solo_tokens_per_sec": solo["tokens_per_sec"],
        "token_ms_p50": full["token_ms_p50"],
        "token_ms_p95": full["token_ms_p95"],
        "token_ms_p99": full["token_ms_p99"],
        "ttft_ms_p50": full["ttft_ms_p50"],
        "ttft_ms_p95": full["ttft_ms_p95"],
        "queue_wait_ms_p50": full["queue_wait_ms_p50"],
        "queue_wait_ms_p95": full["queue_wait_ms_p95"],
        "fresh_compiles_after_warmup": full["fresh_compiles_after_warmup"],
        # lower-is-better latency riders bench_regress gates under
        # LATENCY_TOLERANCE (full-concurrency level; omitted when the
        # level produced no samples so missing-row detection can fire)
        "latency": {
            name: {"metric": name, "value": val, "unit": "ms",
                   "concurrency": SLOTS}
            for name, val in (
                ("serving_ttft_ms_p95", full["ttft_ms_p95"]),
                ("serving_queue_wait_ms_p95", full["queue_wait_ms_p95"]),
            ) if val is not None
        },
        "degraded": degraded,
        "sweep": sweep,
    }))


if __name__ == "__main__":
    main()
