"""Benchmark rider: cold vs warm start through the persistent compile
cache (compile_cache.py).

Launches the SAME child twice against one fresh ``compile_cache_dir``:
the first (cold) child traces + XLA-compiles the bench transformer and
publishes serialized executables; the second (warm) child is a fresh
process that must resolve every executor entry from disk — zero fresh
XLA compiles — and reach its first executed train step in a fraction of
the cold time.

Prints ONE JSON line in the driver format: ``value`` is the warm
compile+first-step wall seconds, ``vs_baseline`` is
``(0.10 * cold) / warm`` against the acceptance target "warm start
<= 10% of cold" (>1.0 beats the target). The cold seconds, the warm
child's hit/miss counters and its per-entry cache outcomes ride along
so the driver can verify the zero-fresh-compiles claim, not just the
wall time.

Env knobs: ``PT_BENCH_BATCH``/``PT_BENCH_SEQ`` (bench.py's transformer
shape), ``PT_BENCH_CPU=1`` to force the CPU backend (fast smoke — the
hosted-TPU plugin overrides JAX_PLATFORMS, so this must be set in
Python before first device use).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

BATCH = int(os.environ.get("PT_BENCH_BATCH", "64"))
SEQ = int(os.environ.get("PT_BENCH_SEQ", "256"))
VOCAB = 10000


def _configure_platform():
    if os.environ.get("PT_BENCH_CPU", "0") != "1":
        return
    import jax

    jax.config.update("jax_platforms", "cpu")


def child(cache_dir: str):
    """One fresh process: build the bench transformer, run startup + one
    train step with the persistent cache at ``cache_dir``, print the
    compile+first-step wall seconds and the cache accounting."""
    _configure_platform()
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import compile_cache, flags, monitor
    from paddle_tpu.models import transformer as T

    flags.set_flags({"telemetry": True, "compile_cache_dir": cache_dir})
    cfg = T.TransformerConfig(
        src_vocab_size=VOCAB,
        trg_vocab_size=VOCAB,
        max_length=SEQ + 2,
        d_model=512,
        d_inner=2048,
        n_head=8,
        n_layer=6,
        dropout=0.1,
    )
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        model = T.build(cfg)
        fluid.optimizer.Adam(1e-4).minimize(model["loss"])
    main_prog._amp = True
    batch = T.make_batch(cfg, BATCH, SEQ, SEQ, seed=0)
    t0 = time.perf_counter()
    exe = fluid.Executor()
    exe.run(startup)
    out = exe.run(main_prog, feed=batch, fetch_list=[model["loss"]])
    loss = float(np.asarray(out[0]))  # forces the step to materialize
    dt = time.perf_counter() - t0
    print(json.dumps({
        "compile_first_step_s": dt,
        "loss": loss,
        "stats": compile_cache.stats(),
        "outcomes": [r["cache"] for r in monitor.recent_steps()],
    }))


def _launch(cache_dir: str) -> dict:
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", cache_dir],
        capture_output=True, text=True, timeout=1800,
        env={**os.environ, "PT_BENCH_WARMSTART": "0"})
    if out.returncode != 0:
        raise RuntimeError(
            f"warm-start child rc={out.returncode}, "
            f"stderr tail: {out.stderr[-1000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def main():
    cache_dir = tempfile.mkdtemp(prefix="pt_warmstart_cc_")
    cold = _launch(cache_dir)
    warm = _launch(cache_dir)
    cold_s, warm_s = cold["compile_first_step_s"], warm["compile_first_step_s"]
    print(json.dumps({
        "metric": "transformer_warm_start_compile_first_step_seconds",
        "value": round(warm_s, 3),
        "unit": "s",
        # target: warm <= 10% of cold; >1.0 beats it
        "vs_baseline": round((0.10 * cold_s) / warm_s, 3) if warm_s else 0.0,
        "cold_s": round(cold_s, 3),
        "warm_hits": warm["stats"]["hits"],
        "warm_misses": warm["stats"]["misses"],
        "warm_errors": warm["stats"]["errors"],
        "warm_outcomes": warm["outcomes"],
    }))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child(sys.argv[2])
    else:
        main()
