"""Step-time ablations for the Transformer bench config (manual TPU tool)."""

import sys
import time

import numpy as np


def run_config(label, dropout, vocab=10000, batch=32, seq=256, amp=True,
               is_test=False, use_pallas=True, steps=10):
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as T

    cfg = T.TransformerConfig(
        src_vocab_size=vocab, trg_vocab_size=vocab, max_length=seq + 2,
        d_model=512, d_inner=2048, n_head=8, n_layer=6, dropout=dropout,
    )
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        model = T.build(cfg, is_test=is_test)
        if not use_pallas:
            # must happen BEFORE minimize(): grad ops copy the forward
            # attrs at append_backward time
            for block in main_prog.blocks:
                for op in block.ops:
                    if op.type == "scaled_dot_product_attention":
                        op.attrs["use_pallas"] = False
        if not is_test:
            fluid.optimizer.Adam(1e-4).minimize(model["loss"])
    main_prog._amp = amp
    exe = fluid.Executor()
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    feeds = [
        {k: jax.device_put(v) for k, v in
         T.make_batch(cfg, batch, seq, seq, seed=s).items()}
        for s in range(2)
    ]
    t0 = time.perf_counter()
    exe.run(main_prog, feed=feeds[0], fetch_list=[model["loss"]], scope=scope)
    compile_s = time.perf_counter() - t0
    for f in feeds:
        exe.run(main_prog, feed=f, fetch_list=[model["loss"]], scope=scope)
    t0 = time.perf_counter()
    out = None
    for i in range(steps):
        out = exe.run(main_prog, feed=feeds[i % 2], fetch_list=[model["loss"]],
                      scope=scope, return_numpy=False)
    _ = float(np.asarray(out[0]))
    dt = (time.perf_counter() - t0) / steps
    print(f"{label:40s} step={dt*1000:7.1f}ms  compile={compile_s:6.1f}s",
          flush=True)
    return dt


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "base"):
        run_config("train base (drop 0.1, pallas)", 0.1)
    if which in ("all", "nodrop"):
        run_config("train no-dropout", 0.0)
    if which in ("all", "dense"):
        run_config("train dense attn", 0.1, use_pallas=False)
    if which in ("all", "fwd"):
        run_config("forward only (is_test)", 0.0, is_test=True)
    if which in ("all", "vocab"):
        run_config("train small vocab 1k", 0.1, vocab=1000)
    if which in ("all", "noamp"):
        run_config("train f32 (no AMP)", 0.1, amp=False)
    if which in ("all", "b64"):
        run_config("train batch 64", 0.1, batch=64)
