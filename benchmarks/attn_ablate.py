"""Transformer 0.45-MFU ceiling ablation (round 4, VERDICT item 3).

Measures the flash-attention FORWARD kernel's softmax/VPU cost against
its MXU floor at the transformer-base shape (b=64, h=8, t=256, dh=64),
isolating each claimed contributor:

  matmul-floor   score + pv matmuls only (no softmax) — the MXU floor
                 at dh=64 (50% K/N fill on the two contractions)
  full           production math: row-max, exp, correction, l-sum
  no-rowmax      exp(s) without the running max (unsafe numerically;
                 measures the max+correction VPU cost)
  bf16-exp       softmax arithmetic in bf16 (measures whether the VPU
                 runs 16-bit exp/max faster on this chip)
  dh128          h=4, dh=128, same d_model: fills the MXU contraction
                 (measures the head-shape fill penalty; note
                 transformer-base is DEFINED as h=8/dh=64, so this is a
                 bound probe, not a config change)

Run on the chip: python benchmarks/attn_ablate.py
Results are read from device traces (the hosted tunnel elides repeated
same-input dispatches, so wall-clock microtiming is invalid —
benchmarks/resnet_roofline.md §5).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float(np.finfo(np.float32).min)


def make_fwd(variant: str, b, h, t, dh, bq, bk):
    nk = t // bk
    scale = 1.0 / np.sqrt(dh)

    def kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr):
        kk = pl.program_id(1)

        @pl.when(kk == 0)
        def _init():
            m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
            l_scr[:] = jnp.zeros_like(l_scr)
            acc_scr[:] = jnp.zeros_like(acc_scr)

        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale
        if variant == "matmul-floor":
            acc_scr[:] += jax.lax.dot_general(
                s.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
        elif variant == "no-rowmax":
            p = jnp.exp(s)
            l_scr[:] += jnp.broadcast_to(
                jnp.sum(p, axis=-1, keepdims=True), l_scr.shape)
            acc_scr[:] += jax.lax.dot_general(
                p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
        elif variant == "bf16-exp":
            m_prev = m_scr[:, :, :1]
            l_prev = l_scr[:, :, :1]
            m_new = jnp.maximum(m_prev,
                                jnp.max(s, axis=-1, keepdims=True))
            sb = (s - m_new).astype(jnp.bfloat16)
            p = jnp.exp(sb)
            corr = jnp.exp((m_prev - m_new).astype(jnp.bfloat16))
            l_new = l_prev * corr.astype(jnp.float32) + jnp.sum(
                p.astype(jnp.float32), axis=-1, keepdims=True)
            acc_scr[:] = acc_scr[:] * corr.astype(jnp.float32) + \
                jax.lax.dot_general(
                    p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)
            m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
            l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        else:  # full
            m_prev = m_scr[:, :, :1]
            l_prev = l_scr[:, :, :1]
            m_new = jnp.maximum(m_prev,
                                jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m_prev - m_new)
            l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
            acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
                p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
            l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

        @pl.when(kk == nk - 1)
        def _finish():
            if variant in ("full", "bf16-exp"):
                o_ref[0] = (acc_scr[:] / l_scr[:, :, :1]).astype(o_ref.dtype)
            elif variant == "no-rowmax":
                o_ref[0] = (acc_scr[:] /
                            jnp.maximum(l_scr[:, :, :1], 1e-9)).astype(
                                o_ref.dtype)
            else:
                o_ref[0] = acc_scr[:].astype(o_ref.dtype)

    def fwd(q, k, v):
        return pl.pallas_call(
            kernel,
            grid=(b, nk),
            in_specs=[
                pl.BlockSpec((1, h, bq, dh), lambda i, kk: (i, 0, 0, 0)),
                pl.BlockSpec((1, h, bk, dh), lambda i, kk: (i, 0, kk, 0)),
                pl.BlockSpec((1, h, bk, dh), lambda i, kk: (i, 0, kk, 0)),
            ],
            out_specs=pl.BlockSpec((1, h, bq, dh),
                                   lambda i, kk: (i, 0, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((b, h, t, dh), jnp.bfloat16),
            scratch_shapes=[
                pltpu.VMEM((h, bq, 128), jnp.float32),
                pltpu.VMEM((h, bq, 128), jnp.float32),
                pltpu.VMEM((h, bq, dh), jnp.float32),
            ],
        )(q, k, v)

    return jax.jit(fwd)


def trace_us(tag, fn, *args, iters=20):
    import glob
    import gzip
    import json

    o = fn(*args)
    jax.block_until_ready(o)
    with jax.profiler.trace(f"/tmp/perf/attn_{tag}"):
        for _ in range(iters):
            o = fn(*args)
        jax.block_until_ready(o)
    fs = sorted(glob.glob(f"/tmp/perf/attn_{tag}/**/*.trace.json.gz",
                          recursive=True))
    ev = json.load(gzip.open(fs[-1]))["traceEvents"]
    tot = sum(e.get("dur", 0) for e in ev
              if e.get("ph") == "X" and e.get("pid") == 3
              and e.get("tid") == 3)
    return tot / iters


def main():
    r = np.random.RandomState(0)
    b, t, d = 64, 256, 512
    results = {}
    for name, (h, dh) in [("h8dh64", (8, 64)), ("h4dh128", (4, 128))]:
        q = jnp.asarray(r.randn(b, h, t, dh) * 0.1, jnp.bfloat16)
        k = jnp.asarray(r.randn(b, h, t, dh) * 0.1, jnp.bfloat16)
        v = jnp.asarray(r.randn(b, h, t, dh) * 0.1, jnp.bfloat16)
        variants = (["matmul-floor", "full", "no-rowmax", "bf16-exp"]
                    if h == 8 else ["matmul-floor", "full"])
        for variant in variants:
            fn = make_fwd(variant, b, h, t, dh, 256, 256)
            us = trace_us(f"{name}_{variant}", fn, q, k, v)
            results[f"{name}/{variant}"] = us
            print(f"{name:8s} {variant:14s}: {us:7.1f} us/call")
    # sanity: full vs reference
    return results


if __name__ == "__main__":
    main()
