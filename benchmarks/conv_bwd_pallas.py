"""Prove-or-kill record: combined conv-backward Pallas kernel (round 4).

VERDICT r3 item 1 proposed closing ResNet-50's MFU gap (0.311 vs the
0.35 gate) with a "conv+BN-reduction Pallas mega-kernel" that fuses BN's
backward reductions into the conv wgrad/dgrad operand reads. Round-4
evidence (this file is the committed record; run it on the chip to
reproduce):

1. **The hypothesized fusion already exists.** The optimized HLO of the
   framework's ResNet-50 train step (dump via
   ``fn.lower(...).compile().as_text()``; analysis notes in
   benchmarks/resnet_roofline.md) shows XLA emitting multi-output
   fusions that contain the convolution AND the BN-backward channel
   reductions AND the relu-mask select in one kernel
   (``convert_reduce_fusion.*``: 1x1 conv + add + 2x reduce -> f32[C]),
   plus wgrad convolutions with the momentum update fused
   (``copy_subtract_fusion.*``) and forward convs with the one-pass
   E[x], E[x^2] stat reductions fused. There is no unfused BN traffic
   left for a mega-kernel to remove.

2. **The one structural trick XLA cannot do — dx and dW from a single
   pass over (x, dy) — is implemented below** (`combined_conv1x1_bwd`:
   one grid, dgrad tile matmul + wgrad scratch accumulation, bit-exact
   vs XLA, saves one full read of dy). Trace-timed on the hosted chip
   at the three ResNet-50 1x1 backward shapes it is SLOWER than XLA's
   two separate dot kernels despite moving ~40% fewer HBM bytes:

       [401408 x  64 ->  256]: pallas 851 us   xla pair 636 us
       [100352 x 128 ->  512]: pallas 265 us   xla pair 146 us
       [ 25088 x 256 -> 1024]: pallas 157 us   xla pair 143 us

   The XLA dot pair achieves ~1.75 TB/s *effective* operand bandwidth
   (trace ``bytes_accessed``/duration) — above the v5e HBM spec — i.e.
   the compiler's dots exploit an on-chip residency (S(1) memory-space
   buffers in the HLO) that Mosaic kernels do not get, so cutting HBM
   bytes does not cut time on this part. Wall-clock microbenchmarks are
   not usable as a cross-check here: the hosted tunnel elides repeated
   identical dispatches (measured 3 us/call for a 154 MB-minimum
   kernel), so trace timings above are the instrument.

3. **Conclusion (kill, with evidence):** ResNet-50 at 0.311 MFU is the
   measured ceiling of the XLA schedule on this chip: the pure-JAX
   model measures the same (r3), every BN/momentum side computation
   already rides a conv kernel, achieved bandwidth in the step trace is
   ~93% of nominal peak, and the recoverable wall-device gap was host
   dispatch jitter, now captured by the whole-window compiled loop
   (Executor.run_steps: ResNet 0.311 -> 0.321 MFU, BERT 0.488 ->
   0.506; bench_common.run_windows notes).
   Batch-stat BN makes the backward irreducibly multi-phase (global
   reductions before every apply), so no single-kernel restructuring
   removes passes XLA hasn't already removed.

Reference capability bar: benchmark/fluid/models/resnet.py:171 (the
model) and BASELINE.md >=0.35 target (unmet at 0.92x; all other driver
gates exceed 1.0).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def combined_conv1x1_bwd(x, dy, w, tn: int = 512):
    """dx = dy @ W^T and dW = x^T @ dy in ONE pass over (x, dy).

    x [n, ci] bf16, dy [n, co] bf16, w [ci, co] -> (dx [n, ci] bf16,
    dW [ci, co] f32). Grid over n tiles; dW accumulates in a VMEM
    scratch across the sequential TPU grid and is written by the last
    program. Bit-exact vs the XLA dot pair (validated on-chip)."""
    n, ci = x.shape
    _, co = dy.shape
    assert n % tn == 0
    nt = n // tn

    def kernel(x_ref, dy_ref, w_ref, dx_ref, dw_ref, acc):
        i = pl.program_id(0)
        xx = x_ref[...]
        dyy = dy_ref[...]
        dx = jax.lax.dot_general(
            dyy, w_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dx_ref[...] = dx.astype(x_ref.dtype)
        part = jax.lax.dot_general(
            xx, dyy, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

        @pl.when(i == 0)
        def _init():
            acc[...] = part

        @pl.when(i > 0)
        def _accum():
            acc[...] += part

        @pl.when(i == nt - 1)
        def _emit():
            dw_ref[...] = acc[...]

    return pl.pallas_call(
        kernel,
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((tn, ci), lambda i: (i, 0)),
            pl.BlockSpec((tn, co), lambda i: (i, 0)),
            pl.BlockSpec((ci, co), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tn, ci), lambda i: (i, 0)),
            pl.BlockSpec((ci, co), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, ci), x.dtype),
            jax.ShapeDtypeStruct((ci, co), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((ci, co), jnp.float32)],
    )(x, dy, w)


@jax.jit
def xla_pair(x, dy, w):
    """The two-kernel XLA baseline the combined kernel races."""
    dx = jax.lax.dot_general(
        dy, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(x.dtype)
    dw = jax.lax.dot_general(
        x, dy, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return dx, dw


def _trace_us(tag, fn, *args, iters=10):
    import glob
    import gzip
    import json

    out = fn(*args)
    jax.block_until_ready(out)
    with jax.profiler.trace(f"/tmp/perf/convbwd_{tag}"):
        o = None
        for _ in range(iters):
            o = fn(*args)
        jax.block_until_ready(o)
    fs = sorted(glob.glob(f"/tmp/perf/convbwd_{tag}/**/*.trace.json.gz",
                          recursive=True))
    ev = json.load(gzip.open(fs[-1]))["traceEvents"]
    tot = sum(e.get("dur", 0) for e in ev
              if e.get("ph") == "X" and e.get("pid") == 3
              and e.get("tid") == 3)
    return tot / iters


def main():
    import numpy as np

    r = np.random.RandomState(0)
    pallas_jit = jax.jit(functools.partial(combined_conv1x1_bwd))
    for (n, ci, co) in [(128 * 56 * 56, 64, 256),
                        (128 * 28 * 28, 128, 512),
                        (128 * 14 * 14, 256, 1024)]:
        x = jnp.asarray(r.randn(n, ci), jnp.bfloat16)
        dy = jnp.asarray(r.randn(n, co), jnp.bfloat16)
        w = jnp.asarray(r.randn(ci, co), jnp.bfloat16)
        dxp, dwp = pallas_jit(x, dy, w)
        dxx, dwx = xla_pair(x, dy, w)
        assert float(jnp.max(jnp.abs(
            dxp.astype(jnp.float32) - dxx.astype(jnp.float32)))) == 0.0
        assert float(jnp.max(jnp.abs(dwp - dwx))) < 1e-3 * float(
            jnp.max(jnp.abs(dwx)))
        tp = _trace_us(f"pal_{ci}", pallas_jit, x, dy, w)
        tx = _trace_us(f"xla_{ci}", xla_pair, x, dy, w)
        print(f"n={n} ci={ci} co={co}: pallas {tp:.0f} us, "
              f"xla pair {tx:.0f} us")


if __name__ == "__main__":
    main()
