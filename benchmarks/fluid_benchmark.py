"""Model-zoo benchmark harness printing examples/sec.

The equivalent of the reference's benchmark driver
(reference: benchmark/fluid/fluid_benchmark.py:296-300 prints
``examples/sec`` for mnist / resnet / vgg / stacked_dynamic_lstm /
machine_translation), redesigned for this framework: every model runs as
one whole-program XLA computation; ``--parallel`` runs GSPMD data
parallelism over the visible devices (the reference's
``CompiledProgram.with_data_parallel`` path).

    python benchmarks/fluid_benchmark.py --model mnist --batch_size 128
    python benchmarks/fluid_benchmark.py --model resnet --iterations 30
    python benchmarks/fluid_benchmark.py --model machine_translation \
        --parallel

Models: mnist, resnet, se_resnext, vgg, stacked_dynamic_lstm (IMDB
sentiment), machine_translation (LSTM NMT seq2seq), transformer, bert,
deepfm.
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _synth(shape, dtype="float32", lo=0, hi=None, seed=0):
    r = np.random.RandomState(seed)
    if dtype == "int64":
        return r.randint(lo, hi, shape).astype(np.int64)
    return r.normal(0, 1, shape).astype(np.float32)


def build_model(name, args):
    """-> (feed_fn(step) -> dict, loss_var, examples_per_batch)"""
    import paddle_tpu as fluid

    b = args.batch_size
    if name == "mnist":
        from paddle_tpu.models import mnist

        model = mnist.get_model(batch_size=b)
        feeds = lambda s: {"pixel": _synth((b, 784), seed=s),
                           "label": _synth((b, 1), "int64", 0, 10, s)}
        return feeds, model["loss"], b
    if name in ("resnet", "vgg", "se_resnext"):
        from paddle_tpu.models import resnet, se_resnext, vgg

        mod = {"resnet": resnet, "vgg": vgg, "se_resnext": se_resnext}[name]
        model = mod.get_model(data_shape=(3, 224, 224), class_dim=1000)
        feeds = lambda s: {"data": _synth((b, 3, 224, 224), seed=s),
                           "label": _synth((b, 1), "int64", 0, 1000, s)}
        return feeds, model["loss"], b
    if name in ("stacked_dynamic_lstm", "stacked_lstm"):
        from paddle_tpu.models import stacked_lstm

        cfg = stacked_lstm.StackedLSTMConfig(max_len=args.seq_len)
        model = stacked_lstm.build(cfg)
        feeds = lambda s: stacked_lstm.make_batch(cfg, b, seed=s)
        return feeds, model["loss"], b
    if name == "machine_translation":
        from paddle_tpu.models import seq2seq

        cfg = seq2seq.Seq2SeqConfig()
        model = seq2seq.build(cfg)
        feeds = lambda s: seq2seq.make_batch(cfg, b, args.seq_len,
                                             args.seq_len, seed=s)
        return feeds, model["loss"], b
    if name == "transformer":
        from paddle_tpu.models import transformer as T

        cfg = T.TransformerConfig(src_vocab_size=10000, trg_vocab_size=10000,
                                  max_length=args.seq_len + 2)
        model = T.build(cfg)
        feeds = lambda s: T.make_batch(cfg, b, args.seq_len, args.seq_len,
                                       seed=s)
        return feeds, model["loss"], b
    if name == "bert":
        from paddle_tpu.models import bert

        cfg = bert.BertConfig()
        model = bert.build(cfg)
        feeds = lambda s: bert.make_batch(cfg, b, args.seq_len, seed=s)
        return feeds, model["loss"], b
    if name == "deepfm":
        from paddle_tpu.models import deepfm

        cfg = deepfm.DeepFMConfig()
        model = deepfm.build(cfg)
        feeds = lambda s: deepfm.make_batch(cfg, b, seed=s)
        return feeds, model["loss"], b
    raise SystemExit(f"unknown model '{name}'")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="mnist")
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--iterations", type=int, default=30)
    p.add_argument("--skip_batch_num", type=int, default=5)
    p.add_argument("--seq_len", type=int, default=64)
    p.add_argument("--learning_rate", type=float, default=1e-3)
    p.add_argument("--parallel", action="store_true",
                   help="GSPMD data parallelism over visible devices")
    p.add_argument("--device", default=None, choices=[None, "cpu", "tpu"],
                   help="cpu forces the virtual host backend")
    p.add_argument("--amp", action="store_true", help="bf16 AMP")
    args = p.parse_args()

    import jax

    if args.device == "cpu":
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)
    jax.config.update("jax_compilation_cache_dir", "/tmp/pt_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import paddle_tpu as fluid

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        feed_fn, loss, examples = build_model(args.model, args)
        fluid.optimizer.Adam(args.learning_rate).minimize(loss)
    if args.amp:
        main_prog._amp = True

    exe = fluid.Executor()
    exe.run(startup)
    program = main_prog
    if args.parallel:
        program = fluid.CompiledProgram(main_prog).with_data_parallel(
            loss_name=loss.name)

    feeds = [{k: jax.device_put(v) for k, v in feed_fn(s).items()}
             for s in range(4)]
    t_compile = time.perf_counter()
    exe.run(program, feed=feeds[0], fetch_list=[loss])
    print(f"compile+first step: {time.perf_counter() - t_compile:.1f}s",
          file=sys.stderr)

    for i in range(args.skip_batch_num):
        exe.run(program, feed=feeds[i % 4], fetch_list=[loss])
    t0 = time.perf_counter()
    out = None
    for i in range(args.iterations):
        out = exe.run(program, feed=feeds[i % 4], fetch_list=[loss],
                      return_numpy=False)
    final_loss = float(np.asarray(out[0]))
    elapsed = time.perf_counter() - t0
    eps = examples * args.iterations / elapsed
    print(f"model={args.model} batch={args.batch_size} "
          f"iters={args.iterations} loss={final_loss:.4f}")
    print(f"{eps:.2f} examples/sec, {elapsed / args.iterations * 1000:.2f} "
          f"ms/step")


if __name__ == "__main__":
    main()
