"""SE-ResNeXt-50 grouped-conv + SE-block microbenchmark (round 5,
VERDICT item 1b/1d).

Isolates the two structures BASELINE.md blames for SE-ResNeXt's 0.202
MFU (vs ResNet-50's 0.321 at near-identical analytic FLOPs) and times
each against explicit rooflines on the real chip:

  grouped      production path: lax.conv feature_group_count=32 (what
               ops/nn_ops.py _conv2d emits), fwd and fwd+bwd
  dense        SAME channel counts, groups=1 — 32x the useful FLOPs.
               If XLA internally rewrites grouped->block-diag-dense,
               grouped ~= dense in time; if grouped >> dense the TPU
               conv emitter handles small channels/group WORSE than a
               dense conv, and a Pallas block-diag kernel has headroom.
  patches_dot  im2col patches + dot_general batched over g=32
               ([M, 9*cg] x [9*cg, cg] per group) — the "keep only
               useful FLOPs on the MXU" formulation; measures the
               batched-small-matmul fill penalty directly.
  se_chain     global-pool -> fc(C/16) -> relu -> fc(C) -> sigmoid ->
               broadcast-mul, per stage output shape — the SE gate's
               serialization + traffic cost against its 3-pass HBM
               floor.

Rooflines per shape: HBM floor = (bytes in + bytes out)/819 GB/s;
MXU-fill bound = useful FLOPs / (197e12 * min(K,128)/128 *
min(N,128)/128) for the per-group contraction [M,K=9cg]x[K,cg];
dense-FLOPs bound = physical block-diag FLOPs / 197e12.

Timing methodology: each variant is chained through a lax.fori_loop
(carry = activation, weights scaled for variance preservation) so every
iteration has different inputs — the hosted tunnel elides repeated
same-input dispatches, so unchained wall-timing is invalid
(benchmarks/resnet_roofline.md §5). Device time is read from the
profiler trace and divided by the trip count.

Run: python benchmarks/grouped_conv_bench.py
"""

from __future__ import annotations

import glob
import gzip
import json

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

HBM_GBS = 819e9
PEAK = 197e12
ITERS = 12

# (tag, N, H, W, C, cg): the four SE-ResNeXt-50 grouped-3x3 stage shapes
# at bench batch 128 (models/se_resnext.py filters_list, cardinality 32).
SHAPES = [
    ("s0", 128, 56, 56, 128, 4),
    ("s1", 128, 28, 28, 256, 8),
    ("s2", 128, 14, 14, 512, 16),
    ("s3", 128, 7, 7, 1024, 32),
]
G = 32


def trace_s(tag, fn, *args):
    """Total device-stream seconds for ONE traced call of fn."""
    o = fn(*args)
    jax.block_until_ready(o)
    d = f"/tmp/perf/gc_{tag}"
    with jax.profiler.trace(d):
        o = fn(*args)
        jax.block_until_ready(o)
    fs = sorted(glob.glob(f"{d}/**/*.trace.json.gz", recursive=True))
    ev = json.load(gzip.open(fs[-1]))["traceEvents"]
    tot = sum(e.get("dur", 0) for e in ev
              if e.get("ph") == "X" and e.get("pid") == 3
              and e.get("tid") == 3)
    return tot * 1e-6


def chain(body):
    @jax.jit
    def run(x):
        return lax.fori_loop(0, ITERS, lambda i, x: body(x), x)
    return run


def conv(x, w, groups):
    return lax.conv_general_dilated(
        x, w, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups)


def fwd_bwd(f, x, *ws):
    """fwd + dgrad + wgrad, dw kept live via a scalar graft onto dx."""
    y, vjp = jax.vjp(f, x, *ws)
    grads = vjp(y)
    dx = grads[0]
    for dw in grads[1:]:
        dx = dx + jnp.mean(dw).astype(dx.dtype)
    return dx * 0.5


def patches_dot(x, w, cg):
    """[N,H,W,C] -> patches [N,H,W,9,g,cg] -> per-group dot.
    w: [g, 9*cg, cg]."""
    n, h, ww, c = x.shape
    p = lax.conv_general_dilated_patches(
        x, (3, 3), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    # patches feature order is [c, kh, kw] flattened -> [C, 9]
    p = p.reshape(n * h * ww, c, 9).reshape(n * h * ww, G, cg, 9)
    p = p.transpose(1, 0, 2, 3).reshape(G, n * h * ww, cg * 9)
    y = lax.dot_general(p, w, (((2,), (1,)), ((0,), (0,))),
                        preferred_element_type=jnp.float32)
    y = y.astype(x.dtype).transpose(1, 0, 2).reshape(n, h, ww, c)
    return y


def se_chain(x, w1, b1, w2, b2):
    n, h, ww, c = x.shape
    pool = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    s = jax.nn.relu(pool @ w1 + b1)
    e = jax.nn.sigmoid(s @ w2 + b2)
    return (x * e[:, None, None, :].astype(x.dtype))


def report(tag, t, useful_gflop, bytes_mb, fill_bound_s, note=""):
    tfs = useful_gflop / t / 1e3 if t > 0 else 0
    hbm_floor = bytes_mb * 1e6 / HBM_GBS
    print(f"  {tag:16s}: {t*1e6:9.1f} us  useful {tfs:7.2f} TF/s  "
          f"hbm-floor {hbm_floor*1e6:7.1f} us  "
          f"fill-bound {fill_bound_s*1e6:7.1f} us {note}")


def main():
    r = np.random.RandomState(0)
    total = {"grouped": 0.0, "dense": 0.0, "se": 0.0}
    # block counts per stage in SE-ResNeXt-50
    blocks = {"s0": 3, "s1": 4, "s2": 6, "s3": 3}
    for tag, n, h, w_, c, cg in SHAPES:
        m = n * h * w_
        useful = 2.0 * m * 9 * cg * c / 1e9          # GFLOP
        dense_fl = 2.0 * m * 9 * c * c / 1e9
        io_mb = 2 * (m * c * 2) / 1e6                # x read + y write, bf16
        k, nn_ = 9 * cg, cg
        fill = (min(k, 128) / 128.0) * (min(nn_, 128) / 128.0)
        fill_bound = useful * 1e9 / (PEAK * fill)
        print(f"{tag}: [{n},{h},{w_},{c}] cg={cg}  useful {useful:.1f} "
              f"GFLOP  dense {dense_fl:.1f} GFLOP  io {io_mb:.0f} MB")

        x = jnp.asarray(r.randn(n, h, w_, c) * 0.5, jnp.bfloat16)
        wg = jnp.asarray(r.randn(3, 3, cg, c) / np.sqrt(9 * cg),
                         jnp.bfloat16)
        wd = jnp.asarray(r.randn(3, 3, c, c) / np.sqrt(9 * c),
                         jnp.bfloat16)
        wp = jnp.asarray(r.randn(G, 9 * cg, cg) / np.sqrt(9 * cg),
                         jnp.bfloat16)

        t = trace_s(f"{tag}_grouped", chain(lambda x: conv(x, wg, G)), x)
        report("grouped fwd", t / ITERS, useful, io_mb, fill_bound)
        total["grouped"] += t / ITERS * blocks[tag]

        t = trace_s(f"{tag}_gbwd",
                    chain(lambda x: fwd_bwd(
                        lambda x, w: conv(x, w, G), x, wg)), x)
        report("grouped f+b", t / ITERS, 3 * useful, 3 * io_mb,
               3 * fill_bound)

        t = trace_s(f"{tag}_dense", chain(lambda x: conv(x, wd, 1)), x)
        report("dense fwd", t / ITERS, dense_fl, io_mb,
               dense_fl * 1e9 / PEAK, "(32x FLOPs)")
        total["dense"] += t / ITERS * blocks[tag]

        t = trace_s(f"{tag}_pdot",
                    chain(lambda x: patches_dot(x, wp, cg)), x)
        report("patches_dot", t / ITERS, useful, io_mb, fill_bound)

        # SE chain on the block OUTPUT shape (2*filters channels)
        c2 = 2 * c
        xe = jnp.asarray(r.randn(n, h, w_, c2) * 0.5, jnp.bfloat16)
        w1 = jnp.asarray(r.randn(c2, c2 // 16) * 0.05, jnp.float32)
        b1 = jnp.zeros((c2 // 16,), jnp.float32)
        w2 = jnp.asarray(r.randn(c2 // 16, c2) * 0.05, jnp.float32)
        b2 = jnp.zeros((c2,), jnp.float32)
        se_mb = 3 * (m * c2 * 2) / 1e6   # pool read + mul read + write
        t = trace_s(f"{tag}_se",
                    chain(lambda x: se_chain(x, w1, b1, w2, b2)), xe)
        report("se_chain fwd", t / ITERS, 0.0, se_mb,
               se_mb * 1e6 / HBM_GBS)
        total["se"] += t / ITERS * blocks[tag]

    print("\nper-step fwd totals over 16 blocks:")
    for k, v in total.items():
        print(f"  {k:8s}: {v*1e3:.2f} ms")


if __name__ == "__main__":
    main()
