"""Pallas grouped-conv attempt (round 5, VERDICT item 1c).

Strategy: the MXU cannot contract per-group [M, 9*cg] x [9*cg, cg]
without idling (36/128 K-fill, 4/128 N-fill at cg=4), and ANY matmul
formulation that packs 32 groups' outputs into the 128-lane dim is
forced block-diagonal (LHS K-lanes are shared across output lanes), so
the minimum MXU work for a 128-channel chunk is 9 dense [M,128]x[128,128]
passes — identical FLOPs to a dense conv, but with weights resident in
VMEM and the im2col halo shifts done on-chip. That bound is 601 us fwd
at s0 vs XLA's measured grouped-conv 957 us (grouped_conv_bench.py), so
the best possible Pallas win on the worst stage is ~1.6x fwd.

Kernel: grid (N, C/128); per step the padded input slab
[1, H+2, W+2, 128] sits in VMEM, weights [9, 128, 128] (block-diagonal,
built host-side) sit in VMEM, and 9 tap-shifted dot_generals accumulate
the [H, W, 128] output in fp32.

dgrad of a stride-1 same-pad conv is the same kernel with
spatially-flipped, IO-transposed block-diag weights, so a fwd win would
carry to the backward at equal cost; wgrad stays on XLA.

Run: python benchmarks/grouped_conv_pallas.py
"""

from __future__ import annotations

import functools
import glob
import gzip
import json

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ITERS = 12


def _kernel(x_ref, w_ref, o_ref, acc, *, h, w):
    acc[:] = jnp.zeros_like(acc)
    for t in range(9):
        dy, dx = t // 3, t % 3
        xs = x_ref[0, dy:dy + h, dx:dx + w, :]
        acc[:] += lax.dot_general(
            xs, w_ref[0, t], (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    o_ref[0] = acc[:].astype(o_ref.dtype)


def grouped_conv_pallas(x, wbd):
    """x: [N, H, W, C] bf16 (unpadded); wbd: [C//128, 9, 128, 128]
    block-diagonal per 128-channel chunk. Stride 1, SAME padding."""
    n, h, w, c = x.shape
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    kern = functools.partial(_kernel, h=h, w=w)
    return pl.pallas_call(
        kern,
        grid=(n, c // 128),
        in_specs=[
            pl.BlockSpec((1, h + 2, w + 2, 128),
                         lambda i, cc: (i, 0, 0, cc)),
            pl.BlockSpec((1, 9, 128, 128), lambda i, cc: (cc, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, w, 128), lambda i, cc: (i, 0, 0, cc)),
        out_shape=jax.ShapeDtypeStruct((n, h, w, c), x.dtype),
        scratch_shapes=[pltpu.VMEM((h, w, 128), jnp.float32)],
    )(xp, wbd)


def make_blockdiag(wg, c, cg):
    """[3, 3, cg, C] HWIO grouped -> [C//128, 9, 128, 128] block-diag."""
    g = c // cg
    out = np.zeros((c // 128, 9, 128, 128), np.float32)
    wg = np.asarray(wg, np.float32).reshape(9, cg, c)
    for gi in range(g):
        chunk = (gi * cg) // 128
        base = gi * cg - chunk * 128
        blk = wg[:, :, gi * cg:(gi + 1) * cg]  # [9, cg_in, cg_out]
        out[chunk, :, base:base + cg, base:base + cg] = blk
    return jnp.asarray(out, jnp.bfloat16)


def conv_ref(x, wg, groups):
    return lax.conv_general_dilated(
        x, wg, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups)


def trace_s(tag, fn, *args):
    o = fn(*args)
    jax.block_until_ready(o)
    d = f"/tmp/perf/gp_{tag}"
    with jax.profiler.trace(d):
        o = fn(*args)
        jax.block_until_ready(o)
    fs = sorted(glob.glob(f"{d}/**/*.trace.json.gz", recursive=True))
    ev = json.load(gzip.open(fs[-1]))["traceEvents"]
    return sum(e.get("dur", 0) for e in ev
               if e.get("ph") == "X" and e.get("pid") == 3
               and e.get("tid") == 3) * 1e-6


def chain(body):
    @jax.jit
    def run(x):
        return lax.fori_loop(0, ITERS, lambda i, x: body(x), x)
    return run


def main():
    r = np.random.RandomState(0)
    for tag, n, h, w_, c, cg in [("s0", 128, 56, 56, 128, 4),
                                 ("s1", 128, 28, 28, 256, 8)]:
        x = jnp.asarray(r.randn(n, h, w_, c) * 0.5, jnp.bfloat16)
        wg = jnp.asarray(r.randn(3, 3, cg, c) / np.sqrt(9 * cg),
                         jnp.bfloat16)
        wbd = make_blockdiag(wg, c, cg)

        y_ref = conv_ref(x, wg, 32)
        y_pl = grouped_conv_pallas(x, wbd)
        err = float(jnp.max(jnp.abs(y_ref.astype(jnp.float32) -
                                    y_pl.astype(jnp.float32))))
        print(f"{tag}: max abs err pallas vs lax grouped = {err:.4f}")

        t_ref = trace_s(f"{tag}_ref", chain(lambda x: conv_ref(x, wg, 32)),
                        x) / ITERS
        t_pl = trace_s(f"{tag}_pl",
                       chain(lambda x: grouped_conv_pallas(x, wbd)),
                       x) / ITERS
        print(f"{tag}: XLA grouped {t_ref*1e6:8.1f} us | "
              f"pallas block-diag {t_pl*1e6:8.1f} us "
              f"({t_ref/t_pl:.2f}x)")


if __name__ == "__main__":
    main()
