"""Hand-written JAX reference of the bench transformer (same shapes/dtypes)
to isolate the achievable step time on this chip from the Program-IR
lowering. Diagnostic tool only — not part of the framework."""

import time
import numpy as np
import jax
import jax.numpy as jnp

B, S, D, DI, H, L, V = 32, 256, 512, 2048, 8, 6, 10000
DH = D // H


def init_params(key):
    ks = jax.random.split(key, 64)
    p = {"emb": jax.random.normal(ks[0], (V, D)) * 0.02,
         "proj": jax.random.normal(ks[1], (D, V)) * 0.02}
    for i in range(L * 2):  # enc + dec-self (cross omitted: close enough)
        k = jax.random.split(ks[2 + i], 8)
        p[f"l{i}"] = {
            "qkv": jax.random.normal(k[0], (D, 3 * D)) * 0.02,
            "o": jax.random.normal(k[1], (D, D)) * 0.02,
            "f1": jax.random.normal(k[2], (D, DI)) * 0.02,
            "f2": jax.random.normal(k[3], (DI, D)) * 0.02,
            "ln1": jnp.ones((D,)), "ln1b": jnp.zeros((D,)),
            "ln2": jnp.ones((D,)), "ln2b": jnp.zeros((D,)),
        }
    return p


def ln(x, s, b):
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, -1, keepdims=True)
    v = jnp.var(xf, -1, keepdims=True)
    return (((xf - m) * jax.lax.rsqrt(v + 1e-5)) * s + b).astype(x.dtype)


def attn(x, p, key):
    qkv = (x @ p["qkv"].astype(jnp.bfloat16)).reshape(B, S, 3, H, DH)
    q, k, v = [qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3)]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / np.sqrt(DH)
    a = jax.nn.softmax(s, -1).astype(jnp.bfloat16)
    keep = jax.random.bernoulli(key, 0.9, a.shape)
    a = jnp.where(keep, a / 0.9, 0).astype(jnp.bfloat16)
    o = jnp.einsum("bhqk,bhkd->bhqd", a, v)
    o = o.transpose(0, 2, 1, 3).reshape(B, S, D)
    return o @ p["o"].astype(jnp.bfloat16)


def layer(x, p, key):
    k1, k2 = jax.random.split(key)
    x = x + attn(ln(x, p["ln1"], p["ln1b"]), p, k1)
    h = jax.nn.relu(ln(x, p["ln2"], p["ln2b"]) @ p["f1"].astype(jnp.bfloat16))
    keep = jax.random.bernoulli(k2, 0.9, h.shape)
    h = jnp.where(keep, h / 0.9, 0).astype(jnp.bfloat16)
    return x + h @ p["f2"].astype(jnp.bfloat16)


def loss_fn(p, ids, y, key):
    x = p["emb"].astype(jnp.bfloat16)[ids]
    for i in range(L * 2):
        key, sub = jax.random.split(key)
        x = layer(x, p[f"l{i}"], sub)
    logits = (x @ p["proj"].astype(jnp.bfloat16)).astype(jnp.float32)
    lp = jax.nn.log_softmax(logits, -1)
    return -jnp.mean(jnp.take_along_axis(lp, y[..., None], -1))


@jax.jit
def step(p, m, v, t, ids, y, key):
    loss, g = jax.value_and_grad(loss_fn)(p, ids, y, key)
    b1, b2, lr, eps = 0.9, 0.999, 1e-4, 1e-8
    t = t + 1
    m = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32), m, g)
    v = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * jnp.square(g.astype(jnp.float32)), v, g)
    def upd(p, m, v):
        mh = m / (1 - b1 ** t)
        vh = v / (1 - b2 ** t)
        return p - lr * mh / (jnp.sqrt(vh) + eps)
    p = jax.tree.map(upd, p, m, v)
    return p, m, v, t, loss


def main():
    key = jax.random.PRNGKey(0)
    p = init_params(key)
    m = jax.tree.map(jnp.zeros_like, p)
    v = jax.tree.map(jnp.zeros_like, p)
    t = jnp.zeros((), jnp.int32)
    ids = jnp.asarray(np.random.randint(0, V, (B, S)))
    y = jnp.asarray(np.random.randint(0, V, (B, S)))
    t0 = time.perf_counter()
    p, m, v, t, loss = step(p, m, v, t, ids, y, key)
    jax.block_until_ready(loss)
    print(f"compile+1st: {time.perf_counter()-t0:.1f}s")
    for _ in range(3):
        p, m, v, t, loss = step(p, m, v, t, ids, y, key)
    jax.block_until_ready(loss)
    n = 20
    t0 = time.perf_counter()
    for _ in range(n):
        p, m, v, t, loss = step(p, m, v, t, ids, y, key)
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / n
    flops = 3 * (2 * B * S * (L * 2) * (4 * D * D + 2 * D * DI) + 2 * B * S * D * V
                 + (L * 2) * 2 * 2 * B * S * S * D)
    print(f"step: {dt*1000:.1f}ms  ~MFU={flops/dt/197e12:.3f}")


if __name__ == "__main__":
    main()
