"""Hand-written pure-JAX SE-ResNeXt-50 train step (same shapes/dtypes as
bench_family.py's se_resnext config: b=128, 224x224, bf16 AMP compute,
fp32 params, momentum) to isolate the achievable step time on this chip
from the Program-IR lowering — the framework-overhead-is-zero leg of the
SE-ResNeXt prove-or-kill (VERDICT r4 item 1a), mirroring what
benchmarks/purejax_ref.py settled for ResNet-50. Diagnostic only.

Run: python benchmarks/purejax_seresnext.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

B = 128
STAGES = [3, 4, 6, 3]
FILTERS = [128, 256, 512, 1024]
CARD = 32
RED = 16


def conv(x, w, stride=1, groups=1):
    k = w.shape[0]
    p = (k - 1) // 2
    return lax.conv_general_dilated(
        x, w, (stride, stride), [(p, p), (p, p)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups)


def bn(x, p, name):
    """One-pass E[x],E[x^2] batch-stat BN in affine y=k*x+c form — the
    same formulation ops/nn_ops.py batch_norm emits (BASELINE.md r3)."""
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=(0, 1, 2))
    m2 = jnp.mean(jnp.square(xf), axis=(0, 1, 2))
    var = m2 - jnp.square(m)
    inv = lax.rsqrt(var + 1e-5) * p[name + ".s"]
    return (x * inv.astype(x.dtype) +
            (p[name + ".b"] - m * inv).astype(x.dtype))


def conv_bn(x, p, name, stride=1, groups=1, relu=True):
    y = bn(conv(x, p[name + ".w"].astype(jnp.bfloat16), stride, groups),
           p, name)
    return jax.nn.relu(y) if relu else y


def se(x, p, name):
    c = x.shape[-1]
    pool = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    s = jax.nn.relu(pool @ p[name + ".w1"] + p[name + ".b1"])
    e = jax.nn.sigmoid(s @ p[name + ".w2"] + p[name + ".b2"])
    return x * e[:, None, None, :].astype(x.dtype)


def block(x, p, name, filters, stride):
    y = conv_bn(x, p, name + ".c0")
    y = conv_bn(y, p, name + ".c1", stride=stride, groups=CARD)
    y = conv_bn(y, p, name + ".c2", relu=False)
    y = se(y, p, name + ".se")
    if x.shape[-1] == 2 * filters and stride == 1:
        short = x
    else:
        short = conv_bn(x, p, name + ".sc", stride=stride, relu=False)
    return jax.nn.relu(short + y)


def forward(p, img):
    x = conv_bn(img, p, "stem", stride=2)
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1),
                          (1, 2, 2, 1), [(0, 0), (1, 1), (1, 1), (0, 0)])
    for si, (n, f) in enumerate(zip(STAGES, FILTERS)):
        for bi in range(n):
            x = block(x, p, f"b{si}_{bi}", f,
                      2 if bi == 0 and si != 0 else 1)
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    return x @ p["fc.w"] + p["fc.b"]


def init_params(rng):
    p = {}

    def cw(name, k, ci, co):
        p[name + ".w"] = jnp.asarray(
            rng.randn(k, k, ci, co) * np.sqrt(2.0 / (k * k * ci)),
            jnp.float32)
        p[name + ".s"] = jnp.ones((co,), jnp.float32)
        p[name + ".b"] = jnp.zeros((co,), jnp.float32)

    cw("stem", 7, 3, 64)
    cin = 64
    for si, (n, f) in enumerate(zip(STAGES, FILTERS)):
        for bi in range(n):
            name = f"b{si}_{bi}"
            cw(name + ".c0", 1, cin, f)
            cw(name + ".c1", 3, f // CARD, f)
            cw(name + ".c2", 1, f, 2 * f)
            c2 = 2 * f
            p[name + ".se.w1"] = jnp.asarray(
                rng.randn(c2, c2 // RED) * np.sqrt(2.0 / c2), jnp.float32)
            p[name + ".se.b1"] = jnp.zeros((c2 // RED,), jnp.float32)
            p[name + ".se.w2"] = jnp.asarray(
                rng.randn(c2 // RED, c2) * np.sqrt(2.0 / (c2 // RED)),
                jnp.float32)
            p[name + ".se.b2"] = jnp.zeros((c2,), jnp.float32)
            if cin != c2 or (bi == 0 and si != 0):
                cw(name + ".sc", 1, cin, c2)
            cin = c2
    p["fc.w"] = jnp.asarray(rng.randn(cin, 1000) * 0.01, jnp.float32)
    p["fc.b"] = jnp.zeros((1000,), jnp.float32)
    return p


def loss_fn(p, img, label):
    logits = forward(p, img)
    lse = jax.nn.logsumexp(logits, axis=-1)
    nll = lse - jnp.take_along_axis(logits, label[:, None], axis=1)[:, 0]
    return jnp.mean(nll)


@jax.jit
def step(p, mom, img, label):
    loss, g = jax.value_and_grad(loss_fn)(p, img, label)
    new_m = {k: 0.9 * mom[k] + g[k] for k in g}
    new_p = {k: p[k] - 0.1 * new_m[k] for k in p}
    return new_p, new_m, loss


def main():
    rng = np.random.RandomState(0)
    p = init_params(rng)
    mom = {k: jnp.zeros_like(v) for k, v in p.items()}
    img = jnp.asarray(rng.randn(B, 224, 224, 3) * 0.5, jnp.bfloat16)
    label = jnp.asarray(rng.randint(0, 1000, (B,)), jnp.int32)

    t0 = time.perf_counter()
    p, mom, loss = step(p, mom, img, label)
    jax.block_until_ready(loss)
    print(f"compile+first: {time.perf_counter() - t0:.1f}s loss={float(loss):.3f}")

    for w in range(3):
        t0 = time.perf_counter()
        for _ in range(30):
            p, mom, loss = step(p, mom, img, label)
        jax.block_until_ready(loss)
        dt = (time.perf_counter() - t0) / 30
        fwd_flops = 8.47e9  # BASELINE.md analytic fwd GFLOP/image
        mfu = 3 * fwd_flops * B / dt / 197e12
        print(f"window {w}: {dt*1e3:.1f} ms/step  "
              f"{B/dt:.0f} img/s  MFU {mfu:.3f}")


if __name__ == "__main__":
    main()
