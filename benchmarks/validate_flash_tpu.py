"""On-chip validation of the Pallas flash-attention kernels (run manually
on a TPU host; the pytest suite covers the same cases via interpret mode
except dropout, which needs the hardware PRNG)."""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.parallel import flash_attention as fa


def rand(shape, seed, scale=0.3):
    return jnp.asarray(
        np.random.RandomState(seed).randn(*shape).astype(np.float32) * scale
    )


def main():
    assert jax.default_backend() == "tpu", jax.default_backend()
    b, h, tq, tk, dh = 2, 4, 512, 512, 64
    q, k, v = rand((b, h, tq, dh), 0), rand((b, h, tk, dh), 1), rand((b, h, tk, dh), 2)
    causal = np.triu(np.full((tk, tk), -1e9, np.float32), k=1)
    bias = jnp.asarray(np.broadcast_to(causal, (b, 1, tk, tk)).copy())
    scale = 1.0 / np.sqrt(dh)

    # forward — compare against f64 ground truth (on TPU the dense f32
    # reference itself is ~1e-4 off f64; the kernel must be no worse)
    out = jax.jit(lambda q, k, v: fa.flash_attention(q, k, v, bias=bias))(q, k, v)
    qc, kc, vc = (np.asarray(x, np.float64) for x in (q, k, v))
    s = np.einsum("bhqd,bhkd->bhqk", qc, kc) * scale + np.asarray(bias, np.float64)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref64 = np.einsum("bhqk,bhkd->bhqd", p, vc)
    ref = fa._reference_attention(q, k, v, bias, scale)
    err = float(np.max(np.abs(np.asarray(out) - ref64)))
    err_dense = float(np.max(np.abs(np.asarray(ref) - ref64)))
    print(f"fwd max err vs f64: pallas={err:.2e} dense={err_dense:.2e}")
    assert err < max(5e-4, 3 * err_dense)

    # backward
    w = jnp.cos(jnp.arange(dh, dtype=jnp.float32))
    f_flash = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(fa.flash_attention(q, k, v, bias=bias) * w),
        argnums=(0, 1, 2)))
    f_ref = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(fa._reference_attention(q, k, v, bias, scale) * w),
        argnums=(0, 1, 2)))
    for a, bb, name in zip(f_flash(q, k, v), f_ref(q, k, v), "qkv"):
        e = float(jnp.max(jnp.abs(a - bb)))
        print(f"d{name} max err vs dense-on-tpu: {e:.2e}")
        assert e < 2e-3, name

    # dropout: determinism + linear-in-v directional derivative
    seed = jnp.asarray(123, jnp.int32)

    def f(v):
        return jnp.sum(fa.flash_attention(q, k, v, seed=seed, p_drop=0.3))

    fj = jax.jit(f)
    o1, o2 = float(fj(v)), float(fj(v))
    assert o1 == o2, (o1, o2)
    print(f"dropout deterministic: {o1:.6f}")

    dv = jax.jit(jax.grad(f))(v)
    direction = rand(v.shape, 9, 0.01)
    fd = (fj(v + direction) - fj(v - direction)) / 2.0
    an = float(jnp.vdot(dv, direction))
    # the dot is cancellation-heavy; normalize by the positive mass
    mass = float(jnp.vdot(jnp.abs(dv), jnp.abs(direction)))
    print(f"dropout dv directional: analytic={an:.6f} fd={float(fd):.6f} "
          f"(mass {mass:.1f})")
    assert abs(an - float(fd)) < 2e-3 * mass

    # dropout keep-rate sanity: the dropped output's expectation is the
    # undropped output, so the mean deviation must stay small
    o_nodrop = jax.jit(lambda: fa.flash_attention(q, k, v))()
    o_drop = fa.flash_attention(q, k, v, seed=seed, p_drop=0.3)
    mean_dev = float(jnp.mean(jnp.abs(o_drop - o_nodrop)))
    print(f"dropout mean-field check: |E[drop]-nodrop| = {mean_dev:.4f}")
    assert mean_dev < 0.05, mean_dev
    print("ALL CHECKS PASSED")


if __name__ == "__main__":
    main()
