// Best-fit host memory arena with coalescing free list.
//
// Native-parity component for the reference's memory manager — the
// best-fit allocator and buddy allocator behind AllocatorFacade
// (reference: paddle/fluid/memory/allocation/best_fit_allocator.h,
// memory/detail/buddy_allocator.cc). On TPU, HBM allocation belongs to
// XLA/PJRT (buffer donation + compiler buffer assignment replaces the
// device-side arena, SURVEY.md section 7 phase 2); what the runtime still
// owns is *host* staging memory: aligned, reusable buffers that feed the
// infeed pipeline without malloc churn. Exposed via ctypes and used by the
// data plane.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>

namespace {

struct Arena {
  uint8_t* base = nullptr;
  size_t capacity = 0;
  std::mutex mu;
  // offset -> size
  std::map<size_t, size_t> free_blocks;
  std::map<size_t, size_t> used_blocks;
  size_t peak = 0;
  size_t in_use = 0;

  explicit Arena(size_t cap) : capacity(cap) {
    base = static_cast<uint8_t*>(aligned_alloc(4096, cap));
    if (base) free_blocks[0] = cap;
  }
  ~Arena() { free(base); }
};

constexpr size_t kAlign = 64;  // cache line

size_t align_up(size_t x) { return (x + kAlign - 1) & ~(kAlign - 1); }

}  // namespace

extern "C" {

void* arena_create(uint64_t capacity) {
  Arena* a = new Arena(align_up(capacity));
  if (!a->base) {
    delete a;
    return nullptr;
  }
  return a;
}

void arena_destroy(void* h) { delete static_cast<Arena*>(h); }

// Best-fit: smallest free block that fits. Returns pointer or null.
void* arena_alloc(void* h, uint64_t size) {
  Arena* a = static_cast<Arena*>(h);
  size = align_up(size ? size : 1);
  std::lock_guard<std::mutex> l(a->mu);
  std::map<size_t, size_t>::iterator best = a->free_blocks.end();
  for (auto it = a->free_blocks.begin(); it != a->free_blocks.end(); ++it) {
    if (it->second >= size &&
        (best == a->free_blocks.end() || it->second < best->second)) {
      best = it;
    }
  }
  if (best == a->free_blocks.end()) return nullptr;
  size_t off = best->first;
  size_t blk = best->second;
  a->free_blocks.erase(best);
  if (blk > size) a->free_blocks[off + size] = blk - size;
  a->used_blocks[off] = size;
  a->in_use += size;
  if (a->in_use > a->peak) a->peak = a->in_use;
  return a->base + off;
}

int arena_free(void* h, void* ptr) {
  Arena* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> l(a->mu);
  size_t off = static_cast<uint8_t*>(ptr) - a->base;
  auto it = a->used_blocks.find(off);
  if (it == a->used_blocks.end()) return -1;
  size_t size = it->second;
  a->used_blocks.erase(it);
  a->in_use -= size;
  // coalesce with neighbors
  auto next = a->free_blocks.lower_bound(off);
  if (next != a->free_blocks.end() && off + size == next->first) {
    size += next->second;
    a->free_blocks.erase(next);
  }
  if (!a->free_blocks.empty()) {
    auto prev = a->free_blocks.lower_bound(off);
    if (prev != a->free_blocks.begin()) {
      --prev;
      if (prev->first + prev->second == off) {
        prev->second += size;
        return 0;
      }
    }
  }
  a->free_blocks[off] = size;
  return 0;
}

uint64_t arena_in_use(void* h) { return static_cast<Arena*>(h)->in_use; }
uint64_t arena_peak(void* h) { return static_cast<Arena*>(h)->peak; }

}  // extern "C"
