// Coordination service: TCP key-value store + barriers for multi-host
// bootstrap and control-plane sync.
//
// Native-parity replacement for the reference's collective bootstrap and
// barrier machinery — exchanging ncclUniqueId over RPC (reference:
// operators/distributed_ops/gen_nccl_id_op.cc:62) and pserver barrier
// counters (reference: operators/distributed_ops/listen_and_serv_op.cc:135).
// On TPU pods the data-plane collectives are XLA/ICI; what remains is a
// small control-plane: rendezvous (PUT/GET with blocking waits), barriers,
// and liveness (heartbeat timestamps for failure detection, SURVEY.md
// section 5 "failure detection").
//
// Wire protocol (length-prefixed): u32 len | u8 op | payload.
//   op 'P': PUT  key\0value      -> "OK"
//   op 'G': GET  key\0timeout_ms -> value (blocks until present or timeout)
//   op 'B': BARRIER name\0count  -> "OK" when count participants arrived
//   op 'H': HEARTBEAT id         -> "OK" (records monotonic timestamp)
//   op 'L': LIVENESS max_age_ms  -> comma-joined ids considered dead

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct Server {
  int listen_fd = -1;
  std::thread accept_thread;
  bool stopping = false;

  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> kv;
  std::map<std::string, int> barrier_count;
  std::map<std::string, int> barrier_gen;
  std::map<std::string, Clock::time_point> heartbeats;
  std::vector<std::thread> workers;
  std::vector<int> conn_fds;  // open connections, shut down on stop

  ~Server() { stop(); }

  void stop() {
    {
      std::lock_guard<std::mutex> l(mu);
      if (stopping) return;
      stopping = true;
      // unblock worker threads parked in recv() on live connections
      for (int fd : conn_fds) shutdown(fd, SHUT_RDWR);
    }
    cv.notify_all();
    if (listen_fd >= 0) {
      shutdown(listen_fd, SHUT_RDWR);
      close(listen_fd);
      listen_fd = -1;
    }
    if (accept_thread.joinable()) accept_thread.join();
    for (auto& w : workers)
      if (w.joinable()) w.join();
  }
};

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t k = send(fd, p, n, 0);
    if (k <= 0) return false;
    p += k;
    n -= size_t(k);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t k = recv(fd, p, n, 0);
    if (k <= 0) return false;
    p += k;
    n -= size_t(k);
  }
  return true;
}

bool send_msg(int fd, const std::string& s) {
  uint32_t len = htonl(uint32_t(s.size()));
  return send_all(fd, &len, 4) && send_all(fd, s.data(), s.size());
}

bool recv_msg(int fd, std::string* s) {
  uint32_t len;
  if (!recv_all(fd, &len, 4)) return false;
  len = ntohl(len);
  if (len > (64u << 20)) return false;
  s->resize(len);
  return len == 0 || recv_all(fd, &(*s)[0], len);
}

void handle_conn(Server* srv, int fd) {
  std::string msg;
  while (recv_msg(fd, &msg)) {
    if (msg.empty()) break;
    char op = msg[0];
    std::string body = msg.substr(1);
    size_t sep = body.find('\0');
    std::string a = sep == std::string::npos ? body : body.substr(0, sep);
    std::string b = sep == std::string::npos ? "" : body.substr(sep + 1);
    if (op == 'P') {
      {
        std::lock_guard<std::mutex> l(srv->mu);
        srv->kv[a] = b;
      }
      srv->cv.notify_all();
      if (!send_msg(fd, "OK")) break;
    } else if (op == 'G') {
      int timeout_ms = b.empty() ? -1 : atoi(b.c_str());
      std::unique_lock<std::mutex> l(srv->mu);
      auto pred = [&] { return srv->stopping || srv->kv.count(a); };
      bool ok;
      if (timeout_ms < 0) {
        srv->cv.wait(l, pred);
        ok = srv->kv.count(a) > 0;
      } else {
        ok = srv->cv.wait_for(l, std::chrono::milliseconds(timeout_ms), pred) &&
             srv->kv.count(a);
      }
      std::string val = ok ? srv->kv[a] : "";
      l.unlock();
      if (!send_msg(fd, ok ? "V" + val : "E")) break;
    } else if (op == 'B') {
      int want = atoi(b.c_str());
      std::unique_lock<std::mutex> l(srv->mu);
      int my_gen = srv->barrier_gen[a];
      if (++srv->barrier_count[a] >= want) {
        srv->barrier_count[a] = 0;
        srv->barrier_gen[a]++;
        srv->cv.notify_all();
      } else {
        srv->cv.wait(l, [&] {
          return srv->stopping || srv->barrier_gen[a] != my_gen;
        });
      }
      l.unlock();
      if (!send_msg(fd, "OK")) break;
    } else if (op == 'D') {
      // delete a KV key (KV hygiene: per-step liveness-barrier arrive
      // keys would otherwise accumulate unboundedly in long runs)
      {
        std::lock_guard<std::mutex> l(srv->mu);
        srv->kv.erase(a);
      }
      if (!send_msg(fd, "OK")) break;
    } else if (op == 'H') {
      {
        std::lock_guard<std::mutex> l(srv->mu);
        srv->heartbeats[a] = Clock::now();
      }
      if (!send_msg(fd, "OK")) break;
    } else if (op == 'L') {
      int max_age_ms = atoi(a.c_str());
      std::string dead;
      {
        std::lock_guard<std::mutex> l(srv->mu);
        auto now = Clock::now();
        for (auto& it : srv->heartbeats) {
          auto age = std::chrono::duration_cast<std::chrono::milliseconds>(
                         now - it.second)
                         .count();
          if (age > max_age_ms) {
            if (!dead.empty()) dead += ",";
            dead += it.first;
          }
        }
      }
      if (!send_msg(fd, dead)) break;
    } else {
      break;
    }
  }
  close(fd);
}

struct Client {
  int fd = -1;
};

}  // namespace

extern "C" {

void* coord_server_start(int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(uint16_t(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    close(fd);
    return nullptr;
  }
  Server* srv = new Server();
  srv->listen_fd = fd;
  srv->accept_thread = std::thread([srv] {
    for (;;) {
      int cfd = accept(srv->listen_fd, nullptr, nullptr);
      if (cfd < 0) break;
      std::lock_guard<std::mutex> l(srv->mu);
      if (srv->stopping) {
        close(cfd);
        break;
      }
      srv->conn_fds.push_back(cfd);
      srv->workers.emplace_back(handle_conn, srv, cfd);
    }
  });
  return srv;
}

void coord_server_stop(void* h) {
  Server* srv = static_cast<Server*>(h);
  srv->stop();
  delete srv;
}

void* coord_client_connect(const char* host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
      connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    close(fd);
    return nullptr;
  }
  Client* c = new Client();
  c->fd = fd;
  return c;
}

void coord_client_close(void* h) {
  Client* c = static_cast<Client*>(h);
  close(c->fd);
  delete c;
}

static int roundtrip(Client* c, const std::string& req, std::string* resp) {
  if (!send_msg(c->fd, req)) return -1;
  if (!recv_msg(c->fd, resp)) return -1;
  return 0;
}

int coord_put(void* h, const char* key, const uint8_t* val, uint32_t len) {
  Client* c = static_cast<Client*>(h);
  std::string req = "P";
  req += key;
  req += '\0';
  req.append(reinterpret_cast<const char*>(val), len);
  std::string resp;
  return roundtrip(c, req, &resp) == 0 && resp == "OK" ? 0 : -1;
}

// returns length (>=0) and copies into out (cap bytes);
// -1 timeout/absent, -2 connection error, -(n+3) value present but needs
// n bytes (> cap).
int coord_get(void* h, const char* key, int timeout_ms, uint8_t* out,
              uint32_t cap) {
  Client* c = static_cast<Client*>(h);
  std::string req = "G";
  req += key;
  req += '\0';
  req += std::to_string(timeout_ms);
  std::string resp;
  if (roundtrip(c, req, &resp) != 0) return -2;
  if (resp.empty() || resp[0] != 'V') return -1;
  uint32_t n = uint32_t(resp.size() - 1);
  if (n > cap) return -int(n) - 3;
  memcpy(out, resp.data() + 1, n);
  return int(n);
}

int coord_barrier(void* h, const char* name, int count) {
  Client* c = static_cast<Client*>(h);
  std::string req = "B";
  req += name;
  req += '\0';
  req += std::to_string(count);
  std::string resp;
  return roundtrip(c, req, &resp) == 0 && resp == "OK" ? 0 : -1;
}

int coord_del(void* h, const char* key) {
  Client* c = static_cast<Client*>(h);
  std::string req = "D";
  req += key;
  std::string resp;
  return roundtrip(c, req, &resp) == 0 && resp == "OK" ? 0 : -1;
}

int coord_heartbeat(void* h, const char* id) {
  Client* c = static_cast<Client*>(h);
  std::string req = "H";
  req += id;
  std::string resp;
  return roundtrip(c, req, &resp) == 0 && resp == "OK" ? 0 : -1;
}

int coord_dead_peers(void* h, int max_age_ms, char* out, uint32_t cap) {
  Client* c = static_cast<Client*>(h);
  std::string req = "L";
  req += std::to_string(max_age_ms);
  std::string resp;
  if (roundtrip(c, req, &resp) != 0) return -1;
  if (resp.size() + 1 > cap) return -1;
  memcpy(out, resp.c_str(), resp.size() + 1);
  return int(resp.size());
}

}  // extern "C"
