// C inference API implementation (see pt_predictor.h).
//
// Embeds CPython (the csrc/standalone_trainer.cc pattern): the XLA
// compute path is identical to the Python Predictor's — fixed-signature
// compiled executables with donated, device-resident parameters
// (paddle_tpu/inference.py). Reference counterpart:
// paddle/fluid/inference/api/api.cc (NativePaddlePredictor C surface).

#include "pt_predictor.h"

#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

namespace {

std::string g_error;

void SetErrorFromPython() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_error = "python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) g_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

bool EnsurePython() {
  if (Py_IsInitialized()) return true;
  Py_Initialize();
  // Make the repo importable: PT_REPO env or cwd (same contract as the
  // standalone trainer).
  const char* repo = std::getenv("PT_REPO");
  std::string code =
      "import sys, os\n"
      "sys.path.insert(0, os.environ.get('PT_REPO', os.getcwd()))\n";
  // The hosted-TPU jax plugin overrides JAX_PLATFORMS; serving hosts
  // that want the CPU backend set PT_CAPI_PLATFORM=cpu.
  code +=
      "if os.environ.get('PT_CAPI_PLATFORM'):\n"
      "    import jax\n"
      "    jax.config.update('jax_platforms', "
      "os.environ['PT_CAPI_PLATFORM'])\n";
  (void)repo;
  if (PyRun_SimpleString(code.c_str()) != 0) {
    g_error = "python bootstrap failed";
    return false;
  }
  return true;
}

struct Output {
  Py_buffer view;        // holds the float32 numpy buffer alive
  std::vector<long long> shape;
  bool held = false;
};

}  // namespace

struct pt_predictor {
  PyObject* globals = nullptr;  // namespace holding PRED / helpers
  std::vector<Output> outputs;

  void ReleaseOutputs() {
    for (auto& o : outputs) {
      if (o.held) PyBuffer_Release(&o.view);
    }
    outputs.clear();
  }
};

extern "C" {

const char* pt_predictor_error(void) { return g_error.c_str(); }

pt_predictor* pt_predictor_create(const char* model_dir) {
  if (!EnsurePython()) return nullptr;
  PyObject* globals = PyDict_New();
  PyDict_SetItemString(globals, "__builtins__", PyEval_GetBuiltins());
  PyObject* dir_obj = PyUnicode_FromString(model_dir);
  PyDict_SetItemString(globals, "MODEL_DIR", dir_obj);  // does not steal
  Py_DECREF(dir_obj);
  static const char kCreate[] = R"PY(
import numpy as np
from paddle_tpu.inference import Config, create_predictor
PRED = create_predictor(Config(MODEL_DIR))
_NP = np
_DTYPES = {0: np.float32, 1: np.int64, 2: np.int32}

def _RUN(feed_specs):
    # feed_specs: list of (name, memoryview, dtype_code, shape_tuple)
    feed = {}
    for name, mv, code, shape in feed_specs:
        arr = np.frombuffer(mv, dtype=_DTYPES[code]).reshape(shape).copy()
        feed[name] = arr
    outs = PRED.run(feed)
    return [np.ascontiguousarray(np.asarray(o), dtype=np.float32)
            for o in outs]
)PY";
  PyObject* r = PyRun_String(kCreate, Py_file_input, globals, globals);
  if (r == nullptr) {
    SetErrorFromPython();
    Py_DECREF(globals);
    return nullptr;
  }
  Py_DECREF(r);
  pt_predictor* p = new pt_predictor();
  p->globals = globals;
  return p;
}

void pt_predictor_destroy(pt_predictor* p) {
  if (p == nullptr) return;
  p->ReleaseOutputs();
  Py_XDECREF(p->globals);
  delete p;
}

int pt_predictor_run(pt_predictor* p, int n_inputs,
                     const char* const* names, const void* const* data,
                     const int* dtypes, const int* ranks,
                     const long long* shapes) {
  static const size_t kDtypeSize[] = {4, 8, 4};
  PyObject* specs = PyList_New(n_inputs);
  const long long* dim = shapes;
  for (int i = 0; i < n_inputs; ++i) {
    long long numel = 1;
    PyObject* shape = PyTuple_New(ranks[i]);
    for (int d = 0; d < ranks[i]; ++d, ++dim) {
      numel *= *dim;
      PyTuple_SetItem(shape, d, PyLong_FromLongLong(*dim));
    }
    if (dtypes[i] < 0 || dtypes[i] > 2) {
      Py_DECREF(shape);
      Py_DECREF(specs);
      g_error = "unknown dtype code";
      return 1;
    }
    PyObject* mv = PyMemoryView_FromMemory(
        const_cast<char*>(static_cast<const char*>(data[i])),
        numel * kDtypeSize[dtypes[i]], PyBUF_READ);
    // PyTuple_Pack increfs its arguments: every temporary must be
    // released here or each call leaks one ref per input (unbounded
    // growth in a steady-state serving loop).
    PyObject* name_obj = PyUnicode_FromString(names[i]);
    PyObject* code_obj = PyLong_FromLong(dtypes[i]);
    PyObject* spec = PyTuple_Pack(4, name_obj, mv, code_obj, shape);
    Py_DECREF(name_obj);
    Py_DECREF(code_obj);
    Py_DECREF(mv);
    Py_DECREF(shape);
    PyList_SetItem(specs, i, spec);  // steals spec
  }
  PyObject* run_fn = PyDict_GetItemString(p->globals, "_RUN");  // borrowed
  PyObject* outs = PyObject_CallFunctionObjArgs(run_fn, specs, nullptr);
  Py_DECREF(specs);
  if (outs == nullptr) {
    SetErrorFromPython();
    return 1;
  }
  p->ReleaseOutputs();
  Py_ssize_t n = PyList_Size(outs);
  p->outputs.resize(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* arr = PyList_GetItem(outs, i);  // borrowed
    Output& o = p->outputs[static_cast<size_t>(i)];
    if (PyObject_GetBuffer(arr, &o.view, PyBUF_CONTIG_RO | PyBUF_FORMAT) !=
        0) {
      SetErrorFromPython();
      Py_DECREF(outs);
      p->ReleaseOutputs();
      return 1;
    }
    o.held = true;  // Py_buffer keeps the array alive after outs dies
    o.shape.assign(o.view.shape, o.view.shape + o.view.ndim);
  }
  Py_DECREF(outs);
  return 0;
}

int pt_predictor_num_outputs(pt_predictor* p) {
  return static_cast<int>(p->outputs.size());
}

int pt_predictor_output_rank(pt_predictor* p, int i) {
  return static_cast<int>(p->outputs[static_cast<size_t>(i)].shape.size());
}

const long long* pt_predictor_output_shape(pt_predictor* p, int i) {
  return p->outputs[static_cast<size_t>(i)].shape.data();
}

const float* pt_predictor_output_data(pt_predictor* p, int i,
                                      long long* numel) {
  const Output& o = p->outputs[static_cast<size_t>(i)];
  long long n = 1;
  for (long long d : o.shape) n *= d;
  if (numel != nullptr) *numel = n;
  return static_cast<const float*>(o.view.buf);
}

}  // extern "C"
