/* C test for the native predictor API (pt_predictor.h): load an
 * exported zoo model, run a float batch read from a raw file, and check
 * the outputs against an expected raw file within tolerance.
 *
 * Usage:
 *   predictor_capi_test <model_dir> <input.bin> <rank> <d0> <d1> ...
 *                       <input_name> <expected.bin>
 * Exit 0 = outputs match. Pure C (compiled with -std=c99), linking only
 * libpt_predictor — proves the ABI needs no C++/Python on the caller
 * side. */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>

#include "pt_predictor.h"

static void* read_file(const char* path, long* size_out) {
  FILE* f = fopen(path, "rb");
  if (!f) return NULL;
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  void* buf = malloc((size_t)sz);
  if (fread(buf, 1, (size_t)sz, f) != (size_t)sz) {
    fclose(f);
    free(buf);
    return NULL;
  }
  fclose(f);
  if (size_out) *size_out = sz;
  return buf;
}

int main(int argc, char** argv) {
  if (argc < 7) {
    fprintf(stderr,
            "usage: %s <model_dir> <input.bin> <rank> <dims...> "
            "<input_name> <expected.bin>\n",
            argv[0]);
    return 2;
  }
  const char* model_dir = argv[1];
  const char* input_path = argv[2];
  int rank = atoi(argv[3]);
  if (argc != 6 + rank) {
    fprintf(stderr, "bad arg count for rank %d\n", rank);
    return 2;
  }
  long long shapes[8];
  long long numel = 1;
  for (int i = 0; i < rank; ++i) {
    shapes[i] = atoll(argv[4 + i]);
    numel *= shapes[i];
  }
  const char* input_name = argv[4 + rank];
  const char* expected_path = argv[5 + rank];

  long in_size = 0, exp_size = 0;
  float* input = (float*)read_file(input_path, &in_size);
  float* expected = (float*)read_file(expected_path, &exp_size);
  if (!input || !expected) {
    fprintf(stderr, "cannot read input/expected files\n");
    return 2;
  }
  if (in_size != numel * 4) {
    fprintf(stderr, "input size %ld != %lld floats\n", in_size, numel * 4);
    return 2;
  }

  pt_predictor* p = pt_predictor_create(model_dir);
  if (!p) {
    fprintf(stderr, "create failed: %s\n", pt_predictor_error());
    return 1;
  }
  const char* names[1] = {input_name};
  const void* data[1] = {input};
  int dtypes[1] = {PT_DTYPE_FLOAT32};
  int ranks[1] = {rank};
  if (pt_predictor_run(p, 1, names, data, dtypes, ranks, shapes) != 0) {
    fprintf(stderr, "run failed: %s\n", pt_predictor_error());
    pt_predictor_destroy(p);
    return 1;
  }
  if (pt_predictor_num_outputs(p) < 1) {
    fprintf(stderr, "no outputs\n");
    pt_predictor_destroy(p);
    return 1;
  }
  long long out_n = 0;
  const float* out = pt_predictor_output_data(p, 0, &out_n);
  if (out_n * 4 != exp_size) {
    fprintf(stderr, "output numel %lld != expected %ld bytes/4\n", out_n,
            exp_size);
    pt_predictor_destroy(p);
    return 1;
  }
  double max_err = 0.0;
  for (long long i = 0; i < out_n; ++i) {
    double e = fabs((double)out[i] - (double)expected[i]);
    if (e > max_err) max_err = e;
  }
  printf("outputs %d, first shape rank %d, numel %lld, max_err %g\n",
         pt_predictor_num_outputs(p), pt_predictor_output_rank(p, 0), out_n,
         max_err);
  /* second run on the same handle must work (steady-state serving) */
  if (pt_predictor_run(p, 1, names, data, dtypes, ranks, shapes) != 0) {
    fprintf(stderr, "second run failed: %s\n", pt_predictor_error());
    pt_predictor_destroy(p);
    return 1;
  }
  pt_predictor_destroy(p);
  free(input);
  free(expected);
  return max_err < 1e-4 ? 0 : 1;
}
