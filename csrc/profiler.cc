// Host-side span profiler -> chrome://tracing JSON.
//
// Native-parity component for the reference's host profiler —
// RecordEvent RAII spans + Enable/DisableProfiler state machine
// (reference: paddle/fluid/platform/profiler.h:81,166) and the
// tools/timeline.py chrome-trace conversion (reference:
// tools/timeline.py:283). Device-side timing is XLA's own profiler
// (xplane); this covers the host runtime: executor dispatch, infeed,
// checkpoint, python-annotated spans. Thread-safe, per-thread buffers
// flushed on dump.

#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct Event {
  std::string name;
  uint64_t ts_us;
  uint64_t dur_us;
  long tid;
};

struct Profiler {
  std::mutex mu;
  std::vector<Event> events;
  std::atomic<bool> enabled{false};
  std::atomic<uint64_t> epoch{0};  // bumped on enable; stale spans dropped
  Clock::time_point start;
};

Profiler g_prof;

struct Span {
  std::string name;
  Clock::time_point start;
  uint64_t epoch;
};

thread_local std::vector<Span> t_stack;

uint64_t us_since_start(Clock::time_point t) {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             t - g_prof.start)
      .count();
}

}  // namespace

extern "C" {

void prof_enable() {
  std::lock_guard<std::mutex> l(g_prof.mu);
  g_prof.start = Clock::now();
  g_prof.events.clear();
  g_prof.epoch.fetch_add(1);
  g_prof.enabled.store(true);
}

void prof_disable() { g_prof.enabled.store(false); }

int prof_is_enabled() { return g_prof.enabled.load() ? 1 : 0; }

void prof_begin(const char* name) {
  if (!g_prof.enabled.load()) return;
  t_stack.push_back({name, Clock::now(), g_prof.epoch.load()});
}

void prof_end() {
  // always pop a matching span so begin/end stay balanced even when
  // profiling is toggled mid-span; record only spans from the live epoch
  if (t_stack.empty()) return;
  Span span = std::move(t_stack.back());
  t_stack.pop_back();
  if (!g_prof.enabled.load() || span.epoch != g_prof.epoch.load()) return;
  auto now = Clock::now();
  Event e;
  e.name = std::move(span.name);
  e.ts_us = us_since_start(span.start);
  e.dur_us = std::chrono::duration_cast<std::chrono::microseconds>(
                 now - span.start)
                 .count();
  e.tid = syscall(SYS_gettid);
  std::lock_guard<std::mutex> l(g_prof.mu);
  g_prof.events.push_back(std::move(e));
}

// Writes chrome://tracing JSON. Returns number of events, -1 on error.
int prof_dump(const char* path) {
  std::lock_guard<std::mutex> l(g_prof.mu);
  FILE* f = fopen(path, "w");
  if (!f) return -1;
  fputs("{\"traceEvents\":[", f);
  for (size_t i = 0; i < g_prof.events.size(); ++i) {
    const Event& e = g_prof.events[i];
    std::string name = e.name;
    for (auto& c : name)
      if (c == '"' || c == '\\' || (unsigned char)c < 0x20) c = '_';
    fprintf(f,
            "%s{\"name\":\"%s\",\"ph\":\"X\",\"pid\":%d,\"tid\":%ld,"
            "\"ts\":%llu,\"dur\":%llu}",
            i ? "," : "", name.c_str(), getpid(), e.tid,
            (unsigned long long)e.ts_us, (unsigned long long)e.dur_us);
  }
  fputs("]}", f);
  fclose(f);
  return int(g_prof.events.size());
}

}  // extern "C"
