/* Stable C inference API over the paddle_tpu Predictor.
 *
 * The native serving surface (reference:
 * paddle/fluid/inference/api/api.cc + paddle_fluid.map symbol control —
 * the reference exports a C/C++ predictor ABI usable from non-Python
 * serving stacks). TPU-native design: the compute path is the same
 * whole-program XLA executable the Python Predictor drives; this layer
 * embeds CPython once per process (the standalone_trainer pattern,
 * csrc/standalone_trainer.cc) and exposes a minimal stable ABI.
 *
 * Threading: calls must come from one thread (the embedded interpreter
 * holds the GIL across calls). Output buffers are owned by the
 * predictor and remain valid until the next pt_predictor_run or
 * pt_predictor_destroy on the same handle.
 */
#ifndef PT_PREDICTOR_H_
#define PT_PREDICTOR_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef struct pt_predictor pt_predictor;

/* dtype codes for pt_predictor_run inputs */
enum { PT_DTYPE_FLOAT32 = 0, PT_DTYPE_INT64 = 1, PT_DTYPE_INT32 = 2 };

/* Load an inference model exported by
 * paddle_tpu.io.save_inference_model. Returns NULL on failure (see
 * pt_predictor_error). */
pt_predictor* pt_predictor_create(const char* model_dir);

/* Last error message for a NULL create or non-zero run (process-wide,
 * not thread-safe). */
const char* pt_predictor_error(void);

void pt_predictor_destroy(pt_predictor* p);

/* Run one batch. shapes = the n_inputs ranks' dims concatenated in
 * order. data[i] points at ranks[i]-rank row-major data of dtypes[i].
 * Returns 0 on success. */
int pt_predictor_run(pt_predictor* p, int n_inputs,
                     const char* const* names, const void* const* data,
                     const int* dtypes, const int* ranks,
                     const long long* shapes);

int pt_predictor_num_outputs(pt_predictor* p);
int pt_predictor_output_rank(pt_predictor* p, int i);
/* dims pointer valid until the next run/destroy */
const long long* pt_predictor_output_shape(pt_predictor* p, int i);
/* Output values as float32 (outputs are converted); *numel receives the
 * element count. Valid until the next run/destroy. */
const float* pt_predictor_output_data(pt_predictor* p, int i,
                                      long long* numel);

#ifdef __cplusplus
}
#endif

#endif /* PT_PREDICTOR_H_ */
