// RecordIO: chunked, CRC-checked record container.
//
// Native-parity component for the reference's C++ RecordIO
// (reference: paddle/fluid/recordio/{header,chunk,scanner,writer}.h):
// records are grouped into chunks, each chunk carries a magic number,
// compressor tag, CRC32 and record count, so a scanner can skip torn or
// corrupt chunks (crash-tolerant appends) and seek chunk-by-chunk.
// Differences by design: compression is raw zlib (always available in this
// image) instead of snappy, and the chunk layout is little-endian fixed
// u32 fields with no protobuf dependency.
//
// Layout per chunk:
//   u32 magic (0x50545231 "PTR1") | u32 compressor (0 none, 1 zlib)
//   u32 num_records | u32 payload_len | u32 crc32(payload)
//   payload: concatenated (u32 len | bytes) records, possibly compressed.

#include <zlib.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x50545231;
constexpr size_t kChunkFlushBytes = 1 << 20;  // 1 MiB

struct Writer {
  FILE* f = nullptr;
  int compressor = 0;
  std::vector<uint8_t> buf;
  uint32_t num_records = 0;

  void append_u32(std::vector<uint8_t>* v, uint32_t x) {
    uint8_t b[4] = {uint8_t(x), uint8_t(x >> 8), uint8_t(x >> 16),
                    uint8_t(x >> 24)};
    v->insert(v->end(), b, b + 4);
  }

  int flush_chunk() {
    if (num_records == 0) return 0;
    std::vector<uint8_t> payload;
    if (compressor == 1) {
      uLongf dst_len = compressBound(buf.size());
      payload.resize(dst_len);
      if (compress2(payload.data(), &dst_len, buf.data(), buf.size(), 6) !=
          Z_OK)
        return -1;
      payload.resize(dst_len);
    } else {
      payload = buf;
    }
    uint32_t crc = crc32(0L, payload.data(), payload.size());
    std::vector<uint8_t> header;
    append_u32(&header, kMagic);
    append_u32(&header, uint32_t(compressor));
    append_u32(&header, num_records);
    append_u32(&header, uint32_t(payload.size()));
    append_u32(&header, crc);
    if (fwrite(header.data(), 1, header.size(), f) != header.size()) return -1;
    if (fwrite(payload.data(), 1, payload.size(), f) != payload.size())
      return -1;
    buf.clear();
    num_records = 0;
    return 0;
  }
};

struct Scanner {
  FILE* f = nullptr;
  std::vector<uint8_t> chunk;     // decompressed payload of current chunk
  size_t pos = 0;                 // cursor within chunk
  std::vector<uint8_t> last_record;

  static bool read_u32(FILE* f, uint32_t* out) {
    uint8_t b[4];
    if (fread(b, 1, 4, f) != 4) return false;
    *out = uint32_t(b[0]) | uint32_t(b[1]) << 8 | uint32_t(b[2]) << 16 |
           uint32_t(b[3]) << 24;
    return true;
  }

  // Loads the next valid chunk; skips corrupt ones (CRC mismatch / bad
  // magic) by scanning forward for the magic marker.
  bool next_chunk() {
    for (;;) {
      uint32_t magic;
      if (!read_u32(f, &magic)) return false;
      if (magic != kMagic) {
        // resync: step back 3 bytes and keep searching
        if (fseek(f, -3, SEEK_CUR) != 0) return false;
        continue;
      }
      uint32_t comp, nrec, plen, crc;
      if (!read_u32(f, &comp) || !read_u32(f, &nrec) || !read_u32(f, &plen) ||
          !read_u32(f, &crc))
        return false;
      // a corrupted length field must not trigger a giant allocation;
      // resync past this header instead (writer never exceeds ~2x the
      // flush threshold even before compression)
      if (plen > (64u << 20)) {
        if (fseek(f, -19, SEEK_CUR) != 0) return false;
        continue;
      }
      std::vector<uint8_t> payload(plen);
      if (fread(payload.data(), 1, plen, f) != plen) return false;
      if (crc32(0L, payload.data(), payload.size()) != crc) continue;  // skip
      if (comp == 1) {
        // decompressed size unknown; grow geometrically
        uLongf cap = plen * 4 + 64;
        for (;;) {
          chunk.resize(cap);
          uLongf dst = cap;
          int rc = uncompress(chunk.data(), &dst, payload.data(), plen);
          if (rc == Z_OK) {
            chunk.resize(dst);
            break;
          }
          if (rc != Z_BUF_ERROR) return false;
          cap *= 2;
        }
      } else {
        chunk = std::move(payload);
      }
      pos = 0;
      return true;
    }
  }
};

}  // namespace

extern "C" {

void* rio_writer_open(const char* path, int compressor) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  Writer* w = new Writer();
  w->f = f;
  w->compressor = compressor;
  return w;
}

int rio_writer_write(void* h, const uint8_t* data, uint32_t len) {
  Writer* w = static_cast<Writer*>(h);
  w->append_u32(&w->buf, len);
  w->buf.insert(w->buf.end(), data, data + len);
  w->num_records++;
  if (w->buf.size() >= kChunkFlushBytes) return w->flush_chunk();
  return 0;
}

int rio_writer_close(void* h) {
  Writer* w = static_cast<Writer*>(h);
  int rc = w->flush_chunk();
  fclose(w->f);
  delete w;
  return rc;
}

void* rio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  Scanner* s = new Scanner();
  s->f = f;
  return s;
}

// returns 1 and sets (*data, *len) on success; 0 on EOF; -1 on error.
int rio_scanner_next(void* h, const uint8_t** data, uint32_t* len) {
  Scanner* s = static_cast<Scanner*>(h);
  for (;;) {
    if (s->pos + 4 <= s->chunk.size()) {
      uint32_t rlen = uint32_t(s->chunk[s->pos]) |
                      uint32_t(s->chunk[s->pos + 1]) << 8 |
                      uint32_t(s->chunk[s->pos + 2]) << 16 |
                      uint32_t(s->chunk[s->pos + 3]) << 24;
      s->pos += 4;
      if (s->pos + rlen > s->chunk.size()) return -1;
      s->last_record.assign(s->chunk.begin() + s->pos,
                            s->chunk.begin() + s->pos + rlen);
      s->pos += rlen;
      *data = s->last_record.data();
      *len = rlen;
      return 1;
    }
    if (!s->next_chunk()) return 0;
  }
}

void rio_scanner_close(void* h) {
  Scanner* s = static_cast<Scanner*>(h);
  fclose(s->f);
  delete s;
}

}  // extern "C"
