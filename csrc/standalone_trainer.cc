// Standalone C++ trainer: train a serialized paddle_tpu Program without
// writing any Python (reference: paddle/fluid/train/demo/demo_trainer.cc
// and train/test_train_recognize_digits.cc).
//
// TPU-native design: the reference links the whole C++ framework and
// interprets the ProgramDesc op by op; here the compute path IS XLA via
// the embedded CPython runtime (the same whole-program compilation the
// Python front end uses), so this binary is the thin native driver the
// reference's demo_trainer is — load ProgramDescs, init the scope, run
// train steps, report losses. Model artifacts come from
// paddle_tpu.contrib.standalone.save_train_program():
//   <dir>/main_program.pb, <dir>/startup_program.pb, <dir>/feeds.json
//
// Usage: standalone_trainer <model_dir> [steps=10] [batch=8]

#include <Python.h>

#include <fstream>
#include <iostream>
#include <sstream>
#include <string>

namespace {

std::string ReadBinaryFile(const std::string& filename) {
  std::ifstream fin(filename, std::ios::in | std::ios::binary);
  if (!fin) {
    std::cerr << "cannot open " << filename << "\n";
    std::exit(2);
  }
  std::ostringstream ss;
  ss << fin.rdbuf();
  return ss.str();
}

// The embedded driver: deserialize, build synthetic feeds from
// feeds.json, run startup once and the train step `steps` times. The
// loss is the first `mean` op's output (the reference demo_trainer's
// convention).
const char kDriver[] = R"PY(
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.environ.get("PT_REPO", os.getcwd()))
import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.framework import Program  # noqa: E402

main = Program.parse_from_string(MAIN_PB)
startup = Program.parse_from_string(STARTUP_PB)
feeds = json.loads(FEEDS_JSON)

loss_name = None
for op in main.blocks[0].ops:
    if op.type == "mean":
        loss_name = op.output_arg_names[0]
        break
assert loss_name is not None, "no mean op found for the loss"

exe = fluid.Executor(fluid.CPUPlace())
scope = fluid.Scope()
rng = np.random.RandomState(0)
with fluid.scope_guard(scope):
    exe.run(startup)
    for step in range(STEPS):
        feed = {}
        for spec in feeds:
            # leading dynamic dim = batch; any other dynamic dim falls
            # back to the spec's "dim" hint or 16 (save_train_program
            # documents passing concrete shapes for NLP-style programs)
            shape = [(BATCH if i == 0 else int(spec.get("dim", 16)))
                     if d in (-1, 0) else d
                     for i, d in enumerate(spec["shape"])]
            if spec["dtype"].startswith("int"):
                hi = int(spec.get("max", 2))
                feed[spec["name"]] = rng.randint(
                    0, max(hi, 1), shape).astype(spec["dtype"])
            else:
                feed[spec["name"]] = rng.normal(
                    0, 1, shape).astype(spec["dtype"])
        (loss,) = exe.run(main, feed=feed, fetch_list=[loss_name])
        print("step %d loss %.6f" % (step, float(np.asarray(loss).ravel()[0])),
              flush=True)
)PY";

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    std::cerr << "usage: " << argv[0] << " <model_dir> [steps] [batch]\n";
    return 2;
  }
  const std::string dir = argv[1];
  const long steps = argc > 2 ? std::strtol(argv[2], nullptr, 10) : 10;
  const long batch = argc > 3 ? std::strtol(argv[3], nullptr, 10) : 8;

  const std::string main_pb = ReadBinaryFile(dir + "/main_program.pb");
  const std::string startup_pb = ReadBinaryFile(dir + "/startup_program.pb");
  const std::string feeds_json = ReadBinaryFile(dir + "/feeds.json");

  Py_Initialize();
  PyObject* globals = PyDict_New();
  PyDict_SetItemString(globals, "__builtins__", PyEval_GetBuiltins());
  PyDict_SetItemString(
      globals, "MAIN_PB",
      PyBytes_FromStringAndSize(main_pb.data(), main_pb.size()));
  PyDict_SetItemString(
      globals, "STARTUP_PB",
      PyBytes_FromStringAndSize(startup_pb.data(), startup_pb.size()));
  PyDict_SetItemString(globals, "FEEDS_JSON",
                       PyUnicode_FromStringAndSize(feeds_json.data(),
                                                   feeds_json.size()));
  PyDict_SetItemString(globals, "STEPS", PyLong_FromLong(steps));
  PyDict_SetItemString(globals, "BATCH", PyLong_FromLong(batch));

  PyObject* result = PyRun_String(kDriver, Py_file_input, globals, globals);
  int rc = 0;
  if (result == nullptr) {
    PyErr_Print();
    rc = 1;
  } else {
    Py_DECREF(result);
  }
  Py_DECREF(globals);
  Py_Finalize();
  return rc;
}
