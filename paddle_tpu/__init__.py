"""paddle_tpu: a TPU-native deep-learning framework.

Re-implements the capability surface of PaddlePaddle Fluid (reference:
/root/reference, lzha106/Paddle) with a TPU-first architecture: a
serializable Program IR built from Python, lowered whole-block to XLA;
JAX/Pallas kernels; GSPMD/pjit parallelism over device meshes; stateless
PRNG; orbax-style sharded checkpointing. See SURVEY.md for the layer map.
"""

__version__ = "0.1.0"

from paddle_tpu import (  # noqa: F401
    backward,
    clip,
    compiler,
    executor,
    framework,
    initializer,
    layers,
    metrics,
    optimizer,
    regularizer,
    unique_name,
)
from paddle_tpu.backward import append_backward, gradients  # noqa: F401
from paddle_tpu.compiler import (  # noqa: F401
    BuildStrategy,
    CompiledProgram,
    ExecutionStrategy,
)
from paddle_tpu import (  # noqa: F401
    dataset_api,
    debugger,
    faults,
    flags,
    fleet_serving,
    inference,
    install_check,
    monitor,
    passes,
    profiler,
    retry,
    serving,
    transpiler,
)
from paddle_tpu.dataset_api import DatasetFactory  # noqa: F401
from paddle_tpu.executor import (  # noqa: F401
    Executor,
    Scope,
    global_scope,
    scope_guard,
)
from paddle_tpu.framework import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    Program,
    TPUPlace,
    Variable,
    default_main_program,
    default_startup_program,
    name_scope,
    program_guard,
)
from paddle_tpu.param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401

# `fluid`-style one-stop namespace: `import paddle_tpu as fluid` largely works.
