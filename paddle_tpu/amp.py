"""Automatic mixed precision.

Reference: contrib/mixed_precision/decorator.py:190 (fp16 compute + fp32
master weights + dynamic loss scaling). TPU-native: bf16 on the MXU needs
no loss scaling, and instead of rewriting the graph with cast ops, the
lowering applies a dtype policy to the MXU-heavy op set at trace time
(core/lowering.py AMP_OP_TYPES) — casts fuse into the matmuls, parameters
stay f32 in HBM.
"""

from __future__ import annotations

from paddle_tpu.framework import default_main_program


def decorate(optimizer, amp_lists=None, init_loss_scaling=1.0,
             use_dynamic_loss_scaling: bool = False):
    """Wrap an optimizer so that minimize() marks the program for bf16
    mixed-precision execution. Loss-scaling args are accepted for API
    parity; bf16's exponent range makes them no-ops."""

    class _AmpOptimizer:
        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, item):
            return getattr(self._inner, item)

        def minimize(self, loss, **kwargs):
            result = self._inner.minimize(loss, **kwargs)
            loss.block.program._amp = True
            return result

        def backward(self, *args, **kwargs):
            return self._inner.backward(*args, **kwargs)

        def apply_gradients(self, params_grads):
            result = self._inner.apply_gradients(params_grads)
            default_main_program()._amp = True
            return result

    return _AmpOptimizer(optimizer)


def enable_amp(program=None):
    """Directly mark a program for bf16 execution of MXU-heavy ops."""
    (program or default_main_program())._amp = True


def disable_amp(program=None):
    (program or default_main_program())._amp = False
