"""Automatic mixed precision.

Reference: contrib/mixed_precision/decorator.py:190 (fp16 compute + fp32
master weights + dynamic loss scaling). TPU-native: bf16 on the MXU needs
no loss scaling for the common case, and instead of rewriting the graph
with cast ops, the lowering applies a dtype policy to the MXU-heavy op set
at trace time (core/lowering.py AMP_OP_TYPES) — casts fuse into the
matmuls, parameters stay f32 in HBM.

``use_dynamic_loss_scaling=True`` additionally builds the reference's
dynamic loss-scaling state machine IN-GRAPH (Micikevicius et al., ICLR
2018): the loss is multiplied by a persistable ``loss_scaling`` var
before backward, gradients are unscaled and zeroed on overflow, the
parameter update is skipped (learning rate gated to 0) when any gradient
went non-finite, and the scale grows ``incr_ratio``x after
``incr_every_n_steps`` clean steps / shrinks ``decr_ratio``x after
``decr_every_n_nan_or_inf`` overflowing steps — all inside the one
compiled step, no host round-trip. The scale and the per-step overflow
flag are registered as numerics-plane aux vars, so with the ``telemetry``
+ ``numerics`` flags on the executor exports ``pt_amp_loss_scale`` and
``pt_amp_overflow_skips_total`` from the same single auxiliary transfer.

Skip semantics: parameters are bit-unchanged on an overflow step.
Optimizer accumulators still see the (zeroed) gradient, so momentum/Adam
moments decay one step and Adam's beta powers advance — the same drift
the reference's zero-the-grads fallback has; exact-state skip would need
doubling accumulator memory.
"""

from __future__ import annotations

from paddle_tpu.framework import default_main_program


class AmpOptimizer:
    """The ``decorate`` wrapper: delegates to the inner optimizer, marks
    programs for bf16 lowering, and (optionally) builds the in-graph
    dynamic loss-scaling state machine around ``minimize``."""

    def __init__(self, inner, init_loss_scaling: float,
                 use_dynamic_loss_scaling: bool,
                 incr_every_n_steps: int, decr_every_n_nan_or_inf: int,
                 incr_ratio: float, decr_ratio: float):
        self._inner = inner
        self._dynamic = bool(use_dynamic_loss_scaling)
        self._init_scale = float(init_loss_scaling)
        self._incr_every_n = int(incr_every_n_steps)
        self._decr_every_n = int(decr_every_n_nan_or_inf)
        self._incr_ratio = float(incr_ratio)
        self._decr_ratio = float(decr_ratio)
        # set by the dynamic minimize: scope names of the state vars
        self.loss_scaling_name = None
        self.found_inf_name = None
        self.skip_count_name = None

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def backward(self, *args, **kwargs):
        return self._inner.backward(*args, **kwargs)

    def apply_gradients(self, params_grads):
        if self._dynamic:
            raise RuntimeError(
                "dynamic loss scaling wires scaling/unscale/skip ops "
                "around the whole backward — use minimize(), not a "
                "separate backward() + apply_gradients()")
        result = self._inner.apply_gradients(params_grads)
        default_main_program()._amp = True
        return result

    def minimize(self, loss, **kwargs):
        from paddle_tpu.dygraph import base as dy_base

        program = loss.block.program
        if not self._dynamic:
            result = self._inner.minimize(loss, **kwargs)
            program._amp = True
            return result
        if dy_base._in_dygraph_mode():
            raise NotImplementedError(
                "dynamic loss scaling is static-graph only (the state "
                "machine compiles into the step); use minimize() on a "
                "Program")
        return self._dynamic_minimize(loss, program, **kwargs)

    def _dynamic_minimize(self, loss, program, startup_program=None,
                          parameter_list=None, no_grad_set=None):
        from paddle_tpu import numerics, unique_name
        from paddle_tpu.layers import more as lmore
        from paddle_tpu.layers import nn, tensor

        program._amp = True
        block = program.global_block()
        scale_var = tensor.create_global_var(
            [1], self._init_scale, "float32", persistable=True,
            name=unique_name.generate("loss_scaling"))
        good_var = tensor.create_global_var(
            [1], 0.0, "float32", persistable=True,
            name=unique_name.generate("loss_scaling_good"))
        bad_var = tensor.create_global_var(
            [1], 0.0, "float32", persistable=True,
            name=unique_name.generate("loss_scaling_bad"))
        skips_var = tensor.create_global_var(
            [1], 0.0, "float32", persistable=True,
            name=unique_name.generate("loss_scaling_skips"))

        scaled_loss = nn.elementwise_mul(loss, block.var(scale_var.name))
        params_grads = self._inner.backward(
            scaled_loss, startup_program, parameter_list, no_grad_set)
        if any(getattr(g, "is_selected_rows", False)
               for _, g in params_grads if g is not None):
            raise NotImplementedError(
                "dynamic loss scaling with row-sparse gradients is not "
                "supported; use is_sparse=False embeddings")

        grads = [g for _, g in params_grads if g is not None]
        # ONE isfinite op over every gradient -> scalar all-finite flag
        fin = lmore.isfinite(grads)
        fin_f = nn.cast(fin, "float32")
        one = tensor.fill_constant([1], "float32", 1.0)
        not_fin = nn.elementwise_sub(one, fin_f)

        # unscale, and ZERO the whole gradient set on overflow (a plain
        # g/scale would turn inf into inf and poison clip/regularizer
        # arithmetic downstream). Divide DIRECTLY rather than multiply
        # by 1/scale: near the f32 ceiling the reciprocal is subnormal
        # and XLA's flush-to-zero would silently zero every gradient.
        new_pgs = []
        for p, g in params_grads:
            if g is None:
                new_pgs.append((p, None))
                continue
            clean = nn.where(
                fin, nn.elementwise_div(g, block.var(scale_var.name)),
                tensor.zeros_like(g))
            new_pgs.append((p, clean))

        # the state machine: grow after incr_every_n clean steps, shrink
        # after decr_every_n overflowing steps, counters reset on the
        # opposite outcome (and on their own firing)
        good1 = nn.elementwise_mul(
            nn.elementwise_add(good_var, one), fin_f)
        bad1 = nn.elementwise_mul(
            nn.elementwise_add(bad_var, one), not_fin)
        grow = nn.elementwise_mul(
            nn.cast(lmore.greater_equal(
                good1, tensor.fill_constant(
                    [1], "float32", float(self._incr_every_n))),
                "float32"),
            fin_f)
        shrink = nn.elementwise_mul(
            nn.cast(lmore.greater_equal(
                bad1, tensor.fill_constant(
                    [1], "float32", float(self._decr_every_n))),
                "float32"),
            not_fin)
        factor = nn.elementwise_mul(
            nn.elementwise_pow(
                tensor.fill_constant([1], "float32", self._incr_ratio),
                grow),
            nn.elementwise_pow(
                tensor.fill_constant([1], "float32", self._decr_ratio),
                shrink))
        # growth guard (reference: update_loss_scaling only grows while
        # the doubled scale is still finite): an unguarded scale
        # overflows f32 after enough clean growth steps, flags EVERY
        # later step as overflow, and freezes training silently
        cand = nn.elementwise_mul(block.var(scale_var.name), factor)
        tensor.assign(
            nn.where(lmore.isfinite(cand), cand,
                     block.var(scale_var.name)),
            output=block.var(scale_var.name))
        tensor.assign(
            nn.elementwise_mul(good1, nn.elementwise_sub(one, grow)),
            output=block.var(good_var.name))
        tensor.assign(
            nn.elementwise_mul(bad1, nn.elementwise_sub(one, shrink)),
            output=block.var(bad_var.name))
        # cumulative in-graph skip counter: exact even when the decode
        # is sampled or the step runs inside a compiled window (the
        # decoder emits the DELTA since its last decode)
        tensor.assign(
            nn.elementwise_add(block.var(skips_var.name), not_fin),
            output=block.var(skips_var.name))

        # numerics-plane aux: the (post-update) scale, this step's
        # overflow flag, and the cumulative skip count ride the single
        # stats bundle — the executor exports pt_amp_loss_scale /
        # pt_amp_overflow_skips_total
        numerics.register_aux(program, "amp_loss_scale", scale_var.name)
        numerics.register_aux(program, "amp_found_inf", not_fin.name)
        numerics.register_aux(program, "amp_overflow_skips",
                              skips_var.name)
        self.loss_scaling_name = scale_var.name
        self.found_inf_name = not_fin.name
        self.skip_count_name = skips_var.name
        program._amp_scale_vars = (scale_var.name, good_var.name,
                                   bad_var.name, not_fin.name)

        # skip path: gate every parameter's learning rate to 0 on an
        # overflow step (instance attr shadows the bound method only for
        # this one apply_gradients — the inner optimizer stays reusable)
        inner = self._inner
        orig_param_lr = inner._param_lr

        def _gated_lr(param):
            return nn.elementwise_mul(orig_param_lr(param), fin_f)

        inner._param_lr = _gated_lr
        try:
            opt_ops = inner.apply_gradients(new_pgs)
        finally:
            del inner.__dict__["_param_lr"]
        return opt_ops, new_pgs


def decorate(optimizer, amp_lists=None, init_loss_scaling=1.0,
             use_dynamic_loss_scaling: bool = False,
             incr_every_n_steps: int = 1000,
             decr_every_n_nan_or_inf: int = 1,
             incr_ratio: float = 2.0, decr_ratio: float = 0.5):
    """Wrap an optimizer so that minimize() marks the program for bf16
    mixed-precision execution; with ``use_dynamic_loss_scaling`` the
    in-graph dynamic loss-scaling state machine (grow/shrink/skip) is
    built around the backward too (see the module docstring)."""
    return AmpOptimizer(optimizer, init_loss_scaling,
                        use_dynamic_loss_scaling, incr_every_n_steps,
                        decr_every_n_nan_or_inf, incr_ratio, decr_ratio)


def enable_amp(program=None):
    """Directly mark a program for bf16 execution of MXU-heavy ops."""
    (program or default_main_program())._amp = True


def disable_amp(program=None):
    (program or default_main_program())._amp = False
