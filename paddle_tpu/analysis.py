"""Pre-compile static program verifier.

BENCH r01 measured a cold compile+first-step at 98.9 s — every bug that
survives to runtime costs two orders of magnitude more than one caught
before tracing. The reference framework bakes static checking into graph
construction (per-op ``InferShape`` on every ``Block.append_op``,
transpiler-time graph rewrites); TVM-style compiler stacks run
whole-program verification passes before codegen. This module is that
layer for the Program IR: a multi-pass verifier over
``Program``/``Block``/``Operator`` that rejects or warns on broken
programs in milliseconds, before the executor ever traces them.

Three entry points:

1. ``lint(program) -> List[Finding]`` — standalone whole-program run.
2. ``passes.apply_pass("lint", program)`` — the registered pass form.
3. Automatically in ``Executor.run``/``run_steps`` before the first
   compile of any (program, feeds, fetches) signature, gated by the
   ``static_lint`` flag (``off|warn|error``, default ``warn``). With the
   flag ``off`` the executor hot path costs one boolean read and
   allocates nothing here (same contract as monitor.py/numerics.py).

Checks — each its own pluggable pass over a shared def-use index
(``Program.def_use_index()``, cached per program version):

- **dataflow** — read-before-write / uninitialized non-persistable
  reads, fetch targets nothing produces, dead ops whose outputs never
  reach a fetch target or persistable state (the same backward
  reachability walk ``io._prune_for_inference`` uses to drop them),
  write-never-read persistables.
- **shapes** — re-runs ``Block._infer_shapes``-style abstract inference
  whole-program (through the shared ``framework.infer_op_outputs``) and
  flags ops whose declared output shapes/dtypes disagree with inferred
  ones; audits implicit f32 -> f16/bf16 downcasts outside an
  ``amp.decorate`` scope; reports inference-coverage gaps (ops with no
  registered kernel / missing metadata) as debug findings.
- **donation** — static twins of the executor's ``_drop_donated``
  runtime hygiene: a donated state input whose pre- and post-update
  values are both read in one step (the buffer behind the first read is
  gone), donated state aliased to multiple writers, feeds aliasing
  donated state.
- **sharding** — with a ``DistributedStrategy``: ops mixing arrays whose
  axis specs cannot unify without an unplanned reshard, flagged with the
  inferred resharding cost; strict-strategy rule misses.
- **collectives** — the static deadlock detector behind the stall
  watchdog: collectives under data-dependent control flow (``cond`` /
  ``while`` sub-blocks) whose per-rank emission may diverge, and — via
  ``check_collective_order([prog_rank0, prog_rank1, ...])`` — cross-rank
  comparison of per-rank collective emission order + participant sets.

Findings are metered (``pt_lint_findings_total{check=,severity=}``),
kept per program for ``debugger.pprint_program`` annotations and the
monitor server's ``/lint`` route, and pretty-printed by
``lint_report(program)``.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

from paddle_tpu import compile_cache as _ccache
from paddle_tpu import flags as _flags
from paddle_tpu import monitor as _monitor
from paddle_tpu.framework import (
    _BATCH_SENTINEL,
    Block,
    Operator,
    Program,
    infer_op_outputs,
)

_log = logging.getLogger("paddle_tpu")

SEVERITIES = ("debug", "info", "warning", "error")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}

_M_FINDINGS = _monitor.counter(
    "pt_lint_findings_total",
    "static-verifier findings, by check family and severity")
_M_RUNS = _monitor.counter(
    "pt_lint_runs_total",
    "whole-program static-verifier runs (executor pre-compile runs are "
    "cached per program fingerprint)")


class LintError(RuntimeError):
    """Raised under ``static_lint=error`` when a program has
    error-severity findings. ``.findings`` carries them."""

    def __init__(self, findings: List["Finding"]):
        self.findings = list(findings)
        head = "; ".join(str(f) for f in self.findings[:3])
        more = len(self.findings) - 3
        if more > 0:
            head += f"; ... {more} more"
        super().__init__(
            f"static lint found {len(self.findings)} error(s): {head} "
            f"(set flag static_lint='warn' to log instead of raise)")


@dataclasses.dataclass
class Finding:
    """One verifier finding: check family, severity, site, fix hint."""

    check: str                      # e.g. 'dataflow.uninitialized_read'
    severity: str                   # debug | info | warning | error
    message: str
    block_idx: int = 0
    op_idx: Optional[int] = None
    op_type: Optional[str] = None
    var: Optional[str] = None
    hint: Optional[str] = None
    cost_bytes: Optional[int] = None  # sharding: est. reshard traffic

    @property
    def site(self) -> str:
        parts = [f"block {self.block_idx}"]
        if self.op_idx is not None:
            parts.append(f"op [{self.op_idx}]"
                         + (f" {self.op_type}" if self.op_type else ""))
        if self.var is not None:
            parts.append(f"var '{self.var}'")
        return " ".join(parts)

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["site"] = self.site
        return d

    def __str__(self):
        s = f"[{self.severity}] {self.check} @ {self.site}: {self.message}"
        if self.cost_bytes is not None:
            s += f" (~{self.cost_bytes:,} B resharded)"
        if self.hint:
            s += f" — fix: {self.hint}"
        return s


# ---------------------------------------------------------------------------
# def-use index (the shared substrate every check walks)
# ---------------------------------------------------------------------------


def _op_attr_refs(block: Block, op: Operator):
    """(sub_blocks, attr-referenced var names) for one op.

    Control-flow ops reference env vars through attrs (``carry_names``,
    ``cond_name``, ``x_names``...) rather than input slots; treating
    those strings as reads keeps the dataflow checks conservative —
    an op whose attrs name a live var is never reported dead and its
    referenced vars are never reported unread."""
    subs: List[Block] = []
    refs: List[str] = []

    def add_sub(b):
        if not any(s is b for s in subs):  # cond may reuse one block
            subs.append(b)

    for val in op.attrs.values():
        if isinstance(val, Block):
            add_sub(val)
        elif isinstance(val, str):
            if block._find_var_recursive(val) is not None:
                refs.append(val)
        elif isinstance(val, (list, tuple)):
            for x in val:
                if isinstance(x, Block):
                    add_sub(x)
                elif isinstance(x, str) and \
                        block._find_var_recursive(x) is not None:
                    refs.append(x)
    return subs, refs


class DefUseIndex:
    """Writers/readers maps over one block's ops, program-order indexed.

    ``writers[name]`` / ``readers[name]`` list op indices in program
    order; ``first_write``/``first_read`` are the head elements.
    ``attr_reads[i]`` are var names op ``i`` references through attrs
    (control-flow carries); ``sub_blocks[i]`` its nested blocks."""

    def __init__(self, block: Block):
        self.block = block
        self.writers: Dict[str, List[int]] = {}
        self.readers: Dict[str, List[int]] = {}
        self.first_write: Dict[str, int] = {}
        self.first_read: Dict[str, int] = {}
        self.attr_reads: Dict[int, List[str]] = {}
        self.sub_blocks: Dict[int, List[Block]] = {}
        for idx, op in enumerate(block.ops):
            for n in op.input_arg_names:
                if not n:
                    continue
                self.readers.setdefault(n, []).append(idx)
                self.first_read.setdefault(n, idx)
            subs, refs = _op_attr_refs(block, op)
            if subs:
                self.sub_blocks[idx] = subs
            if refs:
                self.attr_reads[idx] = refs
                for n in refs:
                    self.readers.setdefault(n, []).append(idx)
                    self.first_read.setdefault(n, idx)
            for n in op.output_arg_names:
                if not n:
                    continue
                self.writers.setdefault(n, []).append(idx)
                self.first_write.setdefault(n, idx)

    def is_persistable(self, name: str) -> bool:
        v = self.block._find_var_recursive(name)
        return bool(v is not None and getattr(v, "persistable", False))


def build_def_use(program: Program) -> Dict[int, DefUseIndex]:
    """{block idx -> DefUseIndex}; call through
    ``Program.def_use_index()`` to get the version-keyed cached copy."""
    return {b.idx: DefUseIndex(b) for b in program.blocks}


@dataclasses.dataclass
class LintContext:
    """Everything one check pass needs, resolved once per lint run."""

    program: Program
    index: Dict[int, DefUseIndex]
    feed_names: Optional[frozenset]     # None = unknown (standalone run)
    fetch_names: Optional[Sequence[str]]
    strategy: Any                       # parallel.DistributedStrategy


# ---------------------------------------------------------------------------
# check registry (pluggable passes)
# ---------------------------------------------------------------------------

_CHECK_REGISTRY: "collections.OrderedDict[str, Callable]" = \
    collections.OrderedDict()


def register_check(name: str):
    """Decorator registering ``fn(ctx: LintContext) -> Iterable[Finding]``
    as a verifier pass (same shape as passes.register_pass)."""

    def deco(fn):
        if name in _CHECK_REGISTRY:
            raise ValueError(f"lint check '{name}' registered twice")
        _CHECK_REGISTRY[name] = fn
        return fn

    return deco


def registered_checks() -> List[str]:
    return list(_CHECK_REGISTRY)


# ---------------------------------------------------------------------------
# check: dataflow
# ---------------------------------------------------------------------------


@register_check("dataflow")
def _check_dataflow(ctx: LintContext) -> List[Finding]:
    block = ctx.program.global_block()
    idx = ctx.index[block.idx]
    feeds = ctx.feed_names
    out: List[Finding] = []

    for i, op in enumerate(block.ops):
        for n in op.input_arg_names:
            if not n or idx.is_persistable(n):
                continue  # scope state: initialized by startup program
            fw = idx.first_write.get(n)
            if fw is not None and fw < i:
                continue
            if feeds is not None:
                if n in feeds:
                    continue
            else:
                v = block._find_var_recursive(n)
                if fw is None and v is not None and v.shape is not None \
                        and v.dtype is not None:
                    continue  # declared input (layers.data feed candidate)
            if fw is None:
                out.append(Finding(
                    "dataflow.uninitialized_read", "error",
                    f"'{n}' is read but never written and is not a feed",
                    op_idx=i, op_type=op.type, var=n,
                    hint="feed it, write it in the startup program, or "
                         "mark it persistable"))
            else:
                out.append(Finding(
                    "dataflow.read_before_write", "error",
                    f"'{n}' is read before its first writer (op [{fw}])",
                    op_idx=i, op_type=op.type, var=n,
                    hint="reorder the ops or feed the initial value"))

    # fetch targets nothing can produce (the lowering env is
    # state-in ∪ feeds ∪ op outputs — see core/lowering.py run_block)
    fetch = list(ctx.fetch_names or ())
    produced = set(idx.writers)
    for n in fetch:
        if n in produced or (feeds is not None and n in feeds):
            continue
        if idx.is_persistable(n) and n in idx.readers:
            continue  # rides into the env as donated state
        if feeds is None:
            v = block._find_var_recursive(n)
            if v is not None and not v.persistable \
                    and v.shape is not None and v.dtype is not None \
                    and n not in idx.writers:
                continue  # declared input: same feed-candidate
                # heuristic the uninitialized-read check applies
        out.append(Finding(
            "dataflow.unreachable_fetch", "error",
            f"fetch target '{n}' is neither produced by an op, fed, nor "
            f"persistable state the program reads",
            var=n,
            hint="fetch a produced var, or add the producing op"))

    # dead ops: backward reachability from fetch targets — the walk
    # _inference_prune uses to drop them, with persistable writes and
    # control-flow ops kept as roots (state updates are step outputs)
    if fetch:
        needed = set(fetch)
        live = [False] * len(block.ops)
        for i in range(len(block.ops) - 1, -1, -1):
            op = block.ops[i]
            outs = op.output_arg_names
            rooted = (
                i in idx.sub_blocks
                or any(idx.is_persistable(n) for n in outs)
                or any(n in needed for n in outs)
            )
            if rooted:
                live[i] = True
                needed.update(n for n in op.input_arg_names if n)
                needed.update(idx.attr_reads.get(i, ()))
        for i, op in enumerate(block.ops):
            if not live[i]:
                out.append(Finding(
                    "dataflow.dead_op", "info",
                    f"outputs {op.output_arg_names} never reach a fetch "
                    f"target or persistable state",
                    op_idx=i, op_type=op.type,
                    hint="drop the op or fetch its output "
                         "(inference_prune would remove it)"))

    # write-never-read persistables (dead state updates)
    fetch_set = set(fetch)
    for n, ws in idx.writers.items():
        if not idx.is_persistable(n):
            continue
        if n in idx.readers or n in fetch_set:
            continue
        out.append(Finding(
            "dataflow.write_never_read", "info",
            f"persistable '{n}' is written but never read or fetched",
            op_idx=ws[0], op_type=block.ops[ws[0]].type, var=n,
            hint="dead state update — drop it or fetch the value"))
    return out


# ---------------------------------------------------------------------------
# check: shapes / dtypes
# ---------------------------------------------------------------------------

# (op type, attr key, input signature) -> (outs-by-slot sig, gap); the
# memo makes whole-program re-inference cheap on repeated-layer programs
# (a transformer re-infers each distinct layer shape once)
_EVAL_CACHE: Dict[tuple, tuple] = {}
_EVAL_CACHE_CAP = 4096

_FLOAT_NARROW = {"float16", "bfloat16"}


def _eval_key(block: Block, op: Operator):
    try:
        attrs = []
        for k, v in op.attrs.items():
            if isinstance(v, Block) or (
                    isinstance(v, (list, tuple))
                    and any(isinstance(x, Block) for x in v)):
                return None  # sub-block semantics: never memo
            attrs.append((k, repr(v)))
    except Exception:
        return None
    sig = []
    for slot, names in sorted(op.inputs.items()):
        for n in names:
            v = block._find_var_recursive(n)
            if v is None or v.shape is None or v.dtype is None:
                return None
            sig.append((slot, tuple(v.shape), v.dtype))
    return (op.type, tuple(sorted(attrs)), tuple(sig))


def _infer_cached(block: Block, op: Operator):
    key = _eval_key(block, op)
    if key is None:
        return infer_op_outputs(block, op)
    hit = _EVAL_CACHE.get(key)
    if hit is None:
        outs, gap = infer_op_outputs(block, op)
        sig = None
        if outs is not None:
            try:
                sig = {
                    slot: [None if r is None
                           else (tuple(r.shape), np.dtype(r.dtype).name)
                           for r in rs]
                    for slot, rs in outs.items()
                }
            except Exception as e:  # malformed kernel result structure
                sig, gap = None, f"eval_failed:{type(e).__name__}: {e}"
        if len(_EVAL_CACHE) >= _EVAL_CACHE_CAP:
            _EVAL_CACHE.clear()
        _EVAL_CACHE[key] = hit = (sig, gap)
    sig, gap = hit
    if sig is None:
        return None, gap
    # rehydrate the memoized signature into ShapeDtypeStruct-likes
    outs = {
        slot: [None if r is None else _Sds(r[0], r[1]) for r in rs]
        for slot, rs in sig.items()
    }
    return outs, None


class _Sds:
    """Tiny (shape, dtype) record mirroring jax.ShapeDtypeStruct for the
    memoized path (no jax import needed to rehydrate)."""

    __slots__ = ("shape", "dtype")

    def __init__(self, shape, dtype):
        self.shape = shape
        self.dtype = dtype


@register_check("shapes")
def _check_shapes(ctx: LintContext) -> List[Finding]:
    block = ctx.program.global_block()
    amp = bool(getattr(ctx.program, "_amp", False))
    out: List[Finding] = []
    for i, op in enumerate(block.ops):
        outs, gap = _infer_cached(block, op)
        if outs is None:
            # coverage honesty: the build-time _infer_shapes silently
            # fell through here before; now it is one debug finding
            out.append(Finding(
                "shapes.no_inference", "debug",
                f"shape inference unavailable ({gap})",
                op_idx=i, op_type=op.type,
                hint="register a kernel / declare input metadata so the "
                     "verifier can cover this op"))
            continue
        try:
            for slot, names in op.outputs.items():
                results = outs.get(slot, [])
                for n, r in zip(names, results):
                    if r is None:
                        continue
                    v = block._find_var_recursive(n)
                    if v is None or v.shape is None or v.dtype is None:
                        continue
                    inferred = tuple(
                        -1 if d == _BATCH_SENTINEL else int(d)
                        for d in r.shape)
                    if tuple(v.shape) != inferred:
                        out.append(Finding(
                            "shapes.shape_mismatch", "warning",
                            f"declared shape {list(v.shape)} disagrees "
                            f"with inferred {list(inferred)}",
                            op_idx=i, op_type=op.type, var=n,
                            hint="the program desc was edited or a pass "
                                 "rewrote the op without re-inferring; "
                                 "fix the producer or re-run shape "
                                 "inference"))
                    idt = np.dtype(r.dtype).name
                    if v.dtype != idt:
                        out.append(Finding(
                            "shapes.dtype_mismatch", "warning",
                            f"declared dtype {v.dtype} disagrees with "
                            f"inferred {idt}",
                            op_idx=i, op_type=op.type, var=n,
                            hint="align the declared dtype with the "
                                 "kernel or insert an explicit cast"))
        except Exception as e:
            # a kernel returning a malformed result structure is a
            # coverage gap for THIS op, never an abort of the whole run
            out.append(Finding(
                "shapes.no_inference", "debug",
                f"shape inference unavailable (malformed kernel "
                f"result: {type(e).__name__}: {e})",
                op_idx=i, op_type=op.type,
                hint="fix the kernel's output structure (slot -> list "
                     "of results)"))
            continue

        # implicit-downcast audit: f32 in, f16/bf16 out, outside an
        # amp.decorate scope, from an op that did not explicitly ask
        # for it (cast, or a dtype attr)
        if amp or op.type == "cast" or "dtype" in op.attrs:
            continue
        in_dtypes = set()
        for n in op.input_arg_names:
            v = block._find_var_recursive(n)
            if v is not None and v.dtype is not None:
                in_dtypes.add(v.dtype)
        for n in op.output_arg_names:
            v = block._find_var_recursive(n)
            if v is None or v.dtype is None:
                continue
            if v.dtype in _FLOAT_NARROW and "float32" in in_dtypes:
                out.append(Finding(
                    "shapes.implicit_downcast", "warning",
                    f"f32 input narrowed to {v.dtype} outside an "
                    f"amp.decorate scope",
                    op_idx=i, op_type=op.type, var=n,
                    hint="wrap the build in amp.decorate / apply the "
                         "'amp' pass, or cast explicitly"))
    return out


# ---------------------------------------------------------------------------
# check: donation / aliasing
# ---------------------------------------------------------------------------


@register_check("donation")
def _check_donation(ctx: LintContext) -> List[Finding]:
    block = ctx.program.global_block()
    idx = ctx.index[block.idx]
    feeds = ctx.feed_names or frozenset()
    out: List[Finding] = []

    from paddle_tpu.core.lowering import analyze_state

    state_in, _ = analyze_state(block, feeds)
    for n in state_in:
        ws = idx.writers.get(n, [])
        if len(ws) > 1:
            out.append(Finding(
                "donation.multi_writer", "warning",
                f"donated state '{n}' has {len(ws)} writers "
                f"(ops {ws}); the donated buffer is aliased to multiple "
                f"updates in one step",
                op_idx=ws[1], op_type=block.ops[ws[1]].type, var=n,
                hint="merge the updates into one op or stage the "
                     "intermediate through a non-persistable temp"))
        if not ws:
            continue
        w0 = ws[0]
        before = [i for i in idx.readers.get(n, []) if i < w0]
        after = [i for i in idx.readers.get(n, []) if i > w0]
        if before and after:
            # one step observing two versions of a donated buffer: the
            # buffer behind the pre-update read was donated to the
            # writer — the static twin of _drop_donated's runtime
            # "deleted donated array" failure
            out.append(Finding(
                "donation.read_after_donate", "warning",
                f"donated input '{n}' is read (op [{before[0]}]) before "
                f"and re-read (op [{after[0]}]) after its overwrite "
                f"(op [{w0}]); the re-read observes the updated value, "
                f"not the donated original",
                op_idx=after[0], op_type=block.ops[after[0]].type, var=n,
                hint="move the read before the update, or snapshot the "
                     "pre-update value into a temp and read that"))

    for n in sorted(feeds):
        if idx.is_persistable(n):
            out.append(Finding(
                "donation.feed_aliases_state", "warning",
                f"feed '{n}' aliases persistable state: the executor "
                f"both donates the scope buffer and binds the feed, so "
                f"one of them silently wins",
                var=n,
                hint="rename the feed or drop the persistable flag"))
    return out


# ---------------------------------------------------------------------------
# check: sharding / mesh consistency
# ---------------------------------------------------------------------------

# ops whose single X input's spec flows through unchanged
_UNARY_PRESERVE = frozenset({
    "scale", "relu", "sigmoid", "tanh", "exp", "log", "sqrt", "square",
    "abs", "gelu", "softmax", "log_softmax", "dropout", "cast",
})
_ELEMENTWISE = frozenset({
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow",
})


def _normspec(p, rank: int):
    """PartitionSpec -> per-dim tuple-of-axis-names, padded to rank."""
    entries = list(p) if p is not None else []
    dims = []
    for e in entries:
        if e is None:
            dims.append(())
        elif isinstance(e, (tuple, list)):
            dims.append(tuple(e))
        else:
            dims.append((e,))
    while len(dims) < rank:
        dims.append(())
    return tuple(dims[:rank])


def _var_bytes(v) -> int:
    if v is None or v.shape is None:
        return 0
    n = 1
    for d in v.shape:
        n *= max(int(d), 1)  # -1 batch dim counted as one sample
    try:
        return n * np.dtype(v.dtype or "float32").itemsize
    except TypeError:
        return n * 4


def _reshard_cost(v, axes, mesh) -> int:
    """Estimated all-gather traffic (bytes) to undo sharding ``axes``
    of ``v`` on ``mesh`` — (s-1)/s of the global array crosses links."""
    from paddle_tpu.parallel.mesh import axis_size

    try:
        s = axis_size(mesh, tuple(axes))
    except Exception:
        s = 2
    b = _var_bytes(v)
    return int(b * (s - 1) / s) if s > 1 else b


@register_check("sharding")
def _check_sharding(ctx: LintContext) -> List[Finding]:
    st = ctx.strategy
    if st is None:
        return []
    block = ctx.program.global_block()
    out: List[Finding] = []
    specs: Dict[str, tuple] = {}

    def var_of(n):
        return block._find_var_recursive(n)

    # seed: persistables from the strategy rules, feeds from the batch
    # sharding; everything else propagates (or stays unknown)
    for b in ctx.program.blocks:
        for name, v in b.vars.items():
            if not v.persistable or v.shape is None:
                continue
            try:
                p = st.spec_for(name)
            except ValueError as e:
                out.append(Finding(
                    "sharding.unmatched_rule", "error", str(e), var=name,
                    hint="add a rule (PartitionSpec() for replicated)"))
                continue
            specs[name] = _normspec(p, len(v.shape))
    batch_axes = tuple(
        a for a in (getattr(st, "slice_axis", None),
                    getattr(st, "data_axis", None)) if a)
    for n in (ctx.feed_names or ()):
        v = var_of(n)
        if v is not None and v.shape is not None and len(v.shape) >= 1:
            specs[n] = ((batch_axes,) if batch_axes else ((),)) + \
                ((),) * (len(v.shape) - 1)

    def unify(i, op, pairs):
        """dim-aligned (name_a, dim_a, name_b, dim_b) unification; a
        conflict emits one finding and wins arbitrarily."""
        for (na, da, nb, db) in pairs:
            sa, sb = specs.get(na), specs.get(nb)
            if sa is None or sb is None:
                continue
            if da >= len(sa) or db >= len(sb):
                continue
            a, b = sa[da], sb[db]
            if a and b and a != b:
                va, vb = var_of(na), var_of(nb)
                victim, axes = (
                    (va, a) if _var_bytes(va) <= _var_bytes(vb)
                    else (vb, b))
                out.append(Finding(
                    "sharding.unresolvable_mix", "warning",
                    f"'{na}' dim {da} is sharded over {list(a)} but "
                    f"'{nb}' dim {db} over {list(b)}; GSPMD must "
                    f"reshard one of them",
                    op_idx=i, op_type=op.type, var=na,
                    cost_bytes=_reshard_cost(victim, axes, st.mesh),
                    hint="align the sharding rules of the two operands "
                         "(or accept the reshard and silence with a "
                         "matching rule)"))

    for i, op in enumerate(block.ops):
        t = op.type
        ins = op.input_arg_names
        if t in _UNARY_PRESERVE and ins:
            s = specs.get(ins[0])
            if s is not None:
                for n in op.output_arg_names:
                    v = var_of(n)
                    if v is not None and v.shape is not None \
                            and len(v.shape) == len(s):
                        specs[n] = s
        elif t in _ELEMENTWISE:
            xs = op.inputs.get("X", [])
            ys = op.inputs.get("Y", [])
            if xs and ys:
                x, y = xs[0], ys[0]
                vx, vy = var_of(x), var_of(y)
                if vx is not None and vy is not None and \
                        vx.shape is not None and vy.shape is not None:
                    rx, ry = len(vx.shape), len(vy.shape)
                    axis = int(op.attrs.get("axis", -1))
                    off = rx - ry if axis == -1 else axis
                    sx, sy = specs.get(x), specs.get(y)
                    if 0 <= off <= rx - ry and sx is not None \
                            and sy is not None:
                        unify(i, op, [(x, off + d, y, d)
                                      for d in range(ry)])
                        # joint spec: per-dim union of the two operands.
                        # A mesh axis claimed by DIFFERENT dims of the
                        # union cannot shard both at once — the operands
                        # can only meet through a reshard even though no
                        # single dim conflicts outright.
                        merged = list(sx)
                        for d in range(ry):
                            if not merged[off + d]:
                                merged[off + d] = sy[d]
                        used: Dict[str, int] = {}
                        collide = None
                        for d, axes in enumerate(merged):
                            for a in axes:
                                if a in used and used[a] != d:
                                    collide = (a, used[a], d)
                                used.setdefault(a, d)
                        if collide is not None:
                            a, d0, d1 = collide
                            victim = (vx if _var_bytes(vx)
                                      <= _var_bytes(vy) else vy)
                            out.append(Finding(
                                "sharding.unresolvable_mix", "warning",
                                f"'{x}' and '{y}' jointly claim mesh "
                                f"axis '{a}' for dims {d0} and {d1}; "
                                f"one axis cannot shard both dims, so "
                                f"GSPMD must reshard an operand",
                                op_idx=i, op_type=op.type, var=x,
                                cost_bytes=_reshard_cost(
                                    victim, (a,), st.mesh),
                                hint="align the two operands' sharding "
                                     "rules on one layout"))
                        else:
                            for n in op.output_arg_names:
                                v = var_of(n)
                                if v is not None and v.shape is not None \
                                        and len(v.shape) == rx:
                                    specs[n] = tuple(merged)
        elif t in ("mul", "matmul", "fc"):
            xn = (op.inputs.get("X") or op.inputs.get("Input") or [None])[0]
            yn = (op.inputs.get("Y") or op.inputs.get("W") or [None])[0]
            if xn is None or yn is None:
                continue
            if t == "matmul" and (op.attrs.get("transpose_x")
                                  or op.attrs.get("transpose_y")):
                continue  # transposed contractions: stay conservative
            vx, vy = var_of(xn), var_of(yn)
            if vx is None or vy is None or vx.shape is None \
                    or vy.shape is None or len(vy.shape) != 2:
                continue
            rx = len(vx.shape)
            # contraction: X's trailing dim against Y's dim 0 — both
            # sharded on the same axis is the PLANNED row-parallel
            # matmul (GSPMD inserts the all-reduce); a mismatch is an
            # unplanned reshard
            unify(i, op, [(xn, rx - 1, yn, 0)])
            sx, sy = specs.get(xn), specs.get(yn)
            if sx is not None and sy is not None:
                for n in op.output_arg_names:
                    v = var_of(n)
                    if v is not None and v.shape is not None \
                            and len(v.shape) >= 2:
                        ro = len(v.shape)
                        specs[n] = tuple(
                            sx[d] if d < ro - 1 and d < len(sx) else
                            (sy[1] if d == ro - 1 else ())
                            for d in range(ro))
            if t == "fc":
                bn = (op.inputs.get("Bias") or [None])[0]
                if bn is not None:
                    unify(i, op, [(yn, 1, bn, 0)])
        # every other op type: outputs stay unknown (conservative)
    return out


# ---------------------------------------------------------------------------
# check: collective order
# ---------------------------------------------------------------------------


def _collective_kind(op: Operator, strategy):
    """(kind, axis) when the op lowers to a cross-rank collective under
    ``strategy``, else None. Strategy-aware by design: the same sdpa op
    is a dense kernel without a context axis and a ring collective with
    one."""
    if strategy is None:
        return None
    if op.type == "scaled_dot_product_attention" and \
            getattr(strategy, "context_axis", None):
        return ("ring_attention", strategy.context_axis)
    if op.type == "switch_moe" and getattr(strategy, "expert_axis", None):
        return ("all_to_all", strategy.expert_axis)
    if op.type == "scan" and op.attrs.get("pipelinable", False) and \
            getattr(strategy, "pipe_axis", None):
        return ("gpipe", strategy.pipe_axis)
    if op.type == "lookup_table" and \
            op.attrs.get("is_distributed", False) and \
            getattr(strategy, "table_axis", None):
        return ("sharded_table", strategy.table_axis)
    return None


def collective_signature(program: Program, strategy=None) -> List[Dict]:
    """Ordered list of the collectives this program emits under
    ``strategy``: one dict per collective with kind, op, axis and
    participant count — the per-rank sequence ``check_collective_order``
    compares. Participant sets come from the parallel modules' spec
    extraction (ring_attention/pipeline ``collective_signature``)."""
    sig: List[Dict] = []

    def walk(block: Block):
        for i, op in enumerate(block.ops):
            kind = _collective_kind(op, strategy)
            if kind is not None:
                kname, axis = kind
                entry: Dict[str, Any] = {
                    "kind": kname, "op": op.type, "axis": axis,
                    "block": block.idx, "op_idx": i,
                }
                mesh = getattr(strategy, "mesh", None)
                if mesh is not None:
                    try:
                        from paddle_tpu.parallel.mesh import axis_sizes

                        # per-rank mesh shape rides the signature: two
                        # ranks building different meshes IS a
                        # participant-set divergence
                        entry["mesh"] = axis_sizes(mesh)
                        if kname == "ring_attention":
                            from paddle_tpu.parallel import (
                                ring_attention as _ra,
                            )

                            entry.update(_ra.collective_signature(
                                mesh, axis))
                        elif kname == "gpipe":
                            from paddle_tpu.parallel import (
                                pipeline as _pp,
                            )

                            entry.update(_pp.collective_signature(
                                mesh, axis,
                                getattr(strategy, "pipe_micro", None)))
                        else:
                            from paddle_tpu.parallel.mesh import axis_size

                            entry["participants"] = axis_size(mesh, axis)
                    except Exception:
                        pass
                sig.append(entry)
            for sub in _op_attr_refs(block, op)[0]:
                walk(sub)

    walk(program.global_block())
    return sig


def check_collective_order(programs: Sequence[Program],
                           strategy=None) -> List[Finding]:
    """Cross-rank lint: compare per-rank collective emission order and
    participant sets; any divergence is a static deadlock (rank A waits
    in collective #k while rank B entered a different one — the hang
    the stall watchdog can only report at runtime). ``strategy`` may be
    one shared strategy or a per-rank sequence."""
    strategies = (list(strategy)
                  if isinstance(strategy, (list, tuple))
                  else [strategy] * len(programs))
    if len(strategies) != len(programs):
        raise ValueError(
            f"check_collective_order: {len(programs)} programs but "
            f"{len(strategies)} strategies — pass one shared strategy "
            f"or exactly one per rank")
    sigs = [collective_signature(p, s)
            for p, s in zip(programs, strategies)]
    out: List[Finding] = []
    base = sigs[0] if sigs else []

    def _key(e):
        # everything except the site (block/op_idx): two ranks may
        # interleave non-collective ops differently and still agree;
        # schedule shape (ticks/rotations/mesh) must match exactly —
        # e.g. differing pipe_micro means differing ppermute hop counts
        return tuple(sorted(
            (k, tuple(sorted(v.items())) if isinstance(v, dict) else v)
            for k, v in e.items() if k not in ("block", "op_idx")))

    for r, sig in enumerate(sigs[1:], 1):
        if len(sig) != len(base):
            out.append(Finding(
                "collectives.count_divergence", "error",
                f"rank 0 emits {len(base)} collectives but rank {r} "
                f"emits {len(sig)}; the shorter rank deadlocks the "
                f"longer one",
                hint="make every rank trace the identical collective "
                     "sequence (same model config, same strategy axes)"))
            continue
        for k, (a, b) in enumerate(zip(base, sig)):
            if _key(a) != _key(b):
                out.append(Finding(
                    "collectives.order_divergence", "error",
                    f"collective #{k} diverges between rank 0 "
                    f"({_key(a)}) and rank {r} ({_key(b)}); mismatched "
                    f"emission order or participant sets deadlock "
                    f"across ranks",
                    op_idx=a.get("op_idx"), op_type=a.get("op"),
                    hint="align the per-rank programs (same op order, "
                         "same axis specs) before dispatch"))
                break
    return out


@register_check("collectives")
def _check_collectives(ctx: LintContext) -> List[Finding]:
    """Single-program half of the collective-order check: collectives
    under data-dependent control flow (``cond`` branches, ``while``
    trip counts) can fire on some ranks and not others."""
    if ctx.strategy is None:
        return []
    block = ctx.program.global_block()
    idx = ctx.index[block.idx]
    out: List[Finding] = []

    def scan_sub(block_, top_idx, top_type):
        for op in block_.ops:
            kind = _collective_kind(op, ctx.strategy)
            if kind is not None:
                out.append(Finding(
                    "collectives.control_flow", "warning",
                    f"collective '{op.type}' ({kind[0]} over "
                    f"'{kind[1]}') sits inside a data-dependent "
                    f"'{top_type}' body; ranks whose condition "
                    f"diverges deadlock the rest",
                    op_idx=top_idx, op_type=top_type,
                    hint="hoist the collective out of the conditional "
                         "or make the condition provably rank-invariant"))
            for sub in _op_attr_refs(block_, op)[0]:
                scan_sub(sub, top_idx, top_type)

    for i, subs in idx.sub_blocks.items():
        top = block.ops[i]
        if top.type not in ("cond", "while"):
            continue  # bounded_while/scan run every rank in lockstep
        for sub in subs:
            scan_sub(sub, i, top.type)
    return out


# ---------------------------------------------------------------------------
# lint driver + latest-findings store
# ---------------------------------------------------------------------------

# program uid -> latest lint record (bounded; debugger + /lint route)
_LATEST: "collections.OrderedDict[int, Dict]" = collections.OrderedDict()
_LATEST_CAP = 64


def lint(program: Program,
         feeds: Optional[Iterable[str]] = None,
         fetches: Optional[Iterable[str]] = None,
         strategy=None,
         checks: Optional[Sequence[str]] = None,
         min_severity: str = "warning") -> List[Finding]:
    """Run the verifier over ``program`` and return findings at or above
    ``min_severity`` (default 'warning'; pass 'debug' for the full set
    including coverage notes). ``feeds``/``fetches`` sharpen the
    dataflow checks (the executor provides them; standalone runs may
    omit them), ``strategy`` enables the sharding + collective checks,
    ``checks`` selects a subset of ``registered_checks()``."""
    if min_severity not in _SEV_RANK:
        raise ValueError(
            f"min_severity '{min_severity}' not in {SEVERITIES}")
    t0 = time.perf_counter()
    ctx = LintContext(
        program=program,
        index=program.def_use_index(),
        feed_names=(frozenset(feeds) if feeds is not None else None),
        fetch_names=(list(fetches) if fetches is not None else None),
        strategy=strategy,
    )
    findings: List[Finding] = []
    for name in (checks if checks is not None else registered_checks()):
        if name not in _CHECK_REGISTRY:
            raise KeyError(
                f"unknown lint check '{name}'; "
                f"registered: {registered_checks()}")
        findings.extend(_CHECK_REGISTRY[name](ctx))
    findings.sort(key=lambda f: (-_SEV_RANK[f.severity],
                                 f.block_idx,
                                 f.op_idx if f.op_idx is not None else -1))
    ms = (time.perf_counter() - t0) * 1e3
    _M_RUNS.inc()
    for f in findings:
        _M_FINDINGS.inc(labels={"check": f.check.split(".", 1)[0],
                                "severity": f.severity})
    _LATEST[program._uid] = {
        "v": 1,
        "program": f"program{program._uid}",
        "version": program.version,
        "lint_ms": ms,
        "counts": _counts(findings),
        "findings": [f.to_dict() for f in findings],
    }
    while len(_LATEST) > _LATEST_CAP:
        _LATEST.popitem(last=False)
    cut = _SEV_RANK[min_severity]
    return [f for f in findings if _SEV_RANK[f.severity] >= cut]


def _counts(findings: List[Finding]) -> Dict[str, int]:
    c: Dict[str, int] = {}
    for f in findings:
        c[f.severity] = c.get(f.severity, 0) + 1
    return c


def format_counts(counts: Dict[str, int]) -> str:
    """'2 error, 1 warning' (most severe first), or 'clean' — the one
    header formatter lint_report and debugger._lint_lines share."""
    return ", ".join(f"{counts[s]} {s}" for s in reversed(SEVERITIES)
                     if s in counts) or "clean"


def findings_for(program_uid: int) -> Optional[Dict]:
    """The latest lint record for a program uid (debugger annotations,
    /lint route), or None when the program was never linted."""
    return _LATEST.get(program_uid)


def summary() -> Dict[str, Any]:
    """JSON-ready view for the monitor server's ``/lint`` route."""
    return {"mode": _mode, "reports": dict(_LATEST)}


def lint_report(program: Program, findings: Optional[List[Finding]] = None,
                **kw) -> str:
    """Human-readable lint report: severity counts header + one line per
    finding (site, message, fix hint). With ``findings=None`` the
    verifier runs fresh at full verbosity (kwargs forwarded to
    ``lint``); ``debugger.pprint_program`` embeds the stored latest
    record instead of re-running."""
    if findings is None:
        kw.setdefault("min_severity", "debug")
        findings = lint(program, **kw)
    lines = [f"static lint ({len(program.global_block().ops)} ops, "
             f"checks: {','.join(registered_checks())}): "
             f"{format_counts(_counts(findings))}"]
    lines += [f"  {f}" for f in findings]
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# executor / build-site integration (the static_lint flag plane)
# ---------------------------------------------------------------------------

_mode = "warn"


def _sync_mode(_value=None):
    global _mode
    v = str(_flags.get_flag("static_lint")).strip().lower()
    if v not in ("off", "warn", "error"):
        _log.warning(
            "static_lint=%r is not one of off|warn|error; using 'warn'",
            v)
        v = "warn"
    if v != _mode:
        # a mode flip changes dispatch semantics (warn logs, error
        # raises): fingerprints linted under the old mode must re-lint,
        # or warn->error would wave known-broken programs through
        _SEEN.clear()
    _mode = v


_flags.watch_flag("static_lint", _sync_mode)


def lint_mode() -> str:
    return _mode


def lint_active() -> bool:
    """One boolean read — the executor's zero-allocation gate."""
    return _mode != "off"


# Canonical (compile_cache.program_fingerprint) signatures already
# linted pre-compile: a recompile of the same signature never re-lints.
# Content-keyed like the executor/compile caches — two identically-built
# programs share one lint run.
_SEEN: "collections.OrderedDict[str, bool]" = collections.OrderedDict()
_SEEN_CAP = 512


def _dispatch(findings: List[Finding], site: str):
    worst = [f for f in findings if f.severity in ("warning", "error")]
    for f in worst:
        _log.warning("static lint [%s]: %s", site, f)
    if _mode == "error":
        errs = [f for f in findings if f.severity == "error"]
        if errs:
            raise LintError(errs)


def _strategy_token(strategy) -> tuple:
    """Content fingerprint of a DistributedStrategy — THE canonical one
    (compile_cache.strategy_token), shared with the executor cache key
    and the persistent compile cache so the three subsystems can never
    drift. id() would alias a fresh strategy to a GC-reused address (the
    same hazard executor._latest_stacked pins references against);
    content keying also lets two equal strategies share one lint run."""
    return _ccache.strategy_token(strategy)


def lint_before_compile(program: Program,
                        feed_names: Sequence[str],
                        fetch_names: Sequence[str],
                        strategy=None,
                        site: str = "executor"):
    """Executor hook: verify once per (program, feeds, fetches,
    strategy) fingerprint, right before the first compile of that
    signature. Logs warning/error findings; raises LintError under
    ``static_lint=error``. Callers must gate on ``lint_active()``."""
    key = _ccache.fingerprint_for(
        ("lint", program._uid, program.version, tuple(feed_names),
         tuple(fetch_names), _strategy_token(strategy)),
        program, strategy=strategy, feed_sig=tuple(feed_names),
        fetch_names=fetch_names, extra=("lint",))
    if key in _SEEN:
        return
    findings = lint(program, feeds=feed_names, fetches=fetch_names,
                    strategy=strategy, min_severity="debug")
    # dispatch BEFORE caching the fingerprint: under static_lint=error a
    # raising dispatch must re-lint (and re-raise) on the next call, not
    # wave the broken program through to the compiler
    _dispatch(findings, site)
    _SEEN[key] = True
    while len(_SEEN) > _SEEN_CAP:
        _SEEN.popitem(last=False)


def lint_at_build(program: Program, strategy=None,
                  checks: Optional[Sequence[str]] = None,
                  site: str = "build"):
    """Build-site hook (CompiledProgram.with_strategy, contrib.Trainer):
    verify the freshly built program without feed/fetch context. Gated
    on ``lint_active()`` internally — call sites stay one-liners."""
    if not lint_active():
        return
    key = _ccache.fingerprint_for(
        ("lint-build", program._uid, program.version, site,
         _strategy_token(strategy)),
        program, strategy=strategy, extra=("lint-build", site))
    if key in _SEEN:
        return
    findings = lint(program, strategy=strategy, checks=checks,
                    min_severity="debug")
    _dispatch(findings, site)  # before caching — see lint_before_compile
    _SEEN[key] = True
    while len(_SEEN) > _SEEN_CAP:
        _SEEN.popitem(last=False)
