"""Graph-time autodiff: append_backward.

Same contract as the reference (reference: python/paddle/fluid/backward.py:394):
walk the op list backwards from the loss, append ``<type>_grad`` ops into the
program, de-duplicate repeated gradients with ``sum`` ops (reference:
backward.py:135 ``_addup_repetitive_outputs_``), prune non-contributing ops
(reference: backward.py:579 ``_find_op_path_``). Unlike the reference there
are no per-op C++ GradOpDescMakers: the grad op descs follow the uniform
convention of core/autodiff.py and their kernels are derived with jax.vjp.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from paddle_tpu.core.autodiff import GRAD_SLOT_PREFIX
from paddle_tpu.core.lowering import resolve_op_def
from paddle_tpu.core.registry import GRAD_OP_SUFFIX
from paddle_tpu.framework import Block, Parameter, Variable, grad_var_name


def _is_float_var(block: Block, name: str) -> bool:
    v = block._find_var_recursive(name)
    if v is None or v.dtype is None:
        return True
    return np.issubdtype(np.dtype(v.dtype), np.floating)


def _find_op_path(block: Block, loss: Variable) -> List[int]:
    """Indices of ops contributing to the loss, in forward order."""
    needed: Set[str] = {loss.name}
    marked: List[int] = []
    for idx in range(len(block.ops) - 1, -1, -1):
        op = block.ops[idx]
        if any(n in needed for n in op.output_arg_names):
            marked.append(idx)
            needed.update(n for n in op.input_arg_names if n)
    marked.reverse()
    return marked


def append_backward(
    loss: Variable,
    parameter_list: Optional[Sequence[str]] = None,
    no_grad_set: Optional[Set[str]] = None,
    callbacks=None,
) -> List[Tuple[Parameter, Variable]]:
    block = loss.block
    program = block.program
    no_grad = set(no_grad_set or ())

    op_path = _find_op_path(block, loss)

    # Track gradient producers: target grad name -> list of written names.
    producers: Dict[str, List[str]] = defaultdict(list)
    finalized: Set[str] = set()

    def provide(var_name: str) -> str:
        g = grad_var_name(var_name)
        k = len(producers[g])
        name = g if k == 0 else f"{g}@RENAME@{k}"
        producers[g].append(name)
        return name

    def lookup(var_name: str) -> Optional[str]:
        g = grad_var_name(var_name)
        lst = producers.get(g)
        if not lst:
            return None
        if len(lst) > 1 and g not in finalized:
            # A row-sparse marker among the partials cannot be summed with
            # dense partials (its array is never materialized). Catches the
            # ordering the sparse grad maker's own @RENAME check misses —
            # the sparse lookup claiming the clean name first.
            for n in lst:
                v = block._find_var_recursive(n)
                if v is not None and getattr(v, "is_selected_rows", False):
                    raise ValueError(
                        f"parameter '{var_name}' has both a row-sparse "
                        f"gradient (is_sparse=True lookup) and other dense "
                        f"gradient contributions; they cannot be combined. "
                        f"Use is_sparse=False for this table."
                    )
            # Combine partial gradients (reference: backward.py:135).
            block.create_var(name=g, dtype=_var_dtype(var_name))
            block.append_op("sum", inputs={"X": list(lst)}, outputs={"Out": g})
            finalized.add(g)
        return g

    def _var_dtype(name: str):
        v = block._find_var_recursive(name)
        return v.dtype if v is not None else "float32"

    def should_skip(name: str, slot: str, opdef) -> bool:
        if not name or name in no_grad:
            return True
        v = block._find_var_recursive(name)
        if v is not None and v.stop_gradient:
            return True
        if opdef.diff_inputs is not None and slot not in opdef.diff_inputs:
            return True
        return not _is_float_var(block, name)

    # Seed: d(loss)/d(loss) = 1.
    loss_grad = grad_var_name(loss.name)
    block.create_var(
        name=loss_grad, shape=loss.shape, dtype=loss.dtype, persistable=False
    )
    block.append_op(
        "fill_any_like",
        inputs={"X": loss},
        outputs={"Out": loss_grad},
        attrs={"value": 1.0},
    )
    producers[loss_grad].append(loss_grad)
    finalized.add(loss_grad)

    for idx in reversed(op_path):
        op = block.ops[idx]
        opdef = resolve_op_def(op.type)
        if opdef.no_grad:
            if op.type == "while" and any(
                lookup(n)
                for names in op.outputs.values() for n in names if n
            ):
                raise RuntimeError(
                    "Cannot backprop through a data-dependent `while` "
                    "loop: XLA's While is not reverse-differentiable, so "
                    "its gradient would be silently dropped. Either (a) "
                    "give the loop an iteration bound — "
                    "While(cond, max_trip_count=N) lowers to a "
                    "differentiable fixed-trip scan with dead iterations "
                    "masked — or (b) rewrite the recurrence with "
                    "layers.StaticRNN / the scan op, the differentiable "
                    "loop primitives. (The reference trains through "
                    "while_op via WhileGradOp, "
                    "operators/controlflow/while_op.cc:43; "
                    "bounded_while is the TPU-native equivalent.)"
                )
            continue

        out_grads: Dict[str, List[str]] = {}
        any_grad = False
        for slot, names in op.outputs.items():
            gs = []
            for n in names:
                g = lookup(n) if n else None
                gs.append(g or "")
                any_grad = any_grad or bool(g)
            out_grads[slot] = gs
        if not any_grad:
            continue

        if opdef.grad_maker is not None:
            descs = opdef.grad_maker(op, block, out_grads, provide, should_skip)
            if descs is not None:  # None = defer to the generic emitter
                for d in descs:
                    block.append_op(**d)
                continue

        g_inputs = dict(op.inputs)
        for slot, names in op.outputs.items():
            g_inputs.setdefault(slot, names)
        for slot, gs in out_grads.items():
            g_inputs[GRAD_SLOT_PREFIX + slot] = gs

        g_outputs: Dict[str, List[str]] = {}
        emitted = False
        for slot, names in op.inputs.items():
            outs = []
            for n in names:
                if should_skip(n, slot, opdef):
                    outs.append("")
                else:
                    gname = provide(n)
                    src = block._find_var_recursive(n)
                    block.create_var(
                        name=gname,
                        shape=src.shape if src is not None else None,
                        dtype=src.dtype if src is not None else "float32",
                    )
                    outs.append(gname)
                    emitted = True
            g_outputs[GRAD_SLOT_PREFIX + slot] = outs
        if not emitted:
            continue

        attrs = dict(op.attrs)
        attrs["fwd_input_slots"] = list(op.inputs.keys())
        attrs["fwd_output_slots"] = list(op.outputs.keys())
        attrs["forward_op_idx"] = idx
        block.append_op(
            op.type + GRAD_OP_SUFFIX,
            inputs=g_inputs,
            outputs=g_outputs,
            attrs=attrs,
        )

    # Finalize every gradient with multiple partial producers, whether or not
    # something downstream consumed it (calc_gradient reads them directly).
    suffix_len = len(grad_var_name(""))
    for gname, lst in list(producers.items()):
        if len(lst) > 1 and gname not in finalized:
            lookup(gname[:-suffix_len])

    # Collect (param, grad) pairs.
    if parameter_list is not None:
        params = [
            block.var(p) if isinstance(p, str) else p for p in parameter_list
        ]
    else:
        params = [p for p in program.all_parameters() if p.trainable]

    result = []
    for p in params:
        g = lookup(p.name)
        if g is None:
            continue
        result.append((p, block.var(g)))
        program._param_grad_map[p.name] = g
    return result


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Gradients of targets w.r.t. arbitrary inputs (reference: backward.py:619)."""
    if isinstance(targets, Variable):
        targets = [targets]
    if isinstance(inputs, Variable):
        inputs = [inputs]
    assert len(targets) == 1, "calc_gradient currently supports one target"
    block = targets[0].block
    append_backward(targets[0], no_grad_set=no_grad_set,
                    parameter_list=[])
    outs = []
    for v in inputs:
        g = grad_var_name(v.name)
        outs.append(block.var(g) if block.has_var(g) else None)
    return outs


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    return calc_gradient(targets, inputs, target_gradients, no_grad_set)
