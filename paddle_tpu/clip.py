"""Gradient clipping (reference: python/paddle/fluid/clip.py)."""

from __future__ import annotations

from typing import Optional


class BaseGradientClipAttr:
    def process(self, params_grads):
        raise NotImplementedError


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def process(self, params_grads):
        from paddle_tpu.layers import nn

        return [
            (p, nn.clip(g, self.min, self.max) if g is not None else None)
            for p, g in params_grads
        ]


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def process(self, params_grads):
        from paddle_tpu.layers import nn

        return [
            (p, nn.clip_by_norm(g, self.clip_norm) if g is not None else None)
            for p, g in params_grads
        ]


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """Scale all gradients so their joint L2 norm stays under
    ``clip_norm``. The pre-clip global norm and the applied scale are
    registered as numerics-plane aux vars (numerics.py), so with the
    ``telemetry`` + ``numerics`` flags on the executor exports
    ``pt_grad_global_norm`` / ``pt_grad_clip_ratio`` /
    ``pt_grad_clips_total`` from the in-graph values — the post-clip
    norm is ``global_norm * scale`` by construction."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)
        # var names of the most recent process() call (one per program
        # build), for tests/debugging
        self.global_norm_name = None
        self.scale_name = None

    def process(self, params_grads):
        from paddle_tpu import numerics
        from paddle_tpu.layer_helper import LayerHelper
        from paddle_tpu.layers import nn, tensor

        helper = LayerHelper("global_norm_clip")
        sq_norms = []
        for _, g in params_grads:
            if g is None:
                continue
            out = helper.create_variable_for_type_inference(dtype=g.dtype)
            helper.append_op("squared_l2_norm", inputs={"X": g},
                             outputs={"Out": out})
            sq_norms.append(out)
        if not sq_norms:
            return params_grads
        total = nn.sums(sq_norms)
        global_norm = nn.sqrt(total)
        clip_v = tensor.fill_constant([1], "float32", self.clip_norm)
        scale = nn.elementwise_div(
            clip_v, nn.elementwise_max(global_norm, clip_v)
        )
        program = helper.main_program
        numerics.register_aux(program, "grad_global_norm",
                              global_norm.name)
        numerics.register_aux(program, "grad_clip_scale", scale.name)
        self.global_norm_name = global_norm.name
        self.scale_name = scale.name
        return [
            (p, nn.elementwise_mul(g, scale) if g is not None else None)
            for p, g in params_grads
        ]


class ErrorClipByValue:
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max


_clip_attr: Optional[BaseGradientClipAttr] = None
_clip_param_names: Optional[set] = None


def set_gradient_clip(clip: BaseGradientClipAttr, param_list=None, program=None):
    """Install a gradient clip. ``param_list`` (names or Variables) restricts
    clipping to those parameters; None clips all."""
    global _clip_attr, _clip_param_names
    _clip_attr = clip
    if param_list is None:
        _clip_param_names = None
    else:
        _clip_param_names = {
            p if isinstance(p, str) else p.name for p in param_list
        }


def has_clip_attr() -> bool:
    return _clip_attr is not None


def clip_applies_to(param_name: str) -> bool:
    """Whether the installed gradient clip covers this parameter
    (set_gradient_clip may scope to an explicit param_list)."""
    if _clip_attr is None:
        return False
    return _clip_param_names is None or param_name in _clip_param_names


def append_gradient_clip_ops(params_grads):
    if _clip_attr is None:
        return params_grads
    if _clip_param_names is None:
        return _clip_attr.process(params_grads)
    selected = [(p, g) for p, g in params_grads if p.name in _clip_param_names]
    untouched = [(p, g) for p, g in params_grads if p.name not in _clip_param_names]
    return _clip_attr.process(selected) + untouched
