"""Persistent (level-2) compile cache: AOT executables resolved from disk
before tracing.

BENCH r01 measured 98.9 s compile+first-step against 1.6 s for 20
steady-state steps — cold start is ~60x the per-step cost, and it is
paid again on every trainer auto-resume, every elastic-resize re-exec
generation, and every serving-process restart. This module removes that
cost for a repeated program: the executor's in-memory compiled-entry
cache stays level 1, and a ``compile_cache_dir`` adds a level 2 that
serializes the compiled XLA executable itself
(``jax.experimental.serialize_executable``), so a FRESH PROCESS resolves
the entry from disk and reaches step 1 without tracing or compiling.

Key composition — an entry is addressed by a sha256 digest over:

  ==========================  ==============================================
  component                   why it must match
  ==========================  ==============================================
  program fingerprint         ``program_fingerprint()``: canonical content
                              digest of blocks/vars/ops/attrs + amp flag +
                              feed signature + fetch list + SPMD strategy /
                              mesh plan (the single fingerprint also used
                              for the executor L1 key, the static
                              verifier's lint-once cache, and the compile
                              report ``cache_key``)
  state signature             (name, shape, dtype) of every state-in array
                              gathered from the scope — state avals are
                              baked into the executable
  PRNG key aval               the key dtype encodes the ``prng_impl``
  window shape                run_steps: (n_feeds, steps) — ``steps`` is a
                              static argument baked into the executable
  environment token           jax/jaxlib versions, backend, cache format
                              version (process-independent)
  owning-shard topology       local executables: (sorted addressable
                              device ids, kind) — the ids, NOT a count:
                              the serialized executable bakes an XLA
                              device assignment, and two ranks of a
                              distributed world share a count but not
                              ids. Excludes process/world counts, so a
                              resize never cold-starts a process whose
                              device identity is unchanged; SPMD
                              executables: (process index, process
                              count, global device count, kind) — one
                              entry per program shard
  ==========================  ==============================================

Entries are written atomically (stage + fsync + rename — the checkpoint
commit idiom), so a crash mid-write leaves a ``.tmp`` straggler, never a
torn published entry. Loads validate the stored format/env/digest header
AND the deserialized executable's input avals against the expected
arguments; any mismatch, read error or deserialization failure degrades
to a fresh compile — metered, warned, never an abort.

Fallback tier: when the flag is set, jax's own persistent compilation
cache is additionally pointed at ``<dir>/xla`` (unless the user already
configured one), so even entries this module cannot serialize skip the
XLA backend work on a recompile (tracing is still paid on that path).

Cache files are pickles and therefore as trusted as the directory they
live in — point ``compile_cache_dir`` only at directories you own, same
as checkpoints.

Disabled-path contract (same as monitor.py/faults.py): while
``compile_cache_dir`` is unset, the executor hot path costs one cached
module-boolean read here and allocates nothing in this file.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import threading
import time
import warnings
from collections import OrderedDict
from typing import Any, Dict, Optional

import jax
import numpy as np

from paddle_tpu import faults as _faults
from paddle_tpu import flags as _flags
from paddle_tpu import monitor as _monitor

# Bump on any incompatible change to the on-disk payload layout; a
# version mismatch is a silent miss, never an error.
# v2: stored executables are compiled WITHOUT input donation — a
# deserialized donating executable corrupts buffer ownership from its
# second call on (jax 0.4.x flaky use-after-free, first surfaced by the
# serving plane's multi-call decode entries); v1 entries must miss.
FORMAT_VERSION = 2

_M_HITS = _monitor.counter(
    "pt_compile_cache_hits_total",
    "persistent compile-cache hits: executables deserialized from disk, "
    "skipping trace + XLA compile entirely")
_M_MISSES = _monitor.counter(
    "pt_compile_cache_misses_total",
    "persistent compile-cache misses (no disk entry, or a format/env/"
    "topology mismatch): a fresh compile follows and repopulates")
_M_ERRORS = _monitor.counter(
    "pt_compile_cache_errors_total",
    "persistent compile-cache failures degraded to a fresh compile, by "
    "stage (spec/load/store)")
_M_LOAD_SECONDS = _monitor.histogram(
    "pt_compile_cache_load_seconds",
    "disk read + executable deserialization time per persistent "
    "compile-cache hit")
_M_EVICTIONS = _monitor.counter(
    "pt_compile_cache_evictions_total",
    "persistent compile-cache entries removed by the size-capped "
    "LRU-by-mtime disk sweep (compile_cache_max_bytes)")

# Chaos sites (faults.py): load tears the published file BEFORE the read
# (corruption-regression drills), store tears the staged file before the
# atomic rename (torn-write drills).
_F_LOAD = _faults.site("ccache.load")
_F_STORE = _faults.site("ccache.store")

try:
    from jax.experimental import serialize_executable as _se

    _HAVE_SERIALIZE = hasattr(_se, "serialize") and hasattr(
        _se, "deserialize_and_load")
except Exception:  # pragma: no cover - jax without the experimental API
    _se = None
    _HAVE_SERIALIZE = False


# --------------------------------------------------------------------------
# flag plumbing (cached-hot-flag pattern, monitor.py)
# --------------------------------------------------------------------------

_dir = ""
_xla_fallback: Optional[str] = None


def _enable_xla_fallback(dirpath: str):
    """Point jax's persistent compilation cache at ``<dir>/xla`` so the
    entries this module cannot serialize still skip XLA backend work on
    recompile. Never overrides a cache dir the user configured (e.g.
    tests/conftest.py, bench.py)."""
    global _xla_fallback
    try:
        cur = jax.config.jax_compilation_cache_dir
        if cur and cur != _xla_fallback:
            return
        target = os.path.join(dirpath, "xla")
        os.makedirs(target, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", target)
        _xla_fallback = target
    except Exception:
        pass  # fallback tier is strictly best-effort


def _sync_dir(v):
    global _dir, _xla_fallback
    _dir = str(v or "")
    if _dir:
        _enable_xla_fallback(_dir)
    elif _xla_fallback is not None:
        # flag cleared: release the fallback tier too, or every later
        # XLA compile keeps writing into the now-disabled (possibly
        # deleted temp) directory. Never touches a dir the user set.
        try:
            if jax.config.jax_compilation_cache_dir == _xla_fallback:
                jax.config.update("jax_compilation_cache_dir", None)
        except Exception:
            pass
        _xla_fallback = None


_max_bytes = 0


def _sync_max_bytes(v):
    global _max_bytes
    _max_bytes = int(v)


_flags.watch_flag("compile_cache_dir", _sync_dir)
_flags.watch_flag("compile_cache_max_bytes", _sync_max_bytes)


def active() -> bool:
    """One cached-boolean read — the executor's zero-allocation gate."""
    return bool(_dir)


def cache_dir() -> str:
    return _dir


# --------------------------------------------------------------------------
# canonical fingerprint — THE compile-signature identity shared by the
# executor cache key, the static verifier's lint-once cache, and the
# compile-report cache_key (three subsystems that used to hand-roll
# overlapping signatures that could drift)
# --------------------------------------------------------------------------

def strategy_token(strategy) -> tuple:
    """Content fingerprint of a DistributedStrategy. id() would alias a
    fresh strategy to a GC-reused address (the _latest_stacked hazard);
    content keying also lets two equal strategies share cache entries."""
    if strategy is None:
        return ()
    mesh = getattr(strategy, "mesh", None)
    return (
        tuple(sorted((a, int(mesh.shape[a])) for a in mesh.axis_names))
        if mesh is not None else None,
        getattr(strategy, "data_axis", None),
        getattr(strategy, "slice_axis", None),
        getattr(strategy, "context_axis", None),
        getattr(strategy, "table_axis", None),
        getattr(strategy, "expert_axis", None),
        getattr(strategy, "pipe_axis", None),
        getattr(strategy, "pipe_micro", None),
        bool(getattr(strategy, "strict", False)),
        tuple((r.pattern, str(r.spec))
              for r in getattr(strategy, "rules", ())),
    )


def mesh_token(mesh) -> tuple:
    """Mesh descriptor: axis names/sizes + device platform + count.
    Device IDENTITY is deliberately dropped (the checkpoint manifest-v2
    convention) — a same-shaped mesh on other devices is the same plan."""
    if mesh is None:
        return ()
    try:
        devs = np.asarray(mesh.devices)
        plat = getattr(devs.flat[0], "platform", "?")
        return (tuple((a, int(mesh.shape[a])) for a in mesh.axis_names),
                str(plat), int(devs.size))
    except Exception:
        return ("mesh?",)


def compiled_token(compiled) -> tuple:
    """Content token of a CompiledProgram execution plan (replaces the
    ``compiled._uid`` identity that used to sit in executor cache keys:
    two CompiledPrograms wrapping the same program with the same plan now
    share one compiled entry)."""
    if compiled is None:
        return ()
    return (bool(getattr(compiled, "_data_parallel", False)),
            mesh_token(getattr(compiled, "mesh", None)),
            strategy_token(getattr(compiled, "_strategy", None)))


def program_fingerprint(program, feed_sig=(), fetch_names=(),
                        strategy=None, compiled=None, extra=()) -> str:
    """Canonical compile-signature fingerprint: a sha256 hex digest over
    the program CONTENT (``Program.content_digest()`` — blocks, vars,
    ops, attrs; stable across processes), the amp flag, the feed
    signature, the fetch list, and the SPMD strategy / CompiledProgram
    plan content. Two identically-built programs in two different
    processes produce the SAME fingerprint — the property the persistent
    compile cache rests on.

    Returns a ``local-`` prefixed identity digest when the program
    content cannot be canonicalized (exotic attrs); such fingerprints
    still key in-process caches correctly but are never used for disk
    resolution."""
    try:
        content = program.content_digest()
    except Exception:
        content = None
    parts = (
        content,
        bool(getattr(program, "_amp", False)),
        tuple(feed_sig),
        tuple(fetch_names),
        strategy_token(strategy),
        compiled_token(compiled),
        tuple(extra),
    )
    digest = hashlib.sha256(repr(parts).encode()).hexdigest()[:40]
    if content is None:
        return f"local-{program._uid}v{program.version}-{digest[:24]}"
    return digest


# (identity tuple) -> fingerprint memo so the executor's per-call key
# assembly costs one dict read steady-state (content digests are cached
# per program version; this bounds even the tuple-hash + sha256 of the
# signature parts to one computation per distinct signature).
_FP_MEMO: "OrderedDict[tuple, str]" = OrderedDict()
_FP_CAP = 512


def fingerprint_for(ident: tuple, program, compiled=None, strategy=None,
                    feed_sig=(), fetch_names=(), extra=()) -> str:
    """Memoized ``program_fingerprint`` keyed by the caller's cheap
    identity tuple (uids/versions/signatures). The memo makes the
    fingerprint safe on the executor hot path: a warm signature is one
    dict lookup."""
    fp = _FP_MEMO.get(ident)
    if fp is not None:
        return fp
    if strategy is None:
        strategy = getattr(compiled, "_strategy", None)
    fp = program_fingerprint(
        program, feed_sig=feed_sig, fetch_names=fetch_names,
        strategy=strategy, compiled=compiled, extra=extra)
    _FP_MEMO[ident] = fp
    while len(_FP_MEMO) > _FP_CAP:
        _FP_MEMO.popitem(last=False)
    return fp


def env_token() -> tuple:
    """The process-independent half of what an executable bakes in: a
    mismatch on any component means the disk entry is not ours to load.
    The device/process half lives in ``topology_token`` (keyed by the
    OWNING shard, not the global world — the property that lets a
    joining host of a resized world warm-start from a smaller
    generation's entries)."""
    import jaxlib

    return (FORMAT_VERSION, jax.__version__, jaxlib.__version__,
            jax.default_backend())


def topology_token(state_vals=(), mesh=None, extra_devices=()) -> tuple:
    """Owning-shard topology token — the multi-host half of the entry
    key (ISSUE 14: replaces the blanket ``process_count() > 1``
    decline).

    An executable whose referenced devices (state array shardings, the
    strategy mesh) are all ADDRESSABLE by this process is **local**:
    its token is ``("local", sorted addressable device ids, kind)``.
    The serialized executable bakes an XLA device assignment, so it is
    loadable exactly where its device ids are addressable — the ids ARE
    the owning-shard identity (two ranks of a distributed world have
    distinct local ids and therefore distinct entries; the same rank
    across generations, or any single-process world, shares). The token
    deliberately excludes the process count and the global device
    count, so a world RESIZE does not cold-start processes whose device
    identity is unchanged — what lets a generation-N+1 member
    warm-start from generation N's store.

    An executable that spans non-addressable devices is a per-process
    shard of an SPMD program: its token is ``("spmd", process index,
    process count, global device count, kind)`` — the owning shard's
    identity, so rank 3's serialized executable can never resolve as
    rank 5's, and a replacement host joining at index 3 resolves
    exactly its predecessor shard's entry."""
    devs = set(extra_devices)
    for v in state_vals:
        if isinstance(v, jax.Array):
            try:
                devs |= set(v.sharding.device_set)
            except Exception:
                pass
    if mesh is not None:
        try:
            devs |= set(np.asarray(mesh.devices).flat)
        except Exception:
            pass
    try:
        local = set(jax.local_devices())
        kind = str(getattr(next(iter(local)), "device_kind", "?"))
    except Exception:
        local, kind = set(), "?"
    if devs - local:
        try:
            n_global = len(jax.devices())
        except Exception:
            n_global = 0
        return ("spmd", int(jax.process_index()),
                int(jax.process_count()), n_global, kind)
    ids = tuple(sorted(int(getattr(d, "id", -1)) for d in local))
    return ("local", ids, kind)


def _aval(v) -> tuple:
    dt = getattr(v, "dtype", None)
    if dt is None:
        dt = np.asarray(v).dtype
    try:
        # the executable bakes jax's CANONICAL aval: with x64 disabled an
        # int64 host feed lowers as int32, so the expectation must match
        # args_info on that form (extended dtypes, e.g. PRNG keys, pass
        # through canonicalize unchanged)
        dt = jax.dtypes.canonicalize_dtype(dt)
    except Exception:
        pass
    return (tuple(np.shape(v)), str(dt))


# --------------------------------------------------------------------------
# disk entries
# --------------------------------------------------------------------------

class Spec:
    """Everything needed to resolve one disk entry: the digest path, the
    example arguments to AOT-lower against on a miss (and validate avals
    against on a hit), and the lowered-block recipe the executor entry
    carries alongside the callable."""

    __slots__ = ("path", "digest", "lower_args", "static_steps",
                 "program", "feed_names", "fetch_names", "strategy")

    def __init__(self, path, digest, lower_args, static_steps,
                 program, feed_names, fetch_names, strategy=None):
        self.path = path
        self.digest = digest
        self.lower_args = lower_args
        self.static_steps = static_steps
        self.program = program
        self.feed_names = feed_names
        self.fetch_names = fetch_names
        self.strategy = strategy

    def make_lowered(self):
        """Rebuild the LoweredBlock for a disk-resolved entry. This is
        block ANALYSIS only (state lists, op histogram) — no jax tracing
        happens until a function is actually jitted, which a disk hit
        never does."""
        from paddle_tpu.core import lowering

        return lowering.lower_block(self.program, 0, self.feed_names,
                                    self.fetch_names)


def executor_spec(program, *, feed_vals, fetch_names, scope, base_key,
                  fingerprint, compiled=None, window_steps=None,
                  n_feeds=None, nan_track=False) -> Optional[Spec]:
    """Build the disk-resolution spec for one executor entry, or None
    when the tier is off or this entry cannot be safely serialized
    (multi-host run, non-portable fingerprint, uninitialized state).
    Called only on a level-1 miss, so its cost is irrelevant next to the
    compile it replaces."""
    if not _dir or not _HAVE_SERIALIZE:
        return None
    if fingerprint.startswith("local-"):
        return None  # content not canonical -> not portable across procs
    try:
        from paddle_tpu.core.lowering import analyze_state

        feed_names = sorted(feed_vals)
        state_in, _ = analyze_state(program.blocks[0], feed_names)
        state = {}
        for n in state_in:
            v = scope.find_var(n)
            if v is None:
                return None  # the run itself will raise the real error
            state[n] = v
        state_sig = tuple((n, _aval(v)) for n, v in state.items())
        # the owning-shard topology token rides the digest: local
        # executables share entries across ranks/world sizes, SPMD
        # executables are keyed per process shard (ISSUE 14 — what used
        # to be a blanket multi-host decline)
        topo = topology_token(
            list(state.values()) + list(feed_vals.values()),
            getattr(compiled, "mesh", None))
        digest = hashlib.sha256(repr((
            fingerprint, state_sig, _aval(base_key),
            None if window_steps is None else (int(n_feeds or 0),
                                               int(window_steps)),
            bool(nan_track), env_token(), topo,
        )).encode()).hexdigest()
        if window_steps is None:
            lower_args: tuple = (state, dict(feed_vals), base_key,
                                 np.uint32(0))
        else:
            lower_args = (state, dict(feed_vals), base_key, np.uint32(0),
                          int(window_steps))
        return Spec(
            path=os.path.join(_dir, f"pcc-{digest[:40]}.bin"),
            digest=digest,
            lower_args=lower_args,
            static_steps=None if window_steps is None else int(window_steps),
            program=program,
            feed_names=tuple(feed_names),
            fetch_names=tuple(fetch_names),
            strategy=getattr(compiled, "_strategy", None),
        )
    except Exception as e:
        _M_ERRORS.inc(labels={"stage": "spec"})
        warnings.warn(f"compile-cache spec degraded to fresh compile "
                      f"({type(e).__name__}: {e})", RuntimeWarning)
        return None


def _canon_host_array(v):
    """Match jax.jit's input canonicalization for a host array. The
    eager jit casts non-canonical host inputs (int64 -> int32 with x64
    off) during device_put; a ``jax.stages.Compiled`` does NOT — it was
    compiled for the canonical aval, and handing it the raw 64-bit
    buffer reinterprets the bytes (garbage values, and observed heap
    corruption on jax 0.4.37). Training-state entries never hit this
    (all-f32 params); the serving programs' int64/bool decode state is
    what first tripped it."""
    if isinstance(v, np.ndarray):
        want = jax.dtypes.canonicalize_dtype(v.dtype)
        if want != v.dtype:
            return v.astype(want)
    return v


def _wrap(comp, static_steps: Optional[int]):
    """Wrap an AOT ``jax.stages.Compiled`` in the executor's call
    convention. run_steps entries bake ``steps`` as a static argument, so
    the wrapper drops the trailing count the eager jit would re-dispatch
    on (the executor keys entries by ``steps``, making a mismatch
    impossible). Host inputs are canonicalized exactly as the eager jit
    would (see _canon_host_array)."""
    _canon = jax.tree_util.tree_map
    if static_steps is None:
        def fn(state, feeds, base_key, step):
            return comp(*_canon(_canon_host_array,
                                (state, feeds, base_key, step)))
    else:
        def fn(state, feeds, base_key, start, n_steps):
            return comp(*_canon(_canon_host_array,
                                (state, feeds, base_key, start)))
    # build_compile_report() reuses this executable for cost/memory
    # analysis instead of AOT-compiling a twin
    fn._pt_compiled = comp
    return fn


def _nonstatic_args(spec: Spec) -> tuple:
    if spec.static_steps is None:
        return spec.lower_args
    return spec.lower_args[:-1]


def _validate_args_info(loaded, spec: Spec):
    """The stored digest already encodes every aval, but a hash is not a
    proof: compare the deserialized executable's input avals against the
    arguments this call will pass. Raises on any drift."""
    got = jax.tree_util.tree_map(
        lambda a: (tuple(a.shape), str(a.dtype)), loaded.args_info)
    exp = jax.tree_util.tree_map(_aval, (_nonstatic_args(spec), {}))
    if got != exp:
        raise ValueError(
            f"cached executable avals {got!r} != expected {exp!r}")


def load(spec: Spec):
    """Resolve ``spec`` from disk. Returns ``(entry_fn, load_ms)`` on a
    hit, None on a miss; counts hits/misses/errors and load seconds.
    Corruption, header mismatch or deserialization failure degrades to a
    miss with a metered error — never raises."""
    t0 = time.perf_counter()
    try:
        if not os.path.exists(spec.path):
            _M_MISSES.inc()
            return None
        _F_LOAD.hit(path=spec.path)
        with open(spec.path, "rb") as f:
            payload = pickle.load(f)
        if (payload.get("format") != FORMAT_VERSION
                or payload.get("env") != env_token()
                or payload.get("digest") != spec.digest):
            # another format/jax/topology wrote this name: silent miss
            _M_MISSES.inc()
            return None
        loaded = _se.deserialize_and_load(
            payload["payload"], payload["in_tree"], payload["out_tree"])
        _validate_args_info(loaded, spec)
        fn = _wrap(loaded, spec.static_steps)
        dt = time.perf_counter() - t0
        _M_HITS.inc()
        _M_LOAD_SECONDS.observe(dt)
        try:
            # LRU touch: the size-capped GC sweep evicts by mtime, so a
            # hit must refresh it or hot entries age like cold ones
            os.utime(spec.path)
        except OSError:
            pass
        return fn, dt * 1e3
    except Exception as e:
        _M_ERRORS.inc(labels={"stage": "load"})
        warnings.warn(
            f"compile-cache entry {os.path.basename(spec.path)} unusable "
            f"({type(e).__name__}: {e}); recompiling", RuntimeWarning)
        return None


def store(spec: Spec, comp) -> bool:
    """Serialize ``comp`` and publish it atomically (stage + fsync +
    rename — the checkpoint commit idiom: a crash leaves a ``.tmp``
    straggler, never a torn published entry). Best-effort: failure
    counts an error and the in-memory entry proceeds unaffected."""
    tmp = None
    try:
        ser, in_tree, out_tree = _se.serialize(comp)
        payload = {
            "format": FORMAT_VERSION,
            "env": env_token(),
            "digest": spec.digest,
            "payload": ser,
            "in_tree": in_tree,
            "out_tree": out_tree,
            "meta": {
                "ts": time.time(),
                "program_uid": int(spec.program._uid),
                "static_steps": spec.static_steps,
                "n_bytes": len(ser),
            },
        }
        os.makedirs(_dir, exist_ok=True)
        # pid alone is not unique: concurrent serving replicas (fleet
        # supervisor loop threads) store the same digest from one
        # process, and a shared tmp name turns the second rename into
        # a FileNotFoundError store failure
        tmp = spec.path + f".tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
            f.flush()
            os.fsync(f.fileno())
        _F_STORE.hit(path=tmp)
        os.replace(tmp, spec.path)
        gc()  # keep the disk tier inside compile_cache_max_bytes
        return True
    except Exception as e:
        _M_ERRORS.inc(labels={"stage": "store"})
        warnings.warn(f"compile-cache store skipped "
                      f"({type(e).__name__}: {e})", RuntimeWarning)
        if tmp is not None:
            try:
                os.remove(tmp)
            except OSError:
                pass
        return False


def aot_build(spec: Spec, jitfn):
    """Fresh-compile path with the disk tier on: AOT-compile ``jitfn``
    against the spec's example arguments (ONE trace + ONE XLA compile —
    the eager jit is never invoked), persist the executable, and return
    the wrapped entry callable. Returns None when AOT compilation itself
    fails; the caller keeps the eager jit and nothing is stored.

    ``jitfn`` must be the DONATION-FREE twin (executor._jit_for
    donate_state=False): a donating executable round-tripped through
    serialize/deserialize mishandles buffer ownership from its second
    call on (jax 0.4.x — flaky use-after-free observed as garbage KV
    caches and glibc heap aborts in the serving decode loop). The cost
    is one extra in-flight copy of the state in disk-tier processes;
    the value contract is what the tier exists for."""
    try:
        from paddle_tpu.core import interp as _interp

        # trace under the strategy's SPMD context, exactly like the
        # eager jit's first call (executor.run) and
        # build_compile_report: collective ops (DGC exchange, MoE
        # all_to_all) read it at TRACE time — without it they silently
        # lower their non-collective fallback, and the wrong executable
        # would be both executed and persisted
        with _interp.spmd_ctx_scope(spec.strategy):
            comp = jitfn.lower(*spec.lower_args).compile()
    except Exception as e:
        _M_ERRORS.inc(labels={"stage": "store"})
        warnings.warn(f"compile-cache AOT build degraded to eager jit "
                      f"({type(e).__name__}: {e})", RuntimeWarning)
        return None
    store(spec, comp)  # best-effort; an unstorable executable still runs
    return _wrap(comp, spec.static_steps)


# stage-file stragglers older than this are crash leftovers (the
# publishing process fsync+renames within seconds); the GC sweep
# reclaims them alongside over-budget entries
_TMP_REAP_AGE_S = 3600.0


def gc(max_bytes: Optional[int] = None) -> int:
    """Size-capped LRU-by-mtime sweep of the persistent cache dir
    (closes the 'unbounded today' remainder of the disk tier): evict
    published ``pcc-*.bin`` entries oldest-mtime-first until the total
    fits ``max_bytes`` (default: the ``compile_cache_max_bytes`` flag;
    0 = unbounded, no sweep), always keeping the newest entry even when
    it alone exceeds the cap (evicting everything would defeat the
    cache). Loads refresh mtime, so eviction order is least-recently-
    USED. Also reaps ``.tmp.*`` stage stragglers older than an hour
    (crashed publishers). Returns entries evicted, metered by
    ``pt_compile_cache_evictions_total``; any listing/unlink error
    degrades silently — GC must never fail a store."""
    cap = _max_bytes if max_bytes is None else int(max_bytes)
    if not _dir or cap <= 0:
        return 0
    evicted = 0
    try:
        entries = []
        now = time.time()
        with os.scandir(_dir) as it:
            for de in it:
                if not de.is_file():
                    continue
                if ".tmp." in de.name:
                    try:
                        st = de.stat()
                        if now - st.st_mtime > _TMP_REAP_AGE_S:
                            os.remove(de.path)
                    except OSError:
                        pass
                    continue
                if de.name.startswith("pcc-") and de.name.endswith(".bin"):
                    try:
                        st = de.stat()
                    except OSError:
                        continue
                    entries.append((st.st_mtime, st.st_size, de.path))
        total = sum(size for _, size, _ in entries)
        entries.sort()  # oldest mtime first = coldest first
        while total > cap and len(entries) > 1:
            mtime, size, path = entries.pop(0)
            try:
                os.remove(path)
            except FileNotFoundError:
                # a concurrent GC reclaimed it — not evicted by us, but
                # the space IS gone: without the subtraction this
                # process over-evicts still-hot entries past the cap
                total -= size
                continue
            except OSError:
                continue
            total -= size
            evicted += 1
        if evicted:
            _M_EVICTIONS.inc(evicted)
    except OSError:
        pass
    return evicted


def stats() -> Dict[str, Any]:
    """Operator-facing snapshot (debugging, tests)."""
    return {
        "dir": _dir,
        "serializer": _HAVE_SERIALIZE,
        "xla_fallback": _xla_fallback,
        "hits": _M_HITS.value(),
        "misses": _M_MISSES.value(),
        "evictions": _M_EVICTIONS.value(),
        "errors": {stage: _M_ERRORS.value(labels={"stage": stage})
                   for stage in ("spec", "load", "store")},
    }
