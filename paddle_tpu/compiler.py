"""CompiledProgram: SPMD parallel execution strategies.

The reference implements data parallelism by graph rewriting — cloning ops
per device and inserting per-gradient NCCL allreduce op handles (reference:
python/paddle/fluid/compiler.py:118, framework/parallel_executor.cc:284,
ir/multi_devices_graph_pass/multi_devices_graph_pass.cc:208-247). On TPU the
idiomatic equivalent is GSPMD: mark the batch inputs as sharded over a device
mesh axis, keep parameters replicated, and let XLA insert the grad
all-reduce over ICI during SPMD partitioning. One program, one compile, any
number of devices.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.framework import Program


class BuildStrategy:
    """Structured build config (reference: details/build_strategy.h:57-93).
    Most knobs are XLA's job now; kept for API parity and for the ones that
    still matter (sharding axes)."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = None
        self.memory_optimize = True   # XLA buffer assignment
        self.enable_inplace = True    # XLA donation
        self.fuse_all_reduce_ops = True  # XLA allreduce combiner


class ExecutionStrategy:
    """(reference: details/execution_strategy.h) — retained for API parity."""

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 1


class CompiledProgram:
    """Wraps a Program with a parallel execution plan
    (reference: compiler.py:49)."""

    _uid_counter = 0

    def __init__(self, program: Program):
        CompiledProgram._uid_counter += 1
        self._uid = CompiledProgram._uid_counter
        self.program = program
        self._mesh: Optional[Mesh] = None
        self._data_parallel = False
        self._strategy = None  # parallel.DistributedStrategy
        self.build_strategy: Optional[BuildStrategy] = None
        self.exec_strategy: Optional[ExecutionStrategy] = None
        self._loss_name: Optional[str] = None

    def with_data_parallel(
        self,
        loss_name: Optional[str] = None,
        build_strategy: Optional[BuildStrategy] = None,
        exec_strategy: Optional[ExecutionStrategy] = None,
        share_vars_from=None,
        places=None,
        devices=None,
    ) -> "CompiledProgram":
        """Data-parallel over all visible devices (or ``devices``)."""
        self._data_parallel = True
        self._loss_name = loss_name
        self.build_strategy = build_strategy or BuildStrategy()
        self.exec_strategy = exec_strategy or ExecutionStrategy()
        devs = devices if devices is not None else jax.devices()
        self._mesh = Mesh(np.asarray(devs), ("data",))
        return self

    def with_strategy(self, strategy) -> "CompiledProgram":
        """Full SPMD strategy: data axis + per-parameter sharding rules
        (tensor/expert/sequence parallelism via parallel.DistributedStrategy)."""
        self._strategy = strategy
        self._mesh = strategy.mesh
        self._data_parallel = True
        # lint-at-build: the sharding + collective-order checks need the
        # strategy, and this is the first moment program and strategy
        # meet — a rule mismatch or unplanned reshard surfaces here, not
        # after the first (minutes-long) compile. Gated on static_lint.
        from paddle_tpu import analysis

        analysis.lint_at_build(
            self.program, strategy=strategy,
            checks=("sharding", "collectives"),
            site="CompiledProgram.with_strategy")
        return self

    @property
    def mesh(self) -> Optional[Mesh]:
        return self._mesh

    # --- executor hooks ---

    def shardings(self, lowered):
        """(in_shardings, out_shardings) pytrees for jit, aligned with
        fn(state, feeds, key) -> (fetches, new_state)."""
        if not self._data_parallel or self._mesh is None:
            return None, None
        repl = NamedSharding(self._mesh, P())
        if self._strategy is None:
            return (repl, self._batch_sharding(), repl), (repl, repl)
        st = self._strategy
        state_in = {n: st.sharding_for(n) for n in lowered.state_in_names}
        state_out = {n: st.sharding_for(n) for n in lowered.state_out_names}
        in_shardings = (state_in, self._batch_sharding(), st.replicated())
        out_shardings = (st.replicated(), state_out)
        return in_shardings, out_shardings

    def shard_inputs(self, state, feeds):
        """Pre-place inputs; jit's in_shardings handles the real placement.

        Multi-host (fleet) jobs: each process holds only ITS batch shard,
        so feeds are assembled into global arrays with
        ``jax.make_array_from_process_local_data`` (the analog of the
        reference's per-trainer feed in NCCL2 mode, test_dist_base.py:459
        — every process feeds its slice of the global batch). State stays
        host-numpy: parameters are replicated and identical across
        processes (same seeded startup program)."""
        if jax.process_count() <= 1 or self._mesh is None:
            return state, feeds
        batch_sh = self._batch_sharding()
        new_feeds = {
            # already-global jax.Arrays pass through (the executor keeps
            # them untouched too); host numpy is this process's shard
            k: v
            if isinstance(v, jax.Array)
            else jax.make_array_from_process_local_data(batch_sh, v)
            for k, v in feeds.items()
        }
        return state, new_feeds

    def _batch_sharding(self):
        """Feed sharding — single source for shardings() and
        shard_inputs(), which must agree on placement."""
        if self._strategy is not None:
            return self._strategy.batch_sharding()
        return NamedSharding(self._mesh, P("data"))
