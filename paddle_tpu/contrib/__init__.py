"""High-level training APIs (reference: python/paddle/fluid/contrib/)."""

from paddle_tpu.contrib.trainer import (  # noqa: F401
    BeginEpochEvent,
    BeginStepEvent,
    CheckpointConfig,
    EndEpochEvent,
    EndStepEvent,
    Trainer,
)
