"""Artifacts for the C++ standalone trainer (reference:
paddle/fluid/train/demo/demo_trainer.cc — train a serialized program
without writing Python).

``save_train_program`` writes <dir>/{main_program.pb, startup_program.pb,
feeds.json}; ``csrc/standalone_trainer`` (built by ``make -C csrc
standalone_trainer``) loads them, initializes the scope, and runs train
steps with synthetic feeds, printing the per-step loss.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Sequence

from paddle_tpu.framework import Program, Variable


def save_train_program(dirname: str, main: Program, startup: Program,
                       feed_vars: Sequence[Variable],
                       int_maxes: Optional[Dict[str, int]] = None,
                       dims: Optional[Dict[str, int]] = None):
    """Serialize a TRAINING program pair + feed specs for the native
    trainer. ``int_maxes``: exclusive upper bound for synthetic integer
    feeds (e.g. vocabulary/class counts), keyed by feed name. ``dims``:
    concrete size for NON-LEADING dynamic dims (e.g. sequence length),
    keyed by feed name; without it the native driver falls back to 16."""
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, "main_program.pb"), "wb") as f:
        f.write(main.to_proto().SerializeToString())
    with open(os.path.join(dirname, "startup_program.pb"), "wb") as f:
        f.write(startup.to_proto().SerializeToString())
    specs = []
    for v in feed_vars:
        spec = {"name": v.name, "shape": list(v.shape or []),
                "dtype": str(v.dtype)}
        if int_maxes and v.name in int_maxes:
            spec["max"] = int(int_maxes[v.name])
        if dims and v.name in dims:
            spec["dim"] = int(dims[v.name])
        specs.append(spec)
    with open(os.path.join(dirname, "feeds.json"), "w") as f:
        json.dump(specs, f)
    return dirname
