"""High-level Trainer with epoch/step checkpoint-resume
(reference: python/paddle/fluid/contrib/trainer.py:379 ``Trainer.train``,
CheckpointConfig :100, serial checkpoint dirs + resume :580,285).

The train loop is the reference's event-driven shape (Begin/EndEpoch,
Begin/EndStep events, ``event_handler`` callback, ``trainer.stop()``);
persistence rides the sharded checkpoint module (parallel/checkpoint.py),
so the same Trainer resumes TP/DP-sharded state bit-exact.
"""

from __future__ import annotations

import os
import shutil
import warnings
from typing import Callable, List, Optional, Sequence

import numpy as np

from paddle_tpu import faults as _faults
from paddle_tpu import io as _io
from paddle_tpu import monitor as _monitor
from paddle_tpu import numerics as _numerics
from paddle_tpu.data_feeder import DataFeeder
from paddle_tpu.executor import Executor, Scope, scope_guard
from paddle_tpu.framework import Program, program_guard
from paddle_tpu.parallel import checkpoint as _ckpt
from paddle_tpu.reader.pipeline import DeviceLoader

# Epoch/step events feed the metrics plane (previously display-only via
# the user's event_handler); spans put them on the same chrome-trace
# timeline as executor compile/run spans.
_M_EPOCHS = _monitor.counter(
    "pt_trainer_epochs_total", "completed training epochs")
_M_TRAIN_STEPS = _monitor.counter(
    "pt_trainer_steps_total", "trainer steps run")
_M_CKPTS = _monitor.counter(
    "pt_trainer_checkpoints_total", "checkpoints saved")
_M_LOSS = _monitor.gauge(
    "pt_trainer_last_loss", "loss fetched at the most recent step")
_M_RESUMES = _monitor.counter(
    "pt_trainer_auto_resumes_total",
    "training failures auto-recovered by restoring the last valid "
    "checkpoint (CheckpointConfig.max_resume_retries), by whether the "
    "world size changed since the save (resized)")

# chaos hook: armed plans can fail the Nth batch fetch, driving the
# auto-resume loop deterministically (tests/test_faults.py)
_F_READER_NEXT = _faults.site("reader.next")


_RNG_STEP_KEY = "__trainer_rng_step__"
_WORLD_KEY = "__trainer_world__"


def _current_world() -> int:
    """Data-parallel world size of THIS run: the fleet's worker count
    when the fleet is up, else the jax process count. Saved into every
    checkpoint so a resume onto a resized world can re-derive its
    cursors (shard boundaries move when the world shrinks/grows)."""
    try:
        from paddle_tpu.incubate.fleet import fleet as _fleet

        if _fleet._initialized:
            return _fleet.worker_num()
    except Exception:  # pragma: no cover — fleet plane absent
        pass
    import jax

    return jax.process_count()


class BeginEpochEvent:
    def __init__(self, epoch_id: int):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id: int):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id: int, step_id: int):
        self.epoch = epoch_id
        self.step = step_id


class EndStepEvent:
    def __init__(self, epoch_id: int, step_id: int, metrics: List):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """reference: contrib/trainer.py:100. Checkpoints are epoch-granular
    (resume replays from an epoch boundary; there is no mid-epoch data
    cursor, so a step_interval would silently re-read data on resume).

    ``max_resume_retries``: on a training failure (a raising step,
    reader, or event handler), ``Trainer.train`` restores the newest
    VALID checkpoint and continues from its epoch, at most this many
    times per ``train()`` call. 0 (default) = fail fast.

    ``async_save``: overlap checkpoint serialization + commit with the
    next epoch's training steps (parallel/checkpoint.py _AsyncHandle
    seam — the device->host snapshot still happens synchronously, so
    the training step may freely donate the buffers afterwards). The
    previous save is waited on before the next one starts and at the
    end of ``train()``, so a failed background save surfaces within one
    checkpoint interval and the auto-resume loop sees it like any other
    training failure."""

    def __init__(
        self,
        checkpoint_dir: str,
        epoch_interval: int = 1,
        max_num_checkpoints: int = 3,
        max_resume_retries: int = 0,
        async_save: bool = False,
    ):
        self.checkpoint_dir = checkpoint_dir
        self.epoch_interval = max(1, int(epoch_interval))
        self.max_num_checkpoints = max(1, int(max_num_checkpoints))
        self.max_resume_retries = max(0, int(max_resume_retries))
        self.async_save = bool(async_save)


class Trainer:
    """train_func builds the forward graph and returns [loss, ...metrics];
    optimizer_func returns the Optimizer (reference Trainer contract)."""

    def __init__(
        self,
        train_func: Callable,
        optimizer_func: Callable,
        place=None,
        parallel: bool = False,
        checkpoint_config: Optional[CheckpointConfig] = None,
        strategy=None,
    ):
        self._ckpt_cfg = checkpoint_config
        self.scope = Scope()
        self.main_program, self.startup_program = Program(), Program()
        with program_guard(self.main_program, self.startup_program):
            outs = train_func()
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            self.train_outputs = list(outs)
            self.loss = self.train_outputs[0]
            self.test_program = self.main_program.clone(for_test=True)
            # keep the instance: its slot_descriptor() is what lets a
            # resume re-key saved moments onto THIS build's slot names
            # (checkpoint.reshard_optimizer_state)
            self._optimizer = optimizer_func()
            self._optimizer.minimize(self.loss)
        if _numerics.active():
            # numerics plane on at build time: instrument the train
            # program so every trainer step feeds tensor stats + NaN
            # provenance (filtered by the numerics_vars flag)
            from paddle_tpu import passes as _passes

            _passes.apply_pass("instrument_numerics", self.main_program)
        # lint-at-build: verify the fully built train program (forward +
        # backward + optimizer + instrumentation) before the trainer's
        # first — and most expensive — compile. Gated on static_lint.
        from paddle_tpu import analysis as _analysis

        _analysis.lint_at_build(self.main_program, strategy=strategy,
                                site="contrib.Trainer")
        self.exe = Executor(place)

        self._run_program = self.main_program
        if parallel or strategy is not None:
            from paddle_tpu.compiler import CompiledProgram

            cp = CompiledProgram(self.main_program)
            self._run_program = (
                cp.with_strategy(strategy)
                if strategy is not None
                else cp.with_data_parallel(loss_name=self.loss.name)
            )

        self._stopped = False
        self._start_epoch = 0
        self._pending_save = None  # (serial, _AsyncHandle) in flight
        self._last_resume_resized = False
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
            self._maybe_resume()

    # --- checkpoint/resume (reference: contrib/trainer.py:285,580) ---

    def _maybe_resume(self):
        """Restore the newest VALID checkpoint into the scope; returns
        its serial, or None when there is nothing to resume. Single
        read: load_latest verifies commit/coverage/checksums in the
        same pass that yields the values."""
        cfg = self._ckpt_cfg
        if cfg is None:
            return None
        loaded = _ckpt.load_latest(cfg.checkpoint_dir)
        if loaded is None:
            return None
        step, values = loaded
        # a second manifest read, deliberately: the fragments are KBs
        # of JSON (no array data) and resume is a rare event — not
        # worth widening load_latest's return shape for
        saved_slots = _ckpt.manifest_slots(cfg.checkpoint_dir, step)
        if saved_slots and self._optimizer is not None:
            # optimizer slot state restores by (param, kind), not by
            # name: a rebuilt/resized program's slot names drift with
            # its unique-name counters, and a by-name restore would
            # silently zero the moments (placement is left to the
            # executor's in_shardings, like the parameters')
            values = _ckpt.reshard_optimizer_state(
                values, saved_slots, self._optimizer.slot_descriptor())
        for n, v in values.items():
            self.scope.set(n, v)
        names = set(values)
        # Every parameter of THIS program must be covered, or training
        # would silently continue from re-initialized values (auto-generated
        # var names drift when a program is rebuilt differently — name your
        # parameters via ParamAttr for stable resume).
        missing = [
            p.name
            for p in self.main_program.all_parameters()
            if p.name not in names
        ]
        if missing:
            raise IOError(
                f"checkpoint_{step} does not cover {len(missing)} program "
                f"parameters (e.g. {missing[:4]}); parameter names differ "
                f"from the run that saved it"
            )
        # restore the executor RNG cursor so stochastic ops (dropout)
        # replay identically to the uninterrupted run. After an elastic
        # RESIZE the cursor is re-derived for the new world: the cursor
        # counts per-process steps, so the same GLOBAL data position is
        # old_steps * old_world / new_world steps into the new world
        # (data-parallel shard boundaries move with the world size; the
        # epoch position itself is world-independent — checkpoints are
        # epoch-granular and every world runs the same global batches).
        rng_step = self.scope.find_var(_RNG_STEP_KEY)
        saved_world = self.scope.find_var(_WORLD_KEY)
        world = _current_world()
        resized = (saved_world is not None
                   and int(np.asarray(saved_world)) != world)
        if rng_step is not None:
            cursor = int(np.asarray(rng_step))
            if resized:
                cursor = (cursor * int(np.asarray(saved_world))) // world
            self.exe._step = cursor
            self.scope.drop(_RNG_STEP_KEY)
        if saved_world is not None:
            self.scope.drop(_WORLD_KEY)
        self._last_resume_resized = resized
        if resized:
            _M_RESUMES.inc(labels={"resized": "true"})
            warnings.warn(
                f"resumed checkpoint_{step} saved by a "
                f"{int(np.asarray(saved_world))}-worker world onto "
                f"{world} workers; RNG cursor re-derived to "
                f"{self.exe._step}", RuntimeWarning)
        self._start_epoch = step  # serial number = next epoch to run
        return step

    def _wait_pending_save(self):
        """Land the in-flight overlapped save, if any: surfaces its
        error into the train loop (-> auto-resume budget) and runs the
        pruning deferred until its commit."""
        pending = self._pending_save
        if pending is None:
            return
        self._pending_save = None
        serial, handle = pending
        handle.wait()
        self._prune(serial)

    def _settle_pending_save(self):
        """Land an in-flight overlapped save before a RESUME decision,
        without burning a second resume retry on its failure. Waiting
        first matters twice over: a commit that lands makes its serial
        the restore point (no wasted replay from N-1), and the restore's
        directory scan must not race the background thread's rename/
        staging sweep. A pending-save failure is warned, not raised —
        one fault, one retry (the training failure that brought us
        here); resume proceeds from the newest valid serial."""
        pending = self._pending_save
        if pending is None:
            return
        self._pending_save = None
        serial, handle = pending
        try:
            handle.wait()
        except Exception as e:  # noqa: BLE001 — subsumed by the resume
            warnings.warn(
                f"overlapped save of checkpoint_{serial} failed during "
                f"auto-resume ({type(e).__name__}: {e}); resuming from "
                f"the newest valid serial", RuntimeWarning)
            return
        self._prune(serial)

    def _save_checkpoint(self, serial: int):
        cfg = self._ckpt_cfg
        self._wait_pending_save()
        self.scope.set(_RNG_STEP_KEY, np.int64(self.exe._step))
        self.scope.set(_WORLD_KEY, np.int64(_current_world()))
        try:
            handle = _ckpt.save_scope(cfg.checkpoint_dir, self.scope,
                                      step=serial,
                                      async_save=cfg.async_save,
                                      slots=self._optimizer
                                      .slot_descriptor())
        finally:
            # safe even under async_save: the device->host snapshot is
            # materialized before save_scope returns, so the scope keys
            # may be dropped (and buffers donated) immediately
            self.scope.drop(_RNG_STEP_KEY)
            self.scope.drop(_WORLD_KEY)
        if handle is not None:
            # overlapped save: checksum + serialize + commit run while
            # the next epoch trains; pruning waits for the commit
            self._pending_save = (serial, handle)
            return
        self._prune(serial)

    def _prune(self, serial: int):
        cfg = self._ckpt_cfg
        # Prune old serial dirs beyond max_num_checkpoints — only AFTER
        # the new checkpoint committed (a failed save raises above and
        # skips pruning), and never the last resumable state: the keep
        # window holds the newest VALID serials, and invalid serials are
        # reclaimed only when a NEWER valid one exists (so a transient
        # validation failure can never delete the sole copy). Foreign
        # entries like checkpoint_best are not ours to touch.
        # The window membership below uses the cheap structural check;
        # resume demands checksums too, so first prove the JUST-written
        # serial to the full standard (page-cache read) — if even it
        # fails, something is deeply wrong with the storage: keep
        # everything rather than prune by a weaker validity definition.
        if not _ckpt.validate_checkpoint(cfg.checkpoint_dir, serial):
            warnings.warn(
                f"checkpoint_{serial} failed checksum validation right "
                f"after commit; skipping pruning", RuntimeWarning)
            return
        serials = sorted(
            _ckpt.available_steps(cfg.checkpoint_dir), reverse=True)
        valid = [s for s in serials
                 if _ckpt.validate_checkpoint(cfg.checkpoint_dir, s,
                                              verify_checksums=False)]
        keep = set(valid[:cfg.max_num_checkpoints])
        # the serial just written and checksum-PROVEN above is kept
        # unconditionally: a structurally-complete-but-bit-rotted newer
        # serial (possible after auto-resume lowered the numbering)
        # must not crowd the one certainly-good checkpoint out
        keep.add(serial)
        newest_valid = valid[0] if valid else None
        for s in serials:
            if s in keep:
                continue
            if s in valid or (newest_valid is not None
                              and s < newest_valid):
                shutil.rmtree(
                    os.path.join(cfg.checkpoint_dir, f"checkpoint_{s}"),
                    ignore_errors=True,
                )

    # --- the loop (reference: contrib/trainer.py:379) ---

    def stop(self):
        self._stopped = True

    def _prefetch_plan(self):
        """(depth, feed sharding) for the step loop's DeviceLoader:
        depth 0 = synchronous DataFeeder staging (the prefetch_depth=0
        opt-out). Multi-host jobs always take the sync path — their
        per-process feed shards must go through shard_inputs' global-
        array assembly, which a plain device_put would bypass. Single-
        process data-parallel runs prefetch straight onto the batch
        sharding so the jit never re-places the feeds."""
        from paddle_tpu import flags as _flags
        import jax

        depth = int(_flags.get_flag("prefetch_depth"))
        if depth <= 0 or jax.process_count() > 1:
            return 0, None
        from paddle_tpu.compiler import CompiledProgram

        sharding = None
        rp = self._run_program
        if isinstance(rp, CompiledProgram) and rp.mesh is not None:
            sharding = rp._batch_sharding()
        return depth, sharding

    def _batches(self, reader, feeder, feed_order, depth, sharding):
        """One epoch's feed-dict stream: a DeviceLoader prefetching
        ``depth`` device-resident batches ahead (batch N+1's device_put
        overlaps batch N's device phase; batch assembly runs in the
        worker OFF the verdict's critical path), or the synchronous
        DataFeeder path when prefetch is off. Returns (iterator,
        loader-or-None); the caller must close the loader."""
        if depth <= 0:
            return (feeder.feed(b) for b in reader()), None
        loader = DeviceLoader(
            lambda: (feeder.feed(b, critical_path=False)
                     for b in reader()),
            list(feed_order), depth=depth, sharding=sharding)
        return iter(loader), loader

    def train(
        self,
        num_epochs: int,
        event_handler: Optional[Callable] = None,
        reader: Optional[Callable] = None,
        feed_order: Optional[Sequence[str]] = None,
        log_time_attribution: bool = True,
    ):
        if reader is None or feed_order is None:
            raise ValueError(
                "Trainer.train needs `reader` (a callable returning an "
                "iterable of batches) and `feed_order` (feed var names)"
            )
        cfg = self._ckpt_cfg
        retries = cfg.max_resume_retries if cfg is not None else 0
        while True:
            try:
                return self._train_impl(
                    num_epochs, event_handler, reader, feed_order,
                    log_time_attribution)
            except (KeyboardInterrupt, SystemExit):
                # deliberately NOT settled: an interrupt should not block
                # on a background commit; the staging protocol already
                # guarantees valid-or-absent serials if the daemon thread
                # dies mid-commit with the process
                raise
            except Exception as e:  # noqa: BLE001 — auto-resume budget
                if retries <= 0:
                    # land the overlapped save before handing control to
                    # caller-side recovery: its directory scan must not
                    # race the background rename, and its error must not
                    # vanish into an atexit warning
                    self._settle_pending_save()
                    raise
                retries -= 1
                self._start_epoch = 0
                self._stopped = False
                self._settle_pending_save()
                with scope_guard(self.scope):
                    step = self._maybe_resume()
                if step is None:
                    raise  # nothing valid to resume from
                warnings.warn(
                    f"training failed ({type(e).__name__}: {e}); "
                    f"auto-resuming from checkpoint_{step} "
                    f"({retries} retries left)", RuntimeWarning)
                if not self._last_resume_resized:
                    # a resized resume already counted itself into the
                    # resized="true" cell in _maybe_resume
                    _M_RESUMES.inc(labels={"resized": "false"})

    def _train_impl(
        self,
        num_epochs: int,
        event_handler: Optional[Callable],
        reader: Callable,
        feed_order: Sequence[str],
        log_time_attribution: bool,
    ):
        handler = event_handler or (lambda e: None)
        feeder = DataFeeder(
            [self.main_program.global_block().var(n) for n in feed_order]
        )
        fetch = [self.loss] + self.train_outputs[1:]
        depth, sharding = self._prefetch_plan()
        lazy = depth > 0  # prefetch on: fetches materialize lazily too
        with scope_guard(self.scope):
            for epoch in range(self._start_epoch, num_epochs):
                if self._stopped:
                    break
                handler(BeginEpochEvent(epoch))
                metrics = None
                batches, loader = self._batches(reader, feeder,
                                                feed_order, depth,
                                                sharding)
                try:
                    with _monitor.span("trainer.epoch"):
                        for step, feed in enumerate(batches):
                            if self._stopped:
                                break
                            _F_READER_NEXT.hit()
                            handler(BeginStepEvent(epoch, step))
                            # the step IS the collective in fleet jobs
                            # (GSPMD all-reduces ride inside the
                            # compiled program): a dead peer shows up as
                            # THIS call never returning, which the
                            # watchdog turns into a stall record with
                            # the span stack
                            with _monitor.span("trainer.step"), \
                                    _monitor.stall_guard("trainer.step"):
                                metrics = self.exe.run(
                                    self._run_program,
                                    feed=feed,
                                    fetch_list=fetch,
                                    async_fetch=lazy,
                                )
                            handler(EndStepEvent(epoch, step, metrics))
                            if _monitor.enabled():
                                _M_TRAIN_STEPS.inc()
                                # the loss gauge forces the deferred
                                # fetch to land; with async fetch it
                                # rides the sampled cadence (or a fixed
                                # period with phases off) so unsampled
                                # steps keep the overlap. An event
                                # handler that already read the metrics
                                # costs nothing extra (ready=True).
                                if metrics and (
                                        not lazy
                                        or getattr(metrics, "ready",
                                                   True)
                                        or _monitor.phases_sampled(
                                            self.exe._step - 1)
                                        or (not _monitor.phases_active()
                                            and step % 16 == 0)):
                                    v = np.asarray(metrics[0])
                                    if v.size:
                                        _M_LOSS.set(float(v.ravel()[0]))
                        if lazy and metrics is not None:
                            # epoch boundary: land the last deferred
                            # fetch so a deferred device error surfaces
                            # inside the epoch's failure budget (auto-
                            # resume), not during checkpointing
                            metrics.wait()
                finally:
                    if loader is not None:
                        # abandoned-consumer hygiene: a raising step /
                        # stop() must release the prefetch worker and
                        # its pinned device batches
                        loader.close()
                if self._stopped:
                    # stopped mid-epoch: the epoch did NOT complete — no
                    # EndEpochEvent and no checkpoint, or resume would
                    # silently skip the untrained remainder of it.
                    break
                handler(EndEpochEvent(epoch))
                _M_EPOCHS.inc()
                if log_time_attribution and _monitor.enabled():
                    # the time-attribution plane's answer to "why was
                    # this epoch slow": which side of the machine the
                    # last window of steps spent its wall time on
                    # (None unless the step_phases plane is producing
                    # verdicts; log_time_attribution=False silences it)
                    b = _monitor.boundedness()
                    if b is not None:
                        s = b["shares"]
                        print(
                            f"[trainer] epoch {epoch} time attribution: "
                            f"{b['verdict']} (input {s['input']:.0%}, "
                            f"dispatch {s['dispatch']:.0%}, device "
                            f"{s['device']:.0%} over last {b['steps']} "
                            f"steps)")
                if _monitor.enabled():
                    # one fleet-summary line per epoch, rank 0 only
                    # (fleet_monitor returns None for single-worker
                    # jobs and non-aggregator ranks); independent of
                    # log_time_attribution, which silences only the
                    # attribution line above; never fails an epoch for
                    # a telemetry hiccup
                    try:
                        from paddle_tpu import fleet_monitor as _fm

                        line = _fm.epoch_summary_line()
                        if line:
                            print(f"[trainer] epoch {epoch} {line}")
                    except Exception as e:  # noqa: BLE001
                        warnings.warn(
                            f"fleet epoch summary skipped: {e!r}",
                            RuntimeWarning)
                if (
                    self._ckpt_cfg is not None
                    and (epoch + 1) % self._ckpt_cfg.epoch_interval == 0
                ):
                    with _monitor.span("trainer.checkpoint"):
                        self._save_checkpoint(epoch + 1)
                    _M_CKPTS.inc()
            # train() returns only with every overlapped save durable —
            # a background failure surfaces HERE, inside the auto-resume
            # budget, not as a warning after the fact
            self._wait_pending_save()

    def test(self, reader, feed_order: Sequence[str]):
        feeder = DataFeeder(
            [self.main_program.global_block().var(n) for n in feed_order]
        )
        fetch = [self.loss] + self.train_outputs[1:]
        depth, _ = self._prefetch_plan()
        totals = None
        count = 0
        with scope_guard(self.scope):
            # the test program runs uncompiled (default placement):
            # prefetch without the train batch sharding
            batches, loader = self._batches(reader, feeder, feed_order,
                                            depth, None)
            try:
                for feed in batches:
                    vals = self.exe.run(
                        self.test_program, feed=feed,
                        fetch_list=fetch,
                    )
                    vals = [np.asarray(v, dtype=np.float64) for v in vals]
                    totals = (
                        vals
                        if totals is None
                        else [t + v for t, v in zip(totals, vals)]
                    )
                    count += 1
            finally:
                if loader is not None:
                    loader.close()
        if totals is None:
            return []
        return [float(t / count) for t in totals]

    def save_params(self, dirname: str):
        with scope_guard(self.scope):
            _io.save_persistables(self.exe, dirname, self.main_program)

    def save_inference_model(self, dirname: str, feeded_var_names,
                             target_vars):
        with scope_guard(self.scope):
            _io.save_inference_model(
                dirname, feeded_var_names, target_vars, self.exe,
                self.test_program,
            )
