"""Auto-derived gradient kernels.

The reference hand-writes a C++ grad kernel and a GradOpDescMaker per op
(reference: framework/grad_op_desc_maker.h; e.g. operators/mul_op.cc). Here a
``<type>_grad`` kernel is derived mechanically from the forward JAX kernel
with ``jax.vjp``: the grad op re-traces the forward inside the same XLA
computation, XLA CSEs the duplicated forward work, and rematerialization
policy is left to the compiler (HBM-friendly; see SURVEY.md section 7).

Grad op desc convention (produced by backward.append_backward):
- inputs:  every forward input slot (same slot names), every forward output
  slot, plus ``GRAD::<out_slot>`` slots holding output gradients.
- outputs: ``GRAD::<in_slot>`` slots holding input gradients, aligned
  positionally with the forward input slot; "" marks a hole (no grad needed).
- attrs:   forward attrs + ``fwd_input_slots``/``fwd_output_slots`` +
  ``forward_op_idx`` (so stochastic ops replay the same PRNG key).
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import OpDef

GRAD_SLOT_PREFIX = "GRAD::"
_GRAD_META_ATTRS = ("fwd_input_slots", "fwd_output_slots", "forward_op_idx")


def _floatp(x) -> bool:
    try:
        return jnp.issubdtype(jnp.result_type(x), jnp.floating)
    except Exception:
        return False


def make_grad_compute(fwd: OpDef):
    """Build the compute fn for the auto grad op of ``fwd``."""

    def grad_compute(ins: Dict[str, List[Any]], attrs: Dict[str, Any], rng=None):
        in_slots = list(attrs["fwd_input_slots"])
        out_slots = list(attrs["fwd_output_slots"])
        fwd_attrs = {k: v for k, v in attrs.items() if k not in _GRAD_META_ATTRS}
        rng_kwargs = {"rng": rng} if fwd.needs_rng else {}

        fwd_ins = {s: list(ins.get(s, [])) for s in in_slots}

        # Which (slot, position) entries are differentiable.
        diff_keys: List[tuple] = []
        for s in in_slots:
            if fwd.diff_inputs is not None and s not in fwd.diff_inputs:
                continue
            for i, x in enumerate(fwd_ins[s]):
                if x is not None and _floatp(x):
                    diff_keys.append((s, i))

        # vjp over a pytree-valued forward: slot arity falls out of the
        # returned structure, so the forward is traced exactly once here
        # (the round-1 arity "probe" doubled trace size and compile time).
        def fwd_fn(diff_vals):
            merged = {s: list(v) for s, v in fwd_ins.items()}
            for (s, i), v in zip(diff_keys, diff_vals):
                merged[s][i] = v
            outs = fwd.compute(merged, fwd_attrs, **rng_kwargs)
            return {o: [y for y in outs.get(o, [])] for o in out_slots}

        primals = [fwd_ins[s][i] for (s, i) in diff_keys]
        out_tree, vjp_fn = jax.vjp(fwd_fn, primals)

        # Cotangents mirroring out_tree; zeros where the program did not
        # provide a gradient for an output.
        cotangents = {}
        for o in out_slots:
            gslot = ins.get(GRAD_SLOT_PREFIX + o, [])
            cots = []
            for i, y in enumerate(out_tree[o]):
                if y is None:
                    cots.append(None)
                    continue
                g = gslot[i] if i < len(gslot) else None
                if g is None:
                    g = jnp.zeros(jnp.shape(y), jnp.result_type(y))
                else:
                    g = jnp.asarray(g, jnp.result_type(y))
                    if jnp.shape(g) != jnp.shape(y):
                        g = jnp.broadcast_to(g, jnp.shape(y))
                cots.append(g)
            cotangents[o] = cots

        (grads,) = vjp_fn(cotangents)

        outs: Dict[str, List[Any]] = {}
        for (s, i), g in zip(diff_keys, grads):
            lst = outs.setdefault(GRAD_SLOT_PREFIX + s, [None] * len(fwd_ins[s]))
            lst[i] = g
        return outs

    grad_compute.__name__ = f"{fwd.type}_grad_compute"
    return grad_compute
