"""Shared op-list interpreter used by block lowering and control-flow ops.

The reference executes sub-blocks of control-flow ops by recursively invoking
its op-by-op Executor on the sub-scope (reference:
operators/controlflow/while_op.cc:43, conditional_block_op.cc:75). Here the
same role is played by tracing the sub-block's registered JAX kernels into the
enclosing XLA computation: ``exec_ops`` runs an ordered op list against a
functional environment (name -> array), and control-flow ops call it inside
``lax.while_loop`` / ``lax.cond`` / ``lax.scan`` closures so the whole nest
compiles to one XLA program.

AMP (bf16 activation-stream) casting is applied here so sub-blocks behave the
same as top-level blocks under mixed precision.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from paddle_tpu import monitor as _monitor
from paddle_tpu.core import autodiff
from paddle_tpu.core.registry import GRAD_OP_SUFFIX, OpDef, get_op_def, has_op

# Ops lowered into XLA programs. exec_ops runs at TRACE time (cached
# compiled steps never re-enter Python), so these count per COMPILE —
# a growing rate mid-training means recompiles, the classic silent
# step-time killer this telemetry exists to surface.
_M_OPS_LOWERED = _monitor.counter(
    "pt_ops_lowered_total", "ops traced into XLA programs (per compile)")
_M_BLOCKS_TRACED = _monitor.counter(
    "pt_blocks_traced_total",
    "op-list traces (top-level blocks + control-flow sub-blocks)")

# MXU-heavy ops that run in bfloat16 under AMP: every f32 input (master
# weights included) is cast to bf16 and the output STAYS bf16, so the whole
# activation stream between matmuls lives in bf16 — halving HBM traffic,
# which profiling showed was the step-time bound (casting back to f32 after
# each matmul made every matmul bandwidth-limited). The analog of the
# reference's AMP cast insertion (reference:
# contrib/mixed_precision/fp16_utils.py:67), but bf16 needs no loss scaling
# (SURVEY.md section 7 phase 4).
AMP_OP_TYPES = {
    "mul",
    "matmul",
    "conv2d",
    "depthwise_conv2d",
    "conv2d_transpose",
    "scaled_dot_product_attention",
}

# Precision-following ops: when any input is already bf16, their remaining
# f32 float inputs (params like layer-norm scale, residual branches) are
# cast down so the op does not silently promote the stream back to f32.
# Numerically sensitive reductions inside these kernels (layer-norm
# mean/var) compute in f32 internally regardless of input dtype.
AMP_FLOW_OP_TYPES = {
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "scale",
    "dropout",
    "relu",
    "gelu",
    "tanh",
    "sigmoid",
    "softmax",
    "concat",
    "stack",
}
# (layer_norm is absent: its kernel handles mixed dtypes itself — f32
# internal math, x-dtype output — so no input casting is wanted.)

# Slots that must stay f32 under AMP (saved numerical stats, not streams).
AMP_KEEP_F32_SLOTS = frozenset({"Lse", "GRAD::Lse"})

# Whether AMP casting is active for the block currently being traced.
# Control-flow op computes read this so sub-blocks inherit the policy of
# the block that contains them (a contextvar because op computes only
# receive (ins, attrs)).
_AMP_ACTIVE: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "paddle_tpu_amp_active", default=False
)


def amp_active() -> bool:
    return _AMP_ACTIVE.get()


def set_amp_active(flag: bool):
    return _AMP_ACTIVE.set(bool(flag))


# SPMD context for ops that need explicit shard_map collectives (ring
# attention over a context axis, psum-sharded embedding tables, expert-
# parallel MoE all_to_all dispatch) rather than relying on GSPMD
# propagation. Set by the Executor while tracing a program compiled with a
# DistributedStrategy that declares those axes; kernels read it at trace
# time. An ``SpmdCtx`` or None.
SpmdCtx = collections.namedtuple(
    "SpmdCtx", ["mesh", "context_axis", "table_axis", "data_axis",
                "expert_axis", "pipe_axis", "pipe_micro"]
)

_SPMD_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "paddle_tpu_spmd_ctx", default=None
)


def spmd_ctx():
    return _SPMD_CTX.get()


def set_spmd_ctx(ctx):
    return _SPMD_CTX.set(ctx)


@contextlib.contextmanager
def spmd_ctx_scope(strategy):
    """Activate a DistributedStrategy's SPMD context (ring attention /
    sharded tables / expert-parallel MoE) for the enclosed trace. The
    single place that builds the context — kernels read fields by name."""
    ctx = None
    if strategy is not None and (
        strategy.context_axis
        or strategy.table_axis
        or getattr(strategy, "expert_axis", None)
        or getattr(strategy, "pipe_axis", None)
    ):
        # Multi-slice: the batch axis kernels see is the COMPOSED
        # (slice, data) tuple so shard_map specs and collective axis
        # lists span both — the batch is sharded over their product
        # (strategy.batch_sharding). Single-axis stays a plain string.
        data_axis = strategy.data_axis
        slice_axis = getattr(strategy, "slice_axis", None)
        if slice_axis is not None:
            data_axis = ((slice_axis, data_axis) if data_axis is not None
                         else slice_axis)
        ctx = SpmdCtx(
            mesh=strategy.mesh,
            context_axis=strategy.context_axis,
            table_axis=strategy.table_axis,
            data_axis=data_axis,
            expert_axis=getattr(strategy, "expert_axis", None),
            pipe_axis=getattr(strategy, "pipe_axis", None),
            pipe_micro=getattr(strategy, "pipe_micro", None),
        )
    tok = _SPMD_CTX.set(ctx)
    try:
        yield
    finally:
        _SPMD_CTX.reset(tok)


def _is_f32(v):
    return v is not None and hasattr(v, "dtype") and v.dtype == jnp.float32


def _is_bf16(v):
    return v is not None and hasattr(v, "dtype") and v.dtype == jnp.bfloat16


def _amp_cast_ins(ins):
    out = {}
    for slot, vals in ins.items():
        if slot in AMP_KEEP_F32_SLOTS:
            out[slot] = list(vals)
            continue
        out[slot] = [
            v.astype(jnp.bfloat16) if _is_f32(v) else v for v in vals
        ]
    return out


def _amp_flow_cast_ins(ins):
    """Cast f32 inputs to bf16 only when the op already consumes bf16."""
    has_bf16 = any(_is_bf16(v) for vals in ins.values() for v in vals)
    if not has_bf16:
        return ins
    return _amp_cast_ins(ins)


def resolve_op_def(op_type: str) -> OpDef:
    """Resolve an op type to its kernel, deriving ``*_grad`` on demand."""
    if has_op(op_type):
        return get_op_def(op_type)
    if op_type.endswith(GRAD_OP_SUFFIX):
        base = op_type[: -len(GRAD_OP_SUFFIX)]
        if has_op(base):
            fwd = get_op_def(base)
            return OpDef(
                type=op_type,
                compute=autodiff.make_grad_compute(fwd),
                needs_rng=fwd.needs_rng,
                no_grad=True,
            )
    return get_op_def(op_type)  # raises with a helpful message


def exec_ops(
    ops,
    env: Dict[str, Any],
    key=None,
    amp: Optional[bool] = None,
    op_defs: Optional[List[OpDef]] = None,
):
    """Execute an op list against ``env`` in place; returns ``env``.

    ``key`` is the PRNG key for this execution; per-op keys are derived by
    folding in the op's ``forward_op_idx`` attr (so a grad op replays its
    forward's key) or its position.
    """
    if amp is None:
        amp = amp_active()
    if op_defs is None:
        op_defs = [resolve_op_def(op.type) for op in ops]
    if _monitor.enabled():
        _M_BLOCKS_TRACED.inc()
        _M_OPS_LOWERED.inc(len(ops))
    for idx, (op, opdef) in enumerate(zip(ops, op_defs)):
        ins = {
            slot: [env[n] if n else None for n in names]
            for slot, names in op.inputs.items()
        }
        kwargs = {}
        if opdef.needs_rng:
            fold = op.attrs.get("forward_op_idx", idx)
            kwargs["rng"] = (
                jax.random.fold_in(key, fold) if key is not None else None
            )
        base_type = (
            op.type[: -len(GRAD_OP_SUFFIX)]
            if op.type.endswith(GRAD_OP_SUFFIX)
            else op.type
        )
        if amp and base_type in AMP_OP_TYPES:
            ins = _amp_cast_ins(ins)
        elif amp and base_type in AMP_FLOW_OP_TYPES:
            ins = _amp_flow_cast_ins(ins)
        outs = opdef.compute(ins, dict(op.attrs), **kwargs)
        for slot, names in op.outputs.items():
            vals = outs.get(slot, [])
            for i, n in enumerate(names):
                if not n:
                    continue
                v = vals[i] if i < len(vals) else None
                if v is not None:
                    env[n] = v
    return env
