"""Block -> XLA lowering.

This replaces the reference's op-by-op interpreters (the single-device
``Executor::Run`` hot loop, reference: framework/executor.cc:149, and the
SSA-graph dataflow executors, reference:
framework/details/threaded_ssa_graph_executor.cc:140). On TPU the right
execution model is *whole-program compilation*: a block is traced once into a
single JAX function over a functional environment (name -> array), jitted by
XLA, and run with donated parameter buffers. Scheduling, fusion, memory reuse
(reference: framework/ir/memory_optimize_pass/*) and stream assignment are
all delegated to XLA.

The in-repo precedent in the reference for this design is its nGraph
subgraph engine (reference: operators/ngraph/ngraph_engine.cc), generalized
here to the whole program.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import autodiff
from paddle_tpu.core.registry import GRAD_OP_SUFFIX, OpDef, get_op_def, has_op
from paddle_tpu.framework import Block, Program

# Ops handled by the lowering itself rather than a registered kernel.
_STRUCTURAL_OPS = ("feed", "fetch")

# MXU-heavy ops that run in bfloat16 under AMP: every f32 input (master
# weights included) is cast to bf16 and the output STAYS bf16, so the whole
# activation stream between matmuls lives in bf16 — halving HBM traffic,
# which profiling showed was the step-time bound (casting back to f32 after
# each matmul made every matmul bandwidth-limited). The analog of the
# reference's AMP cast insertion (reference:
# contrib/mixed_precision/fp16_utils.py:67), but bf16 needs no loss scaling
# (SURVEY.md section 7 phase 4).
AMP_OP_TYPES = {
    "mul",
    "matmul",
    "conv2d",
    "depthwise_conv2d",
    "conv2d_transpose",
    "scaled_dot_product_attention",
}

# Precision-following ops: when any input is already bf16, their remaining
# f32 float inputs (params like layer-norm scale, residual branches) are
# cast down so the op does not silently promote the stream back to f32.
# Numerically sensitive reductions inside these kernels (layer-norm
# mean/var) compute in f32 internally regardless of input dtype.
AMP_FLOW_OP_TYPES = {
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "scale",
    "dropout",
    "relu",
    "gelu",
    "tanh",
    "sigmoid",
    "softmax",
    "concat",
    "stack",
}
# (layer_norm is absent: its kernel handles mixed dtypes itself — f32
# internal math, x-dtype output — so no input casting is wanted.)


def _is_f32(v):
    return v is not None and hasattr(v, "dtype") and v.dtype == jnp.float32


def _is_bf16(v):
    return v is not None and hasattr(v, "dtype") and v.dtype == jnp.bfloat16


# Slots that must stay f32 under AMP (saved numerical stats, not streams).
AMP_KEEP_F32_SLOTS = frozenset({"Lse", "GRAD::Lse"})


def _amp_cast_ins(ins):
    out = {}
    for slot, vals in ins.items():
        if slot in AMP_KEEP_F32_SLOTS:
            out[slot] = list(vals)
            continue
        out[slot] = [
            v.astype(jnp.bfloat16) if _is_f32(v) else v for v in vals
        ]
    return out


def _amp_flow_cast_ins(ins):
    """Cast f32 inputs to bf16 only when the op already consumes bf16."""
    has_bf16 = any(_is_bf16(v) for vals in ins.values() for v in vals)
    if not has_bf16:
        return ins
    return _amp_cast_ins(ins)


def resolve_op_def(op_type: str) -> OpDef:
    """Resolve an op type to its kernel, deriving ``*_grad`` on demand."""
    if has_op(op_type):
        return get_op_def(op_type)
    if op_type.endswith(GRAD_OP_SUFFIX):
        base = op_type[: -len(GRAD_OP_SUFFIX)]
        if has_op(base):
            fwd = get_op_def(base)
            return OpDef(
                type=op_type,
                compute=autodiff.make_grad_compute(fwd),
                needs_rng=fwd.needs_rng,
                no_grad=True,
            )
    return get_op_def(op_type)  # raises with a helpful message


@dataclasses.dataclass
class LoweredBlock:
    """A compiled block: ``fn(state, feeds, key) -> (fetches, new_state)``.

    ``state_in_names``: persistable vars read before being written — fetched
    from the Scope (and donated to XLA). ``state_out_names``: every
    state-in var (donation means its buffer must be returned even if
    unchanged) plus every persistable var the block writes.
    """

    fn: Callable
    state_in_names: Tuple[str, ...]
    state_out_names: Tuple[str, ...]
    feed_names: Tuple[str, ...]
    fetch_names: Tuple[str, ...]
    needs_rng: bool


def analyze_state(
    block: Block, feed_names: Sequence[str]
) -> Tuple[List[str], List[str]]:
    """(state_in, state_out) persistable-var lists for the block.

    The functional analog of the reference's Scope residency
    (reference: framework/scope.h:45).
    """
    feed = set(feed_names)
    written: set = set()
    state_in: List[str] = []
    seen_in: set = set()
    written_persistable: List[str] = []

    def is_persistable(name: str) -> bool:
        v = block._find_var_recursive(name)
        return v is not None and v.persistable

    for op in block.ops:
        for name in op.input_arg_names:
            if not name or name in feed or name in written or name in seen_in:
                continue
            if is_persistable(name):
                state_in.append(name)
                seen_in.add(name)
        for name in op.output_arg_names:
            if name and name not in written:
                written.add(name)
                if is_persistable(name):
                    written_persistable.append(name)
    state_out = list(state_in)
    out_seen = set(state_in)
    for name in written_persistable:
        if name not in out_seen:
            state_out.append(name)
            out_seen.add(name)
    return state_in, state_out


def lower_block(
    program: Program,
    block_idx: int,
    feed_names: Sequence[str],
    fetch_names: Sequence[str],
    amp: bool = False,
) -> LoweredBlock:
    block = program.blocks[block_idx]
    amp = amp or getattr(program, "_amp", False)
    state_in, state_out = analyze_state(block, feed_names)
    state_in, state_out = tuple(state_in), tuple(state_out)
    feed_names = tuple(feed_names)
    fetch_names = tuple(fetch_names)

    # Resolve all kernels up front so unknown ops fail at compile time.
    op_defs = [resolve_op_def(op.type) for op in block.ops]
    needs_rng = any(d.needs_rng for d in op_defs)

    ops = list(block.ops)

    def run_block(state: Dict[str, Any], feeds: Dict[str, Any], key):
        env: Dict[str, Any] = {}
        env.update(state)
        env.update(feeds)
        for idx, (op, opdef) in enumerate(zip(ops, op_defs)):
            ins = {
                slot: [env[n] if n else None for n in names]
                for slot, names in op.inputs.items()
            }
            kwargs = {}
            if opdef.needs_rng:
                fold = op.attrs.get("forward_op_idx", idx)
                kwargs["rng"] = jax.random.fold_in(key, fold)
            base_type = (
                op.type[: -len(GRAD_OP_SUFFIX)]
                if op.type.endswith(GRAD_OP_SUFFIX)
                else op.type
            )
            if amp and base_type in AMP_OP_TYPES:
                ins = _amp_cast_ins(ins)
            elif amp and base_type in AMP_FLOW_OP_TYPES:
                ins = _amp_flow_cast_ins(ins)
            outs = opdef.compute(ins, dict(op.attrs), **kwargs)
            for slot, names in op.outputs.items():
                vals = outs.get(slot, [])
                for i, n in enumerate(names):
                    if not n:
                        continue
                    v = vals[i] if i < len(vals) else None
                    if v is not None:
                        env[n] = v
        fetches = [env[n] for n in fetch_names]
        new_state = {n: env[n] for n in state_out}
        return fetches, new_state

    return LoweredBlock(
        fn=run_block,
        state_in_names=state_in,
        state_out_names=state_out,
        feed_names=feed_names,
        fetch_names=fetch_names,
        needs_rng=needs_rng,
    )


def jit_lowered(
    lowered: LoweredBlock,
    in_shardings=None,
    out_shardings=None,
    donate_state: bool = True,
):
    """Wrap the traced block in jax.jit with parameter-buffer donation."""
    kwargs: Dict[str, Any] = {}
    if donate_state:
        kwargs["donate_argnums"] = (0,)
    if in_shardings is not None:
        kwargs["in_shardings"] = in_shardings
    if out_shardings is not None:
        kwargs["out_shardings"] = out_shardings
    return jax.jit(lowered.fn, **kwargs)
