"""Block -> XLA lowering.

This replaces the reference's op-by-op interpreters (the single-device
``Executor::Run`` hot loop, reference: framework/executor.cc:149, and the
SSA-graph dataflow executors, reference:
framework/details/threaded_ssa_graph_executor.cc:140). On TPU the right
execution model is *whole-program compilation*: a block is traced once into a
single JAX function over a functional environment (name -> array), jitted by
XLA, and run with donated parameter buffers. Scheduling, fusion, memory reuse
(reference: framework/ir/memory_optimize_pass/*) and stream assignment are
all delegated to XLA.

The in-repo precedent in the reference for this design is its nGraph
subgraph engine (reference: operators/ngraph/ngraph_engine.cc), generalized
here to the whole program.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.framework import Block, Program

# Ops handled by the lowering itself rather than a registered kernel.
_STRUCTURAL_OPS = ("feed", "fetch")

# AMP policy + the op-list interpreter live in core/interp.py (shared with
# control-flow ops, which execute sub-blocks inside lax closures). Re-exported
# here for compatibility.
from paddle_tpu.core.interp import (  # noqa: E402,F401
    AMP_FLOW_OP_TYPES,
    AMP_KEEP_F32_SLOTS,
    AMP_OP_TYPES,
    exec_ops,
    resolve_op_def,
    set_amp_active,
)


@dataclasses.dataclass
class LoweredBlock:
    """A compiled block: ``fn(state, feeds, key) -> (fetches, new_state)``.

    ``state_in_names``: persistable vars read before being written — fetched
    from the Scope (and donated to XLA). ``state_out_names``: every
    state-in var (donation means its buffer must be returned even if
    unchanged) plus every persistable var the block writes.
    """

    fn: Callable
    state_in_names: Tuple[str, ...]
    state_out_names: Tuple[str, ...]
    feed_names: Tuple[str, ...]
    fetch_names: Tuple[str, ...]
    needs_rng: bool
    # op type -> count over the lowered block: the op-lowering histogram
    # carried into compile reports (and the estimate fallback when XLA
    # cost analysis is unavailable)
    op_histogram: Optional[Dict[str, int]] = None


def analyze_state(
    block: Block, feed_names: Sequence[str]
) -> Tuple[List[str], List[str]]:
    """(state_in, state_out) persistable-var lists for the block.

    The functional analog of the reference's Scope residency
    (reference: framework/scope.h:45).
    """
    feed = set(feed_names)
    written: set = set()
    state_in: List[str] = []
    seen_in: set = set()
    written_persistable: List[str] = []

    def is_persistable(name: str) -> bool:
        v = block._find_var_recursive(name)
        return v is not None and v.persistable

    for op in block.ops:
        for name in op.input_arg_names:
            if not name or name in feed or name in written or name in seen_in:
                continue
            if is_persistable(name):
                state_in.append(name)
                seen_in.add(name)
        for name in op.output_arg_names:
            if name and name not in written:
                written.add(name)
                if is_persistable(name):
                    written_persistable.append(name)
    state_out = list(state_in)
    out_seen = set(state_in)
    for name in written_persistable:
        if name not in out_seen:
            state_out.append(name)
            out_seen.add(name)
    return state_in, state_out


def lower_block(
    program: Program,
    block_idx: int,
    feed_names: Sequence[str],
    fetch_names: Sequence[str],
    amp: bool = False,
) -> LoweredBlock:
    block = program.blocks[block_idx]
    amp = amp or getattr(program, "_amp", False)
    state_in, state_out = analyze_state(block, feed_names)
    state_in, state_out = tuple(state_in), tuple(state_out)
    feed_names = tuple(feed_names)
    fetch_names = tuple(fetch_names)

    # Resolve all kernels up front so unknown ops fail at compile time.
    op_defs = [resolve_op_def(op.type) for op in block.ops]
    needs_rng = any(d.needs_rng for d in op_defs)

    ops = list(block.ops)

    def run_block(state: Dict[str, Any], feeds: Dict[str, Any], key):
        env: Dict[str, Any] = {}
        env.update(state)
        env.update(feeds)
        tok = set_amp_active(amp)
        try:
            exec_ops(ops, env, key=key, amp=amp, op_defs=op_defs)
        finally:
            from paddle_tpu.core.interp import _AMP_ACTIVE

            _AMP_ACTIVE.reset(tok)
        fetches = [env[n] for n in fetch_names]
        new_state = {n: env[n] for n in state_out}
        return fetches, new_state

    op_histogram: Dict[str, int] = {}
    for op in ops:
        op_histogram[op.type] = op_histogram.get(op.type, 0) + 1

    return LoweredBlock(
        fn=run_block,
        state_in_names=state_in,
        state_out_names=state_out,
        feed_names=feed_names,
        fetch_names=fetch_names,
        needs_rng=needs_rng,
        op_histogram=op_histogram,
    )


def jit_lowered(
    lowered: LoweredBlock,
    in_shardings=None,
    out_shardings=None,
    donate_state: bool = True,
    fold_step: bool = False,
):
    """Wrap the traced block in jax.jit with parameter-buffer donation.

    ``fold_step``: the returned fn has signature
    ``fn(state, feeds, base_key, step)`` and derives the per-step key with
    ``fold_in`` INSIDE the compiled computation — host-side key derivation
    costs two extra device dispatches per step (measured ~10 ms through
    the hosted-TPU tunnel).

    Entry layouts stay at jax defaults deliberately: AUTO state layouts
    were measured <1% on ResNet-50 (relayout copies are async-prefetched
    off the critical path) and executables with custom entry layouts
    deserialize broken from the persistent XLA compilation cache — see
    BASELINE.md "ResNet-50 roofline analysis"."""
    kwargs: Dict[str, Any] = {}
    if donate_state:
        kwargs["donate_argnums"] = (0,)
    if in_shardings is not None:
        kwargs["in_shardings"] = in_shardings
    if out_shardings is not None:
        kwargs["out_shardings"] = out_shardings
    if not fold_step:
        return jax.jit(lowered.fn, **kwargs)

    def step_fn(state, feeds, base_key, step):
        return lowered.fn(state, feeds, jax.random.fold_in(base_key, step))

    return jax.jit(step_fn, **kwargs)


def jit_lowered_multi(lowered: LoweredBlock, n_feeds: int,
                      track_nonfinite: bool = False,
                      donate_state: bool = True):
    """Compile ``n_steps`` training steps as ONE XLA program.

    The returned fn has signature
    ``fn(state, feeds_stacked, base_key, start_step, n_steps)`` where
    ``feeds_stacked`` carries each feed with a leading [n_feeds] axis;
    step ``i`` consumes feed ``i % n_feeds`` and folds ``start_step + i``
    into the PRNG key, so the random stream is bit-identical to
    ``n_steps`` successive single-step calls. One host dispatch per
    window instead of one per step — the whole-loop-compiled analog of
    the reference's ``Executor::RunFromDataset`` hot loop
    (reference: framework/executor.cc:120-147, device_worker.h:94
    ``TrainFiles`` — thread-resident step loops without per-step Python);
    through the hosted-TPU tunnel the per-dispatch host cost is ~1.7 ms,
    which at ResNet-50 step times is ~5% of wall clock.

    ``track_nonfinite``: carry an in-loop finiteness scan of each step's
    float fetches + updated state; the returned fn then yields
    ``(fetches, new_state, first_bad)`` where ``first_bad`` is the LOCAL
    index of the first step that produced a non-finite value (``n_steps``
    when the whole window was clean). This is how ``check_nan_inf``
    names the exact failing step inside a compiled window without
    breaking it into per-step host dispatches.
    """
    sin = lowered.state_in_names
    sout = lowered.state_out_names
    extra_names = tuple(n for n in sout if n not in sin)

    def one(state, feeds_stacked, base_key, step_idx, feed_idx):
        # step_idx (GLOBAL, uint32) feeds the PRNG fold to match the
        # single-step path's fold_in(base_key, np.uint32(step)) stream;
        # feed_idx (LOCAL loop index) drives the rotation so "step i
        # consumes feed i % n_feeds" holds regardless of executor
        # history
        feeds = {
            k: jax.lax.dynamic_index_in_dim(
                v, jax.numpy.remainder(feed_idx, n_feeds), 0,
                keepdims=False
            )
            for k, v in feeds_stacked.items()
        }
        return lowered.fn(
            state, feeds, jax.random.fold_in(base_key, step_idx)
        )

    def _all_finite(vals):
        import jax.numpy as jnp

        flags = [
            jnp.all(jnp.isfinite(v)) for v in vals
            if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating)
        ]
        if not flags:
            return jnp.bool_(True)
        return jnp.all(jnp.stack(flags))

    def multi_fn(state, feeds_stacked, base_key, start_step, n_steps):
        import jax.numpy as jnp

        shapes = jax.eval_shape(
            lambda s, f, k: one(s, f, k, start_step, 0),
            state, feeds_stacked, base_key,
        )
        fetch0 = [jnp.zeros(x.shape, x.dtype) for x in shapes[0]]
        extra0 = {
            n: jnp.zeros(shapes[1][n].shape, shapes[1][n].dtype)
            for n in extra_names
        }
        # sentinel = n_steps (static here): "no step went non-finite"
        bad0 = jnp.int32(n_steps)

        def body(i, carry):
            st, _extra, _f, bad = carry
            idx = start_step + i.astype(jax.numpy.uint32)
            fetches, new_state = one(st, feeds_stacked, base_key, idx, i)
            if track_nonfinite:
                ok = _all_finite(list(fetches) + list(new_state.values()))
                bad = jnp.where((bad == n_steps) & ~ok,
                                i.astype(jnp.int32), bad)
            st2 = {n: new_state.get(n, st[n]) for n in sin}
            ex2 = {n: new_state[n] for n in extra_names}
            return (st2, ex2, fetches, bad)

        st, ex, fetches, bad = jax.lax.fori_loop(
            0, n_steps, body, (state, extra0, fetch0, bad0)
        )
        if track_nonfinite:
            return fetches, {**st, **ex}, bad
        return fetches, {**st, **ex}

    kwargs: Dict[str, Any] = {}
    if donate_state:
        # the serialized-executable tier compiles a donation-free twin:
        # deserialized donating executables mishandle buffer ownership
        # from their second call on (jax 0.4.x) — see compile_cache.py
        kwargs["donate_argnums"] = (0,)
    return jax.jit(multi_fn, static_argnums=(4,), **kwargs)


# ---------------------------------------------------------------------------
# compile-cost analysis (monitor.py compile reports)
# ---------------------------------------------------------------------------

def _as_int(v) -> Optional[int]:
    try:
        return int(v)
    except (TypeError, ValueError):
        return None


def build_compile_report(
    jitfn,
    lowered: LoweredBlock,
    args: tuple,
    *,
    program,
    kind: str = "step",
    compile_ms: Optional[float] = None,
    strategy: Optional[str] = None,
    cache_key=None,
    window_steps: Optional[int] = None,
) -> Dict[str, Any]:
    """Cost/memory report for a freshly compiled executor entry
    (schema: monitor.COMPILE_REPORT_FIELDS).

    AOT-lowers ``jitfn`` against ``args`` (lowering never executes, so
    donated buffers survive — call this BEFORE the step runs) and pulls
    XLA's ``cost_analysis()`` / ``memory_analysis()`` off the compiled
    executable. Both APIs drift across jax versions and backends, so
    every extraction is guarded: when nothing can be extracted the
    report degrades to ``source: "estimate"`` with null cost fields and
    the op-lowering histogram as the only cost signal. Never raises.

    The AOT compile is an extra compile — jax does not reliably share
    the backend cache between ``lower().compile()`` and the eager jit
    path (measured on jax 0.4.37) — which is why compile reports are
    opt-in per monitor.compile_reports_active()."""
    import hashlib
    import time as _time

    from paddle_tpu import monitor as _monitor

    key_digest = hashlib.sha1(
        repr(cache_key).encode()).hexdigest()[:16]
    hist = dict(lowered.op_histogram or {})
    report: Dict[str, Any] = {
        "v": _monitor.COMPILE_REPORT_SCHEMA_VERSION,
        "ts": _time.time(),
        "program": f"program{program._uid}",
        "program_uid": int(program._uid),
        "cache_key": key_digest,
        "kind": kind,
        "backend": jax.default_backend(),
        "source": "estimate",
        "compile_ms": compile_ms,
        "analysis_ms": None,
        "flops": None,
        "bytes_accessed": None,
        "peak_bytes": None,
        "argument_bytes": None,
        "output_bytes": None,
        "temp_bytes": None,
        "alias_bytes": None,
        "generated_code_bytes": None,
        "n_ops": sum(hist.values()),
        "op_histogram": hist,
        "strategy": strategy,
    }
    if window_steps is not None:
        # a window report's flops/bytes cover the WHOLE compiled window;
        # recording its length lets the roofline plane recover per-step
        # costs (optional field — compile-report schema stays v1)
        report["window_steps"] = int(window_steps)
    try:
        t0 = _time.perf_counter()
        # an entry built through the persistent compile cache carries
        # its AOT executable (compile_cache._wrap): analyze that instead
        # of AOT-compiling a twin
        compiled = getattr(jitfn, "_pt_compiled", None)
        if compiled is None:
            compiled = jitfn.lower(*args).compile()
        report["analysis_ms"] = (_time.perf_counter() - t0) * 1e3
    except Exception:
        return report

    got_any = False
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            if ca.get("flops") is not None:
                report["flops"] = float(ca["flops"])
                got_any = True
            if ca.get("bytes accessed") is not None:
                report["bytes_accessed"] = float(ca["bytes accessed"])
                got_any = True
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        arg = _as_int(getattr(ma, "argument_size_in_bytes", None))
        out = _as_int(getattr(ma, "output_size_in_bytes", None))
        tmp = _as_int(getattr(ma, "temp_size_in_bytes", None))
        ali = _as_int(getattr(ma, "alias_size_in_bytes", None))
        gen = _as_int(getattr(ma, "generated_code_size_in_bytes", None))
        report["argument_bytes"] = arg
        report["output_bytes"] = out
        report["temp_bytes"] = tmp
        report["alias_bytes"] = ali
        report["generated_code_bytes"] = gen
        if None not in (arg, out, tmp):
            report["peak_bytes"] = arg + out + tmp - (ali or 0)
            got_any = True
    except Exception:
        pass
    if got_any:
        report["source"] = "xla"
    else:
        # the AOT compile worked but exposed no numbers (some backends
        # return empty analyses): keep analysis_ms, mark the cost fields
        # as estimates
        report["analysis_ms"] = None
    return report
