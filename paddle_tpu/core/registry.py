"""Operator registry.

The TPU-native analog of the reference's op registry
(reference: paddle/fluid/framework/op_registry.h:197-240 and
framework/op_info.h). Differences by design:

- A kernel here is a pure JAX function over ``jax.numpy`` arrays, traced and
  fused by XLA, instead of a (place, dtype, layout, library)-dispatched C++
  kernel (reference: framework/operator.cc:881-964). Kernel selection,
  layout/dtype transform (reference: framework/data_transform.cc) and device
  placement all collapse into XLA compilation.
- Gradient kernels are not hand-written. Every op gets an auto-derived
  ``<type>_grad`` kernel built from ``jax.vjp`` of its forward compute
  (replacing the per-op GradOpDescMaker machinery, reference:
  framework/grad_op_desc_maker.h). Ops with non-default gradient structure
  (e.g. dropout reusing its mask) may register a custom grad maker.
- Shape inference (reference: framework/shape_inference.h) is abstract
  evaluation: ``jax.eval_shape`` over the same compute function.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence

# Slot-keyed values: {"X": [arr, ...], "Y": [arr]}
Ins = Dict[str, List[Any]]
Outs = Dict[str, List[Any]]
ComputeFn = Callable[..., Outs]  # compute(ins, attrs, rng=None) -> outs

GRAD_SUFFIX = "@GRAD"
GRAD_OP_SUFFIX = "_grad"


@dataclasses.dataclass
class OpDef:
    """Definition of one operator type."""

    type: str
    compute: ComputeFn
    # Slots that hold differentiable (float) inputs. None = all float inputs.
    diff_inputs: Optional[Sequence[str]] = None
    # Custom grad maker: fn(op: Operator, block) -> list of op-desc dicts.
    # None = auto vjp-based grad.
    grad_maker: Optional[Callable] = None
    # True if this op has no gradient (e.g. metrics, fill ops).
    no_grad: bool = False
    # True if compute wants an `rng` keyword (PRNG key).
    needs_rng: bool = False
    # Persistable state the op updates in place, as {output_slot: input_slot}
    # name-aliasing pairs (e.g. batch_norm MeanOut <- Mean).
    inplace: Optional[Dict[str, str]] = None
    # Python-level metadata for program printing.
    doc: str = ""

    def __post_init__(self):
        if self.diff_inputs is not None:
            self.diff_inputs = tuple(self.diff_inputs)


_OP_REGISTRY: Dict[str, OpDef] = {}


def register_op(
    type: str,
    *,
    diff_inputs: Optional[Sequence[str]] = None,
    grad_maker: Optional[Callable] = None,
    no_grad: bool = False,
    needs_rng: bool = False,
    inplace: Optional[Dict[str, str]] = None,
    doc: str = "",
) -> Callable[[ComputeFn], ComputeFn]:
    """Decorator registering ``fn`` as the kernel for op ``type``."""

    def deco(fn: ComputeFn) -> ComputeFn:
        if type in _OP_REGISTRY:
            raise ValueError(f"op '{type}' registered twice")
        _OP_REGISTRY[type] = OpDef(
            type=type,
            compute=fn,
            diff_inputs=diff_inputs,
            grad_maker=grad_maker,
            no_grad=no_grad,
            needs_rng=needs_rng,
            inplace=inplace,
            doc=doc or (fn.__doc__ or ""),
        )
        return fn

    return deco


def get_op_def(type: str) -> OpDef:
    _ensure_ops_loaded()
    try:
        return _OP_REGISTRY[type]
    except KeyError:
        raise KeyError(
            f"operator '{type}' is not registered; known ops: "
            f"{sorted(_OP_REGISTRY)[:40]}..."
        ) from None


def has_op(type: str) -> bool:
    _ensure_ops_loaded()
    return type in _OP_REGISTRY


def registered_ops() -> List[str]:
    _ensure_ops_loaded()
    return sorted(_OP_REGISTRY)


_ops_loaded = False


def _ensure_ops_loaded():
    # Lazy import to break the registry <-> ops module cycle.
    global _ops_loaded
    if not _ops_loaded:
        _ops_loaded = True
        try:
            from paddle_tpu import ops  # noqa: F401  (registers everything)
        except Exception:
            # Re-surface the real import error on the next call instead of
            # reporting an empty registry forever.
            _ops_loaded = False
            raise
