"""DataFeeder: sample lists -> feed dict of dense numpy batches
(reference: python/paddle/fluid/data_feeder.py).

LoD conversion is replaced by pad-to-bucket: variable-length sequence fields
are padded to the batch max (or a fixed bucket) and a companion ``<name>_len``
int array carries true lengths (SURVEY.md section 5 static-shape discipline).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu import monitor as _monitor
from paddle_tpu.framework import Variable


class DataFeeder:
    """``pad_to`` declares ragged fields: {var_name: bucket_len}. A declared
    field is ALWAYS padded/truncated to its bucket and always emits a
    companion ``<name>_len`` int64 array — fixed shapes (one XLA compile),
    no batch-dependent feed signature."""

    def __init__(
        self,
        feed_list: Sequence[Variable],
        place=None,
        program=None,
        pad_to: Optional[Dict[str, int]] = None,
    ):
        self.feed_vars = list(feed_list)
        self.place = place
        self.pad_to = dict(pad_to or {})

    def feed(self, iterable,
             critical_path: bool = True) -> Dict[str, np.ndarray]:
        """iterable: list of samples; each sample is a tuple aligned with
        feed_list. Returns {name: batched ndarray} (+ ``name_len`` for fields
        declared in ``pad_to``).

        With telemetry on, the batch-assembly time feeds
        ``pt_feed_build_seconds`` and — on the critical path — the
        boundedness verdict's input score: batching on the step loop's
        critical path is input-pipeline time even though nothing
        'waits'. Pass ``critical_path=False`` from a prefetch worker
        (overlapped assembly must not fake an input_bound verdict; the
        consumer's queue wait is the honest signal there)."""
        if not _monitor.enabled():
            return self._feed(iterable)
        t0 = time.perf_counter()
        out = self._feed(iterable)
        _monitor.feed_build(time.perf_counter() - t0,
                            critical_path=critical_path)
        return out

    def _feed(self, iterable) -> Dict[str, np.ndarray]:
        columns: List[List] = [[] for _ in self.feed_vars]
        for sample in iterable:
            if len(sample) != len(self.feed_vars):
                raise ValueError(
                    f"sample has {len(sample)} fields, expected "
                    f"{len(self.feed_vars)}"
                )
            for c, v in zip(columns, sample):
                c.append(np.asarray(v))
        out: Dict[str, np.ndarray] = {}
        for var, col in zip(self.feed_vars, columns):
            if var.name in self.pad_to:
                bucket = self.pad_to[var.name]
                tail = col[0].shape[1:]
                batch = np.zeros((len(col), bucket) + tail, dtype=col[0].dtype)
                lengths = np.zeros((len(col),), dtype=np.int64)
                for i, a in enumerate(col):
                    n = min(a.shape[0], bucket)
                    batch[i, :n] = a[:n]
                    lengths[i] = n
                out[var.name + "_len"] = lengths
            else:
                shapes = {a.shape for a in col}
                if len(shapes) != 1:
                    raise ValueError(
                        f"feed field '{var.name}' is ragged {sorted(shapes)[:3]}; "
                        f"declare it in DataFeeder(pad_to={{'{var.name}': L}}) "
                        f"to pad to a fixed bucket (XLA needs static shapes)"
                    )
                batch = np.stack(col)
            dtype = np.dtype(var.dtype) if var.dtype else batch.dtype
            if batch.dtype != dtype:
                batch = batch.astype(dtype)
            want = var.shape
            if want is not None and len(want) == batch.ndim + 1 and want[-1] == 1:
                batch = batch[..., None]  # label column convention [N] -> [N,1]
            out[var.name] = batch
        return out
