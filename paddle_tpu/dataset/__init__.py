"""Dataset loaders (reference: python/paddle/dataset/).

The reference auto-downloads mnist/cifar/imdb/wmt16/... In this environment
there is no egress, so each dataset has a deterministic synthetic generator
with the exact shapes/vocabulary of the real one (same reader contract), and
an optional ``data_dir`` to load real files when present. Benchmarks are
throughput-oriented, so synthetic data measures the same compute.
"""

from paddle_tpu.dataset import (  # noqa: F401
    cifar,
    conll05,
    flowers,
    imagenet,
    imdb,
    mnist,
    movielens,
    sentiment,
    uci_housing,
    wmt14,
    wmt16,
)
