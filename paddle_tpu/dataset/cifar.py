"""CIFAR-10/100 readers (reference: python/paddle/dataset/cifar.py).
Synthetic offline generator: 3x32x32 floats, learnable labels."""

from __future__ import annotations

import numpy as np

SHAPE = (3, 32, 32)


def _synthetic(n, num_classes, seed):
    dim = int(np.prod(SHAPE))
    probes = np.random.RandomState(11).randn(dim, num_classes)

    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            img = r.uniform(-1, 1, SHAPE).astype(np.float32)
            label = int(np.argmax(img.reshape(-1) @ probes))
            yield img.reshape(-1), label

    return reader


def train10(data_dir=None):
    return _synthetic(8192, 10, seed=3)


def test10(data_dir=None):
    return _synthetic(1024, 10, seed=4)


def train100(data_dir=None):
    return _synthetic(8192, 100, seed=5)


def test100(data_dir=None):
    return _synthetic(1024, 100, seed=6)
