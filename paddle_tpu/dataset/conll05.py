"""CoNLL-2005 SRL reader (reference: python/paddle/dataset/conll05.py).

Synthetic offline with the reference record contract — 9 parallel
sequences per sentence::

    (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2,
     predicate_ids, mark, label_ids)

where the five ctx_* sequences broadcast the verb's +-2 window over the
sentence length, ``mark`` flags that window, and labels are BIO
argument tags. Labels are generated as a deterministic function of the
token and its distance to the predicate, so SRL models (book ch7)
genuinely learn.
"""

from __future__ import annotations

import numpy as np

UNK_IDX = 0

_WORD_VOCAB = 44068
_PRED_VOCAB = 3162
# 'O' + B-/I- over A0..A4, V, AM-* style slots: the reference label
# dict has 59 entries
_N_LABELS = 59


def get_dict():
    """(word_dict, verb_dict, label_dict) — reference: conll05.py:205."""
    word_dict = {f"w{i}": i for i in range(_WORD_VOCAB)}
    verb_dict = {f"v{i}": i for i in range(_PRED_VOCAB)}
    label_dict = {f"l{i}": i for i in range(_N_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Fixed word embedding table (reference: conll05.py:218 — the
    downloaded emb file; here a deterministic matrix)."""
    return np.random.RandomState(61).normal(
        0, 0.1, (_WORD_VOCAB, 32)).astype(np.float32)


def _label_for(word, dist):
    # BIO structure around the verb: near tokens -> argument tags tied
    # to the word id (learnable), far tokens -> O (label 0)
    if dist == 0:
        return 1  # B-V analog
    if abs(dist) <= 3:
        return 2 + (word + abs(dist)) % (_N_LABELS - 2)
    return 0


def _reader(n, seed):
    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            length = int(r.randint(5, 30))
            words = r.randint(1, _WORD_VOCAB, length)
            vi = int(r.randint(0, length))
            pred = int(words[vi] % _PRED_VOCAB)

            def ctx(off):
                j = vi + off
                return int(words[j]) if 0 <= j < length else UNK_IDX

            mark = [1 if abs(i - vi) <= 2 else 0 for i in range(length)]
            labels = [_label_for(int(w), i - vi)
                      for i, w in enumerate(words)]
            wl = words.tolist()
            yield (wl, [ctx(-2)] * length, [ctx(-1)] * length,
                   [ctx(0)] * length, [ctx(1)] * length,
                   [ctx(2)] * length, [pred] * length, mark, labels)

    return reader


def test():
    return _reader(1024, 62)


# the reference ships only a test split; a train split is provided so
# convergence tests have data of the same contract
def train():
    return _reader(8192, 63)
