"""Flowers-102 reader (reference: python/paddle/dataset/flowers.py).
Synthetic offline generator (no egress): 3x224x224 floats, 102 classes
with learnable linear-probe labels, matching the reference benchmark's
input contract (benchmark/fluid/fluid_benchmark.py resnet-on-flowers)."""

from __future__ import annotations

import numpy as np

SHAPE = (3, 224, 224)
NUM_CLASSES = 102


def _synthetic(n, seed):
    # probe on a downsampled view to keep label computation cheap
    probes = np.random.RandomState(17).randn(3 * 16 * 16, NUM_CLASSES)

    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            img = r.uniform(-1, 1, SHAPE).astype(np.float32)
            small = img[:, ::14, ::14].reshape(-1)  # [3*16*16]
            label = int(np.argmax(small @ probes))
            yield img, label

    return reader


def train(data_dir=None, use_xmap=True):
    return _synthetic(2048, seed=7)


def test(data_dir=None, use_xmap=True):
    return _synthetic(256, seed=8)


def valid(data_dir=None, use_xmap=True):
    return _synthetic(256, seed=9)
