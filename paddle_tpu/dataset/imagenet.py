"""Synthetic ImageNet-shaped reader (reference:
benchmark/fluid/imagenet_reader.py — the benchmark harness's fake-data
mode). Batched variant feeds the ResNet benchmark without per-sample
Python overhead dominating the measurement."""

from __future__ import annotations

import numpy as np

SHAPE = (3, 224, 224)
NUM_CLASSES = 1000


def train(n: int = 1024, seed: int = 21):
    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            img = r.uniform(-1, 1, SHAPE).astype(np.float32)
            yield img, int(r.randint(NUM_CLASSES))

    return reader


def batched(batch_size: int, steps: int, seed: int = 22,
            data_shape=SHAPE, class_dim=NUM_CLASSES):
    """Yields {feed_name: array} batches directly (fast path for bench)."""
    def reader():
        r = np.random.RandomState(seed)
        for _ in range(steps):
            yield {
                "data": r.uniform(
                    -1, 1, (batch_size,) + tuple(data_shape)
                ).astype(np.float32),
                "label": r.randint(
                    0, class_dim, (batch_size, 1)
                ).astype(np.int64),
            }

    return reader
