"""IMDB sentiment reader (reference: python/paddle/dataset/imdb.py).
Synthetic offline: word-id sequences whose label depends on the balance of
"positive" vs "negative" token ranges — learnable by embedding+pool models."""

from __future__ import annotations

import numpy as np


def word_dict(vocab_size: int = 5148):
    return {f"w{i}": i for i in range(vocab_size)}


def _synthetic(n, vocab_size, seed):
    def reader():
        r = np.random.RandomState(seed)
        half = vocab_size // 2
        for _ in range(n):
            length = int(r.randint(20, 200))
            label = int(r.randint(0, 2))
            # positive reviews draw 70% of tokens from the upper half
            p_hi = 0.7 if label else 0.3
            hi = r.randint(half, vocab_size, length)
            lo = r.randint(2, half, length)
            pick = r.rand(length) < p_hi
            ids = np.where(pick, hi, lo).astype(np.int64)
            yield ids, label

    return reader


def train(word_idx=None):
    n_words = len(word_idx) if word_idx else 5148
    return _synthetic(4096, n_words, seed=31)


def test(word_idx=None):
    n_words = len(word_idx) if word_idx else 5148
    return _synthetic(512, n_words, seed=32)
