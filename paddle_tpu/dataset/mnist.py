"""MNIST reader (reference: python/paddle/dataset/mnist.py).

Loads real IDX files from ``data_dir`` if present; otherwise serves a
deterministic synthetic set with the same shapes (784 floats in [-1, 1],
label 0-9) so the book-chapter training tests and benchmarks run offline.
The synthetic task is learnable (label = argmax of 10 fixed random linear
probes of the image) so convergence tests are meaningful.
"""

from __future__ import annotations

import gzip
import os

import numpy as np

IMAGE_SIZE = 784
NUM_CLASSES = 10


def _synthetic(n: int, seed: int):
    rng = np.random.RandomState(seed)
    probes = np.random.RandomState(7).randn(IMAGE_SIZE, NUM_CLASSES)

    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            img = r.uniform(-1, 1, IMAGE_SIZE).astype(np.float32)
            label = int(np.argmax(img @ probes))
            yield img, label

    return reader


def _idx_reader(image_path: str, label_path: str):
    def reader():
        with gzip.open(image_path, "rb") as fi, gzip.open(label_path, "rb") as fl:
            fi.read(16)
            fl.read(8)
            while True:
                lbl = fl.read(1)
                if not lbl:
                    break
                img = np.frombuffer(fi.read(IMAGE_SIZE), dtype=np.uint8)
                img = img.astype(np.float32) / 127.5 - 1.0
                yield img, int(lbl[0])

    return reader


def _make(split: str, n: int, seed: int, data_dir=None):
    data_dir = data_dir or os.environ.get("PADDLE_TPU_DATA_DIR")
    if data_dir:
        prefix = "train" if split == "train" else "t10k"
        ip = os.path.join(data_dir, f"{prefix}-images-idx3-ubyte.gz")
        lp = os.path.join(data_dir, f"{prefix}-labels-idx1-ubyte.gz")
        if os.path.exists(ip) and os.path.exists(lp):
            return _idx_reader(ip, lp)
    return _synthetic(n, seed)


def train(data_dir=None):
    return _make("train", 8192, seed=1, data_dir=data_dir)


def test(data_dir=None):
    return _make("test", 1024, seed=2, data_dir=data_dir)
