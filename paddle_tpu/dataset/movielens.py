"""MovieLens ml-1m reader (reference: python/paddle/dataset/movielens.py).

Synthetic offline, with the real ml-1m cardinalities (3952 movies, 6040
users, 21 jobs, 18 genres, the reference's 7-bucket age table) and the
same record contract::

    (user_id, gender_id, age_id, job_id,
     movie_id, [category_ids], [title_ids], score)

Ratings are a LOW-RANK function of fixed per-user/per-movie latent
vectors (score = clip(round(3 + u.v), 1, 5)), so factorization
recommenders (book ch5) genuinely learn from it.
"""

from __future__ import annotations

import numpy as np

age_table = [1, 18, 25, 35, 45, 50, 56]

_MAX_USER = 6040
_MAX_MOVIE = 3952
_MAX_JOB = 20
_N_CATEGORIES = 18
_TITLE_VOCAB = 5175
_LATENT_K = 6


class MovieInfo:
    """Movie id, title word ids and category ids
    (reference: movielens.py:49 — here ids directly, no raw strings)."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = list(categories)
        self.title = list(title)

    def value(self):
        return [self.index, self.categories, self.title]


class UserInfo:
    """User id, gender, bucketed age, job (reference: movielens.py:74)."""

    def __init__(self, index, gender_id, age_id, job_id):
        self.index = int(index)
        self.is_male = gender_id == 0
        self.age = int(age_id)
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]


def _latents():
    r = np.random.RandomState(41)
    u = r.normal(0, 0.6, (_MAX_USER + 1, _LATENT_K))
    m = r.normal(0, 0.6, (_MAX_MOVIE + 1, _LATENT_K))
    return u, m


def _movie_meta():
    r = np.random.RandomState(42)
    cats = [sorted(set(r.randint(0, _N_CATEGORIES,
                                 1 + int(r.randint(3))).tolist()))
            for _ in range(_MAX_MOVIE + 1)]
    titles = [r.randint(3, _TITLE_VOCAB, 2 + int(r.randint(4))).tolist()
              for _ in range(_MAX_MOVIE + 1)]
    return cats, titles


def _user_meta():
    """(genders, ages, jobs) arrays indexed by user id — the single
    source for demographics, shared by the reader and user_info()."""
    meta = np.random.RandomState(43)
    genders = meta.randint(0, 2, _MAX_USER + 1)
    ages = meta.randint(0, len(age_table), _MAX_USER + 1)
    jobs = meta.randint(0, _MAX_JOB + 1, _MAX_USER + 1)
    return genders, ages, jobs


def _reader(n, seed):
    def reader():
        u_lat, m_lat = _latents()
        cats, titles = _movie_meta()
        r = np.random.RandomState(seed)
        genders, ages, jobs = _user_meta()
        for _ in range(n):
            u = int(r.randint(1, _MAX_USER + 1))
            m = int(r.randint(1, _MAX_MOVIE + 1))
            score = float(np.clip(
                np.round(3.0 + u_lat[u] @ m_lat[m]), 1, 5))
            yield [u, int(genders[u]), int(ages[u]), int(jobs[u]),
                   m, cats[m], titles[m], score]

    return reader


def train(rand_seed=0):
    return _reader(16384, 51 + rand_seed)


def test(rand_seed=0):
    return _reader(2048, 52 + rand_seed)


def max_user_id():
    return _MAX_USER


def max_movie_id():
    return _MAX_MOVIE


def max_job_id():
    return _MAX_JOB


def movie_categories():
    return {f"genre{i}": i for i in range(_N_CATEGORIES)}


def get_movie_title_dict():
    return {f"t{i}": i for i in range(_TITLE_VOCAB)}


def user_info():
    genders, ages, jobs = _user_meta()
    return {i: UserInfo(i, int(genders[i]), int(ages[i]), int(jobs[i]))
            for i in range(1, _MAX_USER + 1)}


def movie_info():
    cats, titles = _movie_meta()
    return {i: MovieInfo(i, cats[i], titles[i])
            for i in range(1, _MAX_MOVIE + 1)}
