"""NLTK movie-reviews sentiment reader (reference:
python/paddle/dataset/sentiment.py — 2000 polarity-labelled reviews).

Synthetic offline with the reference contract: ``train()``/``test()``
yield ``(word_ids, label)`` with label 0/1 and the corpus split sizes
(1600/400); ``get_word_dict()`` is frequency-ordered like the
reference's. Positive reviews oversample the upper token range, so
embedding+pool classifiers (book ch6) genuinely learn.
"""

from __future__ import annotations

import numpy as np

_VOCAB = 39768
NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000


def get_word_dict():
    """word -> id, ordered by (synthetic) frequency
    (reference: sentiment.py:56)."""
    return {f"w{i}": i for i in range(_VOCAB)}


def _reader(n, seed):
    def reader():
        r = np.random.RandomState(seed)
        half = _VOCAB // 2
        for _ in range(n):
            length = int(r.randint(30, 400))
            label = int(r.randint(0, 2))
            p_hi = 0.68 if label else 0.32
            hi = r.randint(half, _VOCAB, length)
            lo = r.randint(1, half, length)
            pick = r.rand(length) < p_hi
            yield np.where(pick, hi, lo).astype(np.int64).tolist(), label

    return reader


def train():
    return _reader(NUM_TRAINING_INSTANCES, 71)


def test():
    return _reader(NUM_TOTAL_INSTANCES - NUM_TRAINING_INSTANCES, 72)
