"""UCI housing regression reader (reference: python/paddle/dataset/uci_housing.py).
Synthetic offline: 13 features, linear target + noise."""

from __future__ import annotations

import numpy as np

FEATURES = 13
_W = np.random.RandomState(17).randn(FEATURES)


def _synthetic(n, seed):
    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            x = r.randn(FEATURES).astype(np.float32)
            y = np.float32(x @ _W + 0.1 * r.randn())
            yield x, np.array([y], dtype=np.float32)

    return reader


def train():
    return _synthetic(404, seed=41)


def test():
    return _synthetic(102, seed=42)
