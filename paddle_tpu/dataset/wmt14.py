"""WMT14 en-fr reader (reference: python/paddle/dataset/wmt14.py).

Synthetic offline sharing the wmt16 generator machinery (same
BOS=0/EOS=1/UNK=2 contract, learnable token mapping) with the wmt14
API: ``train(dict_size)``/``test(dict_size)`` yield
``(src_ids, trg_ids, trg_next_ids)``; ``get_dict(dict_size, reverse)``
returns the (src, trg) vocabularies.
"""

from __future__ import annotations

from paddle_tpu.dataset import wmt16 as _wmt16

BOS, EOS, UNK = _wmt16.BOS, _wmt16.EOS, _wmt16.UNK


def train(dict_size):
    return _wmt16._synthetic(19200, dict_size, dict_size, max_len=50,
                             seed=81)


def test(dict_size):
    return _wmt16._synthetic(960, dict_size, dict_size, max_len=50,
                             seed=82)


def gen(dict_size):
    return _wmt16._synthetic(960, dict_size, dict_size, max_len=50,
                             seed=83)


def get_dict(dict_size, reverse=True):
    """(src_dict, trg_dict); reverse=True gives id -> token
    (reference: wmt14.py:156)."""
    if reverse:
        d = {i: f"tok{i}" for i in range(dict_size)}
        for i, name in ((BOS, "<s>"), (EOS, "<e>"), (UNK, "<unk>")):
            d[i] = name
        return d, dict(d)
    d = {f"tok{i}": i for i in range(dict_size)}
    d.update({"<s>": BOS, "<e>": EOS, "<unk>": UNK})
    return d, dict(d)
