"""WMT16 en-de token-pair reader (reference: python/paddle/dataset/wmt16.py).

Synthetic offline generator: (src_ids, trg_ids, trg_next_ids) int sequences
with the reference's vocab contract (BOS=0, EOS=1, UNK=2) and a learnable
copy-ish mapping (trg token = f(src token)) so Transformer convergence tests
are meaningful. Lengths are bucketed for static shapes.
"""

from __future__ import annotations

import numpy as np

BOS, EOS, UNK = 0, 1, 2
RESERVED = 3


def _synthetic(n, src_vocab_size, trg_vocab_size, max_len, seed):
    # fixed random permutation mapping src token -> trg token
    perm = np.random.RandomState(13).permutation(
        max(src_vocab_size, trg_vocab_size)
    )

    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            length = int(r.randint(max(4, max_len // 4), max_len - 2))
            src = r.randint(RESERVED, src_vocab_size, length)
            trg_core = perm[src] % (trg_vocab_size - RESERVED) + RESERVED
            src_ids = np.concatenate([[BOS], src, [EOS]]).astype(np.int64)
            trg_ids = np.concatenate([[BOS], trg_core]).astype(np.int64)
            trg_next = np.concatenate([trg_core, [EOS]]).astype(np.int64)
            yield src_ids, trg_ids, trg_next

    return reader


def train(src_dict_size=10000, trg_dict_size=10000, src_lang="en", max_len=50):
    return _synthetic(20000, src_dict_size, trg_dict_size, max_len, seed=21)


def test(src_dict_size=10000, trg_dict_size=10000, src_lang="en", max_len=50):
    return _synthetic(1000, src_dict_size, trg_dict_size, max_len, seed=22)


def validation(src_dict_size=10000, trg_dict_size=10000, src_lang="en",
               max_len=50):
    return _synthetic(1000, src_dict_size, trg_dict_size, max_len, seed=23)
