"""Dataset API: in-memory and streaming dataset containers
(reference: python/paddle/fluid/dataset.py — DatasetFactory:21,
InMemoryDataset:215 with local/global shuffle:262, QueueDataset; C++
side framework/data_set.h:40,101 and the MultiSlotDataFeed channel
pipeline, framework/data_feed.h:353).

TPU-native redesign: the reference's C++ channel pipeline + pslib-RPC
global shuffle feed an op-by-op CPU trainer; here datasets produce padded
numpy batches for the XLA step function, files parse on host threads
(multiprocess_reader), and "global shuffle" across workers exchanges
sample ranges through the fleet KV service instead of pserver RPC.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np


class DatasetFactory:
    """reference: fluid/dataset.py DatasetFactory.create_dataset."""

    def create_dataset(self, datafeed_class: str = "QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError(f"unknown dataset class {datafeed_class!r}")


class DatasetBase:
    def __init__(self):
        self._filelist: List[str] = []
        self._parse_fn: Optional[Callable] = None
        self._batch_size = 1
        self._thread_num = 1
        self._use_var_names: List[str] = []

    # --- reference-parity configuration surface ---

    def set_filelist(self, filelist: Sequence[str]):
        self._filelist = list(filelist)

    def set_batch_size(self, batch_size: int):
        self._batch_size = int(batch_size)

    def set_thread(self, thread_num: int):
        self._thread_num = max(1, int(thread_num))

    def set_use_var(self, var_list):
        self._use_var_names = [
            v.name if hasattr(v, "name") else str(v) for v in var_list
        ]

    def set_parse_fn(self, fn: Callable[[str], Iterable[tuple]]):
        """``fn(line) -> sample tuple`` aligned with set_use_var order
        (replaces the reference's MultiSlotDataFeed proto config)."""
        self._parse_fn = fn

    # --- iteration ---

    def _sample_reader(self):
        if self._parse_fn is None:
            raise RuntimeError("set_parse_fn before iterating the dataset")

        def reader():
            for path in self._filelist:
                with open(path) as f:
                    for line in f:
                        line = line.rstrip("\n")
                        if line:
                            yield self._parse_fn(line)

        return reader

    def batch_reader(self):
        """-> callable yielding {var_name: stacked numpy batch}."""
        sample_reader = self._shuffled_reader()
        names = self._use_var_names

        def reader():
            buf: List[tuple] = []
            for s in sample_reader():
                buf.append(s)
                if len(buf) == self._batch_size:
                    yield self._stack(buf, names)
                    buf = []
            if buf:
                yield self._stack(buf, names)

        return reader

    @staticmethod
    def _stack(samples, names) -> Dict[str, np.ndarray]:
        cols = list(zip(*samples))
        if names and len(names) != len(cols):
            raise ValueError(
                f"samples have {len(cols)} slots but {len(names)} use_vars"
            )
        out = {}
        for i, col in enumerate(cols):
            key = names[i] if names else str(i)
            out[key] = np.stack([np.asarray(v) for v in col])
        return out

    def _shuffled_reader(self):
        return self._sample_reader()


class QueueDataset(DatasetBase):
    """Streaming dataset: files parse on worker processes and stream
    through a queue (reference: QueueDataset over MultiSlotDataFeed
    channels). No shuffle beyond file order."""

    def _shuffled_reader(self):
        if self._thread_num <= 1 or len(self._filelist) <= 1:
            return self._sample_reader()
        from paddle_tpu.reader.decorator import multiprocess_reader

        per_worker = [
            self._filelist[i :: self._thread_num]
            for i in range(min(self._thread_num, len(self._filelist)))
        ]
        parse = self._parse_fn

        def make(files):
            def r():
                for path in files:
                    with open(path) as f:
                        for line in f:
                            line = line.rstrip("\n")
                            if line:
                                yield parse(line)

            return r

        return multiprocess_reader([make(fs) for fs in per_worker if fs])


class InMemoryDataset(DatasetBase):
    """Loads all samples to memory; supports local and fleet-wide global
    shuffle (reference: InMemoryDataset.load_into_memory /
    local_shuffle / global_shuffle:262)."""

    def __init__(self):
        super().__init__()
        self._samples: Optional[List[tuple]] = None
        self._seed = 0

    def load_into_memory(self):
        self._samples = list(self._sample_reader()())

    def set_shuffle_seed(self, seed: int):
        self._seed = int(seed)

    def local_shuffle(self):
        if self._samples is None:
            raise RuntimeError("load_into_memory before local_shuffle")
        random.Random(self._seed).shuffle(self._samples)

    def global_shuffle(self, fleet=None):
        """Exchange shuffled sample shards across fleet workers through
        the coordination KV (the reference shuffles globally via pslib
        RPC, data_set.h global_shuffle). Single-worker fleets degrade to
        a local shuffle."""
        if self._samples is None:
            raise RuntimeError("load_into_memory before global_shuffle")
        if fleet is None or fleet.worker_num() <= 1:
            self.local_shuffle()
            return
        import pickle

        rank, n = fleet.worker_index(), fleet.worker_num()
        rng = random.Random(self._seed)
        rng.shuffle(self._samples)
        # partition my samples into n shards; publish the shards meant
        # for other workers, keep mine
        shards = [self._samples[i::n] for i in range(n)]
        for dst in range(n):
            if dst != rank:
                fleet.put(f"gshuffle/{rank}->{dst}",
                          pickle.dumps(shards[dst]))
        fleet.barrier("gshuffle/published")
        merged = list(shards[rank])
        for src in range(n):
            if src != rank:
                merged.extend(pickle.loads(
                    fleet.get(f"gshuffle/{src}->{rank}")))
        rng.shuffle(merged)
        self._samples = merged
        fleet.barrier("gshuffle/done")

    def release_memory(self):
        self._samples = None

    def _shuffled_reader(self):
        if self._samples is None:
            return self._sample_reader()
        samples = self._samples

        def reader():
            yield from samples

        return reader
