"""Program inspection: pretty printer + graphviz export
(reference: python/paddle/fluid/debugger.py — draw_block_graphviz /
pprint_program_codes)."""

from __future__ import annotations

from typing import Optional

from paddle_tpu.framework import Program


def _compile_report_lines(program: Program) -> list:
    """Annotation header from the program's latest compile report (if the
    telemetry plane recorded one): the listing then answers not just
    "what ops" but "what do they cost compiled"."""
    from paddle_tpu import monitor

    rep = monitor.compile_reports().get(f"program{program._uid}")
    if rep is None:
        return []

    def _fmt(v, unit=""):
        if v is None:
            return "null"
        if unit == "B":
            return f"{int(v):,} B"
        return f"{v:,.0f}" if isinstance(v, float) else f"{v:,}"

    # .get throughout: record_compile_report accepts (and never rejects)
    # hand-built reports, and the debugging utility must not crash on one
    return [
        f"compile report (v{rep.get('v')}, source={rep.get('source')}, "
        f"backend={rep.get('backend')}):",
        f"  flops={_fmt(rep.get('flops'))} "
        f"bytes_accessed={_fmt(rep.get('bytes_accessed'))}",
        f"  peak={_fmt(rep.get('peak_bytes'), 'B')} "
        f"(args={_fmt(rep.get('argument_bytes'), 'B')} "
        f"out={_fmt(rep.get('output_bytes'), 'B')} "
        f"temp={_fmt(rep.get('temp_bytes'), 'B')})",
        f"  n_ops={rep.get('n_ops')} "
        f"compile_ms={_fmt(rep.get('compile_ms'))} "
        f"analysis_ms={_fmt(rep.get('analysis_ms'))}",
    ]


def _time_attribution_lines() -> list:
    """Annotation from the time-attribution plane: the latest step
    record's phase breakdown plus the rolling boundedness verdict. Not
    program-keyed (step records aren't) — it describes the most recent
    executor step, which during single-program debugging is the one
    being inspected."""
    from paddle_tpu import monitor

    recs = monitor.recent_steps(1)
    phases = recs[0].get("phases") if recs else None
    bound = monitor.boundedness()
    if phases is None and bound is None:
        return []
    lines = []
    if phases is not None:
        lines.append(
            "time attribution (last step): " + " ".join(
                f"{k}={phases[k]:.2f}ms" for k in
                ("feed", "dispatch", "device", "fetch") if k in phases))
    if bound is not None:
        s = bound["shares"]
        lines.append(
            f"  boundedness: {bound['verdict']} over last "
            f"{bound['steps']} steps (input {s['input']:.0%} dispatch "
            f"{s['dispatch']:.0%} device {s['device']:.0%})")
    return lines


def _roofline_lines(program: Program):
    """(header lines, {op type -> per-op device ms}) from the roofline
    plane's latest device profile for this program: measured MFU +
    verdict + top device ops, and — on an xplane-sourced profile — a
    per-op-type device-time estimate for the listing's annotation
    column (an HLO op's seconds are attributed to every candidate
    framework op type of its group, spread across that type's op
    count, so the column is a shortlist-grade estimate, not a proof)."""
    from paddle_tpu import roofline

    prof = roofline.latest(program)
    if prof is None:
        return [], {}
    mfu = prof.get("measured_mfu")
    dev = prof.get("device_seconds")
    lines = [
        f"device profile (v{prof.get('v')}, source={prof.get('source')}, "
        f"steps={prof.get('steps')}): verdict={prof.get('verdict')} "
        f"measured_mfu={'null' if mfu is None else f'{mfu:.3f}'} "
        f"device_s={'null' if dev is None else f'{dev:.4f}'}"
    ]
    timed = [o for o in prof.get("top_ops", ()) if o.get("seconds")]
    if timed:
        lines.append("  top device ops: " + " ".join(
            f"{o['name']}={o['seconds'] * 1e3:.2f}ms"
            f"({o['share']:.0%})" for o in timed[:5]))
    # per-op-type device time for the annotation column
    type_seconds: dict = {}
    type_counts: dict = {}
    for op in program.blocks[0].ops:
        type_counts[op.type] = type_counts.get(op.type, 0) + 1
    for o in timed:
        for fw in o.get("framework_ops", ()):
            type_seconds[fw] = type_seconds.get(fw, 0.0) + o["seconds"]
    per_op_ms = {t: s * 1e3 / type_counts.get(t, 1)
                 for t, s in type_seconds.items() if t in type_counts}
    return lines, per_op_ms


def _numerics_lines(program: Program):
    """(header lines, {op idx -> marker}) from the numerics plane's
    latest NaN/Inf provenance record for this program (if any)."""
    from paddle_tpu import numerics

    rec = numerics.provenance_for(program._uid)
    if rec is None:
        return [], {}
    step = rec.get("nan_step")
    step = rec.get("step") if step is None else step
    header = [
        f"numerics provenance (v{rec.get('v')}): first non-finite at "
        f"op [{rec.get('op_idx')}] {rec.get('op_type')} -> "
        f"'{rec.get('var')}' (step {step}, "
        f"nonfinite={rec.get('nonfinite'):.0f}, "
        f"maxabs={rec.get('maxabs'):.3g})",
    ]
    marks = {rec.get("op_idx"): "   !! first non-finite "
                                f"(var {rec.get('var')}, step {step})"}
    return header, marks


def _lint_lines(program: Program):
    """(header lines, {op idx -> marker}) from the static verifier's
    latest findings for this program (analysis.findings_for): severity
    counts plus one line per warning/error, with error sites marked
    inline on the op listing."""
    from paddle_tpu import analysis

    rec = analysis.findings_for(program._uid)
    if rec is None:
        return [], {}
    lines = [f"static lint (v{rec.get('v')}, "
             f"{rec.get('lint_ms', 0.0):.1f}ms): "
             f"{analysis.format_counts(rec.get('counts') or {})}"]
    marks = {}
    for f in rec.get("findings", ()):
        if f.get("severity") not in ("warning", "error"):
            continue
        lines.append(f"  [{f.get('severity')}] {f.get('check')} @ "
                     f"{f.get('site')}: {f.get('message')}")
        if f.get("hint"):
            lines.append(f"    fix: {f['hint']}")
        if f.get("severity") == "error" and f.get("op_idx") is not None \
                and f.get("block_idx") == 0:
            marks.setdefault(
                f["op_idx"],
                f"   !! lint: {f.get('check')} ('{f.get('var')}')")
    return lines, marks


def pprint_program(program: Program, with_shapes: bool = True,
                   with_compile_report: bool = True,
                   with_numerics: bool = True,
                   with_timeline: bool = True,
                   with_lint: bool = True,
                   with_roofline: bool = True) -> str:
    """Readable multi-block listing of a Program's vars and ops,
    prefixed with the latest compile-report annotation when telemetry
    recorded one (``with_compile_report=False`` opts out), the latest
    NaN/Inf provenance record when the numerics plane holds one — the
    offending op line is marked inline (``with_numerics=False`` opts
    out) — the latest step's phase breakdown + boundedness verdict
    from the time-attribution plane (``with_timeline=False`` opts
    out), the static verifier's latest findings for the program
    with error sites marked inline (``with_lint=False`` opts out),
    and the roofline plane's latest device profile — measured MFU +
    verdict + top device ops in the header, and a per-op device-time
    column on the op listing when an xplane-sourced profile attributes
    HLO seconds to the op's type (``with_roofline=False`` opts out)."""
    lines = []
    if with_compile_report:
        lines.extend(_compile_report_lines(program))
    if with_timeline:
        lines.extend(_time_attribution_lines())
    per_op_ms = {}
    if with_roofline:
        header, per_op_ms = _roofline_lines(program)
        lines.extend(header)
    marks = {}
    if with_lint:
        header, marks = _lint_lines(program)
        lines.extend(header)
    if with_numerics:
        header, nmarks = _numerics_lines(program)
        lines.extend(header)
        for k, v in nmarks.items():
            marks.setdefault(k, v)
    for block in program.blocks:
        lines.append(f"block {block.idx}:")
        for name, var in sorted(block.vars.items()):
            shape = f" shape={list(var.shape)}" if (
                with_shapes and var.shape is not None) else ""
            tags = "".join(
                t for t, on in ((" param", var.is_parameter),
                                (" persistable", var.persistable),
                                (" stop_grad", var.stop_gradient)) if on
            )
            lines.append(f"  var {name}{shape}{tags}")
        for i, op in enumerate(block.ops):
            ins = ", ".join(
                f"{k}={v}" for k, v in op.inputs.items() if v)
            outs = ", ".join(
                f"{k}={v}" for k, v in op.outputs.items() if v)
            mark = marks.get(i, "") if block.idx == 0 else ""
            dev = ""
            if block.idx == 0 and op.type in per_op_ms:
                dev = f"  [dev ~{per_op_ms[op.type]:.3f}ms]"
            lines.append(f"  [{i}] {op.type}({ins}) -> {outs}{dev}{mark}")
    return "\n".join(lines)


def draw_block_graphviz(program: Program, block_idx: int = 0,
                        path: Optional[str] = None,
                        highlights: Optional[set] = None) -> str:
    """Graphviz dot source for one block's dataflow: op nodes (boxes)
    connected through var nodes (ellipses). Write to ``path`` if given."""
    block = program.blocks[block_idx]
    highlights = highlights or set()
    lines = ["digraph G {", "  rankdir=TB;"]
    # sequential ids: deterministic across runs and collision-free
    var_ids: dict = {}

    def var_node(name):
        if name not in var_ids:
            var_ids[name] = f"var_{len(var_ids)}"
            color = ' style=filled fillcolor="#ffd27f"' \
                if name in highlights else ""
            lines.append(
                f'  {var_ids[name]} [label="{name}" shape=ellipse{color}];')
        return var_ids[name]

    for i, op in enumerate(block.ops):
        op_id = f"op_{i}"
        lines.append(
            f'  {op_id} [label="{op.type}" shape=box '
            f'style=filled fillcolor="#cfe2ff"];'
        )
        for n in op.input_arg_names:
            if n:
                lines.append(f"  {var_node(n)} -> {op_id};")
        for n in op.output_arg_names:
            if n:
                lines.append(f"  {op_id} -> {var_node(n)};")
    lines.append("}")
    dot = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot
