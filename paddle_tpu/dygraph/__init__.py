from paddle_tpu.dygraph import base  # noqa: F401
