"""Dygraph (imperative/eager) mode
(reference: python/paddle/fluid/dygraph/)."""

from paddle_tpu.dygraph import nn  # noqa: F401
from paddle_tpu.dygraph.base import (  # noqa: F401
    _in_dygraph_mode,
    enabled,
    guard,
    no_grad,
    to_variable,
)
from paddle_tpu.dygraph.checkpoint import load_dygraph, save_dygraph  # noqa: F401
from paddle_tpu.dygraph.layers import Layer  # noqa: F401
from paddle_tpu.dygraph.nn import (  # noqa: F401
    FC,
    BatchNorm,
    Conv2D,
    Conv2DTranspose,
    Dropout,
    Embedding,
    GroupNorm,
    GRUUnit,
    LayerNorm,
    Linear,
    Pool2D,
    PRelu,
)
from paddle_tpu.dygraph.parallel import (  # noqa: F401
    DataParallel,
    ParallelEnv,
)
from paddle_tpu.dygraph.tracer import Tracer, VarBase, get_tracer  # noqa: F401
