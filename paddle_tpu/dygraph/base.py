"""Dygraph (eager) mode base (reference: python/paddle/fluid/dygraph/base.py:29)."""

import contextlib

_in_dygraph = False


def _in_dygraph_mode() -> bool:
    return _in_dygraph


@contextlib.contextmanager
def guard(place=None):
    global _in_dygraph
    old = _in_dygraph
    _in_dygraph = True
    try:
        yield
    finally:
        _in_dygraph = old
