"""Dygraph (eager) mode entry points
(reference: python/paddle/fluid/dygraph/base.py:29).

``guard()`` switches the process into imperative mode: layers and optimizers
check ``_in_dygraph_mode()`` and route through the eager Tracer instead of
appending ops to the default Program.
"""

from __future__ import annotations

import contextlib

import numpy as np

from paddle_tpu.dygraph.tracer import VarBase, get_tracer

_in_dygraph = False


def _in_dygraph_mode() -> bool:
    return _in_dygraph


enabled = _in_dygraph_mode


@contextlib.contextmanager
def guard(place=None):
    """Enter dygraph mode. ``place`` is accepted for API parity; device
    placement is JAX's default-device policy (TPU when present).

    For inference loops use :func:`no_grad` inside the guard — otherwise
    every op touching a trainable parameter is taped until ``backward()``
    consumes it. The tape is released when the guard exits."""
    global _in_dygraph
    old = _in_dygraph
    _in_dygraph = True
    try:
        yield
    finally:
        _in_dygraph = old
        if not old:
            get_tracer().reset()


def to_variable(value, name=None, block=None) -> VarBase:
    """numpy / scalar / VarBase -> eager VarBase
    (reference: dygraph/base.py ``to_variable``)."""
    if isinstance(value, VarBase):
        return value
    arr = np.asarray(value)
    # Data (as opposed to parameters) defaults to no-grad, matching the
    # reference where only parameters/intermediates track gradients unless
    # stop_gradient is cleared explicitly.
    return VarBase(arr, name=name, stop_gradient=True)


@contextlib.contextmanager
def no_grad():
    with get_tracer().no_grad():
        yield
