"""Eager save/load of Layer state dicts
(reference: python/paddle/fluid/dygraph/checkpoint.py)."""

from __future__ import annotations

import os
from typing import Dict

import numpy as np


def save_dygraph(state_dict: Dict[str, np.ndarray], model_path: str):
    """Save a ``Layer.state_dict()`` (or optimizer state) to ``<path>.npz``."""
    if not state_dict:
        raise ValueError("save_dygraph: empty state dict")
    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    np.savez(model_path + ".npz", **{k: np.asarray(v) for k, v in state_dict.items()})


def load_dygraph(model_path: str) -> Dict[str, np.ndarray]:
    """Load a state dict saved by ``save_dygraph``."""
    path = model_path if model_path.endswith(".npz") else model_path + ".npz"
    with np.load(path) as z:
        return {k: z[k] for k in z.files}
