"""Eager ``Layer`` base class (reference: python/paddle/fluid/dygraph/layers.py:31).

Parameters are eager ``VarBase`` values created by running the same
initializer ops the static graph uses (traced into a throwaway block and
executed through the shared interpreter), so eager and static models
initialize identically given the same seed.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import jax
import numpy as np

from paddle_tpu import unique_name
from paddle_tpu.core.interp import exec_ops
from paddle_tpu.dygraph.tracer import VarBase, get_tracer
from paddle_tpu.framework import Program
from paddle_tpu.initializer import (
    ConstantInitializer,
    Initializer,
    XavierInitializer,
)
from paddle_tpu.param_attr import ParamAttr

_init_counter = [0]


def eager_initialize(shape, dtype, initializer: Initializer, seed=None):
    """Run a static-graph initializer eagerly: trace its fill op into a
    throwaway block, execute through the shared interpreter."""
    prog = Program()
    block = prog.global_block()
    var = block.create_var(name="param", shape=list(shape), dtype=dtype)
    initializer(var, block)
    _init_counter[0] += 1
    key = jax.random.PRNGKey(
        seed if seed is not None else _init_counter[0]
    )
    env = exec_ops(block.ops, {}, key=key, amp=False)
    return env["param"]


class Layer:
    """Composable eager module (reference: dygraph/layers.py:31)."""

    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        self._full_name = unique_name.generate(
            name_scope or self.__class__.__name__.lower()
        )
        self._dtype = dtype
        self._parameters: Dict[str, VarBase] = {}
        self._sub_layers: Dict[str, "Layer"] = {}
        self.training = True

    def full_name(self) -> str:
        return self._full_name

    # --- modes ---

    def train(self):
        self.training = True
        for l in self._sub_layers.values():
            l.train()
        return self

    def eval(self):
        self.training = False
        for l in self._sub_layers.values():
            l.eval()
        return self

    # --- parameter management ---

    def create_parameter(
        self,
        attr,
        shape,
        dtype="float32",
        is_bias: bool = False,
        default_initializer: Optional[Initializer] = None,
        suffix: Optional[str] = None,
    ) -> Optional[VarBase]:
        attr = ParamAttr._to_attr(attr)
        if attr is False or (attr is not None and attr.name is False):
            return None
        name = (attr.name if attr else None) or unique_name.generate(
            f"{self._full_name}.{suffix or ('b' if is_bias else 'w')}"
        )
        init = (attr.initializer if attr else None) or default_initializer
        if init is None:
            init = ConstantInitializer(0.0) if is_bias else XavierInitializer()
        value = eager_initialize(shape, dtype, init)
        p = VarBase(value, name=name, stop_gradient=False, persistable=True)
        p.optimize_attr = {
            "learning_rate": attr.learning_rate if attr else 1.0
        }
        p.regularizer = attr.regularizer if attr else None
        self._parameters[name] = p
        return p

    def add_parameter(self, name: str, param: VarBase) -> VarBase:
        self._parameters[name] = param
        return param

    def add_sublayer(self, name: str, layer: "Layer") -> "Layer":
        self._sub_layers[name] = layer
        return layer

    def parameters(self, include_sublayers: bool = True) -> List[VarBase]:
        out = list(self._parameters.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.parameters())
        return out

    def sublayers(self, include_sublayers: bool = True) -> List["Layer"]:
        out = list(self._sub_layers.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.sublayers())
        return out

    def named_parameters(self) -> Iterator[Tuple[str, VarBase]]:
        for n, p in self._parameters.items():
            yield n, p
        for l in self._sub_layers.values():
            yield from l.named_parameters()

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    # --- state dict (reference: dygraph/checkpoint.py) ---

    def state_dict(self) -> Dict[str, np.ndarray]:
        return {n: p.numpy() for n, p in self.named_parameters()}

    def set_dict(self, state: Dict[str, np.ndarray], strict: bool = True):
        own = dict(self.named_parameters())
        missing = [n for n in own if n not in state]
        if strict and missing:
            raise KeyError(
                f"set_dict: {len(missing)} parameters missing from the "
                f"state dict (e.g. {missing[:5]})"
            )
        for n, p in own.items():
            if n in state:
                p._value = jax.numpy.asarray(state[n]).astype(p.dtype)

    load_dict = set_dict

    # --- attribute sugar: assignment registers params/sublayers ---

    def __setattr__(self, name, value):
        if isinstance(value, VarBase) and getattr(value, "persistable", False):
            params = self.__dict__.get("_parameters")
            if params is not None:
                params[value.name] = value
        elif isinstance(value, Layer):
            subs = self.__dict__.get("_sub_layers")
            if subs is not None:
                subs[name] = value
        object.__setattr__(self, name, value)

    # --- forward ---

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    # helper for subclasses
    def _trace(self, op_type, ins, attrs=None):
        return get_tracer().trace_op(op_type, ins, attrs)
