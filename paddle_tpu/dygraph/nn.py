"""Eager layer classes (reference: python/paddle/fluid/dygraph/nn.py:35-2334).

Each class owns its parameters as eager VarBases and routes forward through
the shared op registry via the tracer, so static and eager modes exercise
the same kernels.
"""

from __future__ import annotations

from typing import Optional

from paddle_tpu.dygraph.layers import Layer
from paddle_tpu.dygraph.tracer import VarBase
from paddle_tpu.initializer import ConstantInitializer, NormalInitializer


def _first(outs, *slots):
    for s in slots:
        if s in outs and outs[s]:
            return outs[s][0]
    raise KeyError(f"none of {slots} in op outputs")


class Conv2D(Layer):
    """reference: dygraph/nn.py Conv2D (operators/conv_op.cc)."""

    def __init__(
        self,
        name_scope,
        num_filters,
        filter_size,
        stride=1,
        padding=0,
        dilation=1,
        groups=1,
        param_attr=None,
        bias_attr=None,
        use_cudnn=True,
        act=None,
        dtype="float32",
    ):
        super().__init__(name_scope, dtype)
        self._num_filters = num_filters
        self._filter_size = self._pair(filter_size)
        self._stride = self._pair(stride)
        self._padding = self._pair(padding)
        self._dilation = self._pair(dilation)
        self._groups = groups
        self._act = act
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._filter: Optional[VarBase] = None
        self._bias: Optional[VarBase] = None

    @staticmethod
    def _pair(v):
        return tuple(v) if isinstance(v, (list, tuple)) else (v, v)

    def _build_once(self, x):
        cin = x.shape[1]
        fshape = [
            self._num_filters,
            cin // self._groups,
            self._filter_size[0],
            self._filter_size[1],
        ]
        self._filter = self.create_parameter(
            self._param_attr, fshape, self._dtype
        )
        self._bias = self.create_parameter(
            self._bias_attr, [self._num_filters], self._dtype, is_bias=True
        )

    def forward(self, x: VarBase) -> VarBase:
        if self._filter is None:
            self._build_once(x)
        outs = self._trace(
            "conv2d",
            {"Input": [x], "Filter": [self._filter]},
            {
                "strides": list(self._stride),
                "paddings": list(self._padding),
                "dilations": list(self._dilation),
                "groups": self._groups,
            },
        )
        y = _first(outs, "Output")
        if self._bias is not None:
            y = _first(
                self._trace(
                    "elementwise_add",
                    {"X": [y], "Y": [self._bias]},
                    {"axis": 1},
                ),
                "Out",
            )
        if self._act:
            y = _first(self._trace(self._act, {"X": [y]}, {}), "Out")
        return y


class Conv2DTranspose(Conv2D):
    """reference: dygraph/nn.py Conv2DTranspose."""

    def _build_once(self, x):
        cin = x.shape[1]
        fshape = [
            cin,
            self._num_filters // self._groups,
            self._filter_size[0],
            self._filter_size[1],
        ]
        self._filter = self.create_parameter(
            self._param_attr, fshape, self._dtype
        )
        self._bias = self.create_parameter(
            self._bias_attr, [self._num_filters], self._dtype, is_bias=True
        )

    def forward(self, x: VarBase) -> VarBase:
        if self._filter is None:
            self._build_once(x)
        outs = self._trace(
            "conv2d_transpose",
            {"Input": [x], "Filter": [self._filter]},
            {
                "strides": list(self._stride),
                "paddings": list(self._padding),
                "dilations": list(self._dilation),
                "groups": self._groups,
            },
        )
        y = _first(outs, "Output")
        if self._bias is not None:
            y = _first(
                self._trace(
                    "elementwise_add",
                    {"X": [y], "Y": [self._bias]},
                    {"axis": 1},
                ),
                "Out",
            )
        if self._act:
            y = _first(self._trace(self._act, {"X": [y]}, {}), "Out")
        return y


class Pool2D(Layer):
    """reference: dygraph/nn.py Pool2D (operators/pool_op.cc)."""

    def __init__(
        self,
        name_scope,
        pool_size=-1,
        pool_type="max",
        pool_stride=1,
        pool_padding=0,
        global_pooling=False,
        ceil_mode=False,
        exclusive=True,
        dtype="float32",
    ):
        super().__init__(name_scope, dtype)
        self._attrs = {
            "pooling_type": pool_type,
            "ksize": list(Conv2D._pair(pool_size)),
            "strides": list(Conv2D._pair(pool_stride)),
            "paddings": list(Conv2D._pair(pool_padding)),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        }

    def forward(self, x: VarBase) -> VarBase:
        return _first(self._trace("pool2d", {"X": [x]}, dict(self._attrs)), "Out")


class FC(Layer):
    """Fully connected (reference: dygraph/nn.py FC; mul_op.cc)."""

    def __init__(
        self,
        name_scope,
        size,
        num_flatten_dims=1,
        param_attr=None,
        bias_attr=None,
        act=None,
        dtype="float32",
    ):
        super().__init__(name_scope, dtype)
        self._size = size
        self._num_flatten_dims = num_flatten_dims
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act
        self._w: Optional[VarBase] = None
        self._b: Optional[VarBase] = None

    def forward(self, x: VarBase) -> VarBase:
        if self._w is None:
            in_dim = 1
            for d in x.shape[self._num_flatten_dims :]:
                in_dim *= d
            self._w = self.create_parameter(
                self._param_attr, [in_dim, self._size], self._dtype
            )
            self._b = self.create_parameter(
                self._bias_attr, [self._size], self._dtype, is_bias=True
            )
        y = _first(
            self._trace(
                "mul",
                {"X": [x], "Y": [self._w]},
                {"x_num_col_dims": self._num_flatten_dims, "y_num_col_dims": 1},
            ),
            "Out",
        )
        if self._b is not None:
            y = _first(
                self._trace(
                    "elementwise_add",
                    {"X": [y], "Y": [self._b]},
                    {"axis": self._num_flatten_dims},
                ),
                "Out",
            )
        if self._act:
            y = _first(self._trace(self._act, {"X": [y]}, {}), "Out")
        return y


class Linear(Layer):
    """Later-API linear layer with explicit dims:
    ``Linear(input_dim, output_dim, ...)`` (vs FC's lazy input-dim)."""

    def __init__(
        self,
        input_dim,
        output_dim,
        param_attr=None,
        bias_attr=None,
        act=None,
        dtype="float32",
    ):
        super().__init__("linear", dtype)
        self._act = act
        self.weight = self.create_parameter(
            param_attr, [int(input_dim), int(output_dim)], dtype
        )
        self.bias = self.create_parameter(
            bias_attr, [int(output_dim)], dtype, is_bias=True
        )

    def forward(self, x: VarBase) -> VarBase:
        y = _first(
            self._trace(
                "mul",
                {"X": [x], "Y": [self.weight]},
                {"x_num_col_dims": max(x.ndim - 1, 1), "y_num_col_dims": 1},
            ),
            "Out",
        )
        if self.bias is not None:
            y = _first(
                self._trace(
                    "elementwise_add",
                    {"X": [y], "Y": [self.bias]},
                    {"axis": -1},
                ),
                "Out",
            )
        if self._act:
            y = _first(self._trace(self._act, {"X": [y]}, {}), "Out")
        return y


class BatchNorm(Layer):
    """reference: dygraph/nn.py BatchNorm (operators/batch_norm_op.cc).
    Running mean/variance live as no-grad VarBases updated in place."""

    def __init__(
        self,
        name_scope,
        num_channels,
        act=None,
        momentum=0.9,
        epsilon=1e-5,
        param_attr=None,
        bias_attr=None,
        data_layout="NCHW",
        dtype="float32",
    ):
        super().__init__(name_scope, dtype)
        self._momentum = momentum
        self._epsilon = epsilon
        self._layout = data_layout
        self._act = act
        self.scale = self.create_parameter(
            param_attr,
            [num_channels],
            dtype,
            default_initializer=ConstantInitializer(1.0),
            suffix="scale",
        )
        self.bias = self.create_parameter(
            bias_attr, [num_channels], dtype, is_bias=True, suffix="offset"
        )
        import jax.numpy as jnp

        # Running stats are persistable (round-trip through state_dict)
        # but stop_gradient, so optimizers skip them.
        self._mean = self.add_parameter(
            f"{self._full_name}.mean",
            VarBase(
                jnp.zeros((num_channels,), dtype),
                name=f"{self._full_name}.mean",
                stop_gradient=True,
                persistable=True,
            ),
        )
        self._variance = self.add_parameter(
            f"{self._full_name}.variance",
            VarBase(
                jnp.ones((num_channels,), dtype),
                name=f"{self._full_name}.variance",
                stop_gradient=True,
                persistable=True,
            ),
        )

    def forward(self, x: VarBase) -> VarBase:
        outs = self._trace(
            "batch_norm",
            {
                "X": [x],
                "Scale": [self.scale] if self.scale is not None else [],
                "Bias": [self.bias] if self.bias is not None else [],
                "Mean": [self._mean],
                "Variance": [self._variance],
            },
            {
                "epsilon": self._epsilon,
                "momentum": self._momentum,
                "is_test": not self.training,
                "data_layout": self._layout,
            },
        )
        # in-place running-stat update (reference batch_norm MeanOut<-Mean)
        if self.training:
            self._mean._value = outs["MeanOut"][0]._value
            self._variance._value = outs["VarianceOut"][0]._value
        y = _first(outs, "Y")
        if self._act:
            y = _first(self._trace(self._act, {"X": [y]}, {}), "Out")
        return y


class Embedding(Layer):
    """reference: dygraph/nn.py Embedding (operators/lookup_table_op.cc)."""

    def __init__(
        self,
        name_scope,
        size,
        is_sparse=False,
        padding_idx=None,
        param_attr=None,
        dtype="float32",
    ):
        super().__init__(name_scope, dtype)
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            param_attr,
            list(size),
            dtype,
            default_initializer=NormalInitializer(0.0, 0.02),
        )

    def forward(self, ids: VarBase) -> VarBase:
        attrs = {"squeeze_last": False}
        if self._padding_idx is not None:
            attrs["padding_idx"] = self._padding_idx
        return _first(
            self._trace(
                "lookup_table", {"W": [self.weight], "Ids": [ids]}, attrs
            ),
            "Out",
        )


class LayerNorm(Layer):
    """reference: dygraph/nn.py LayerNorm (operators/layer_norm_op.cc)."""

    def __init__(
        self,
        name_scope,
        normalized_shape,
        scale=True,
        shift=True,
        begin_norm_axis=1,
        epsilon=1e-5,
        param_attr=None,
        bias_attr=None,
        act=None,
        dtype="float32",
    ):
        super().__init__(name_scope, dtype)
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._epsilon = epsilon
        self._begin_norm_axis = begin_norm_axis
        self._act = act
        n = 1
        for d in normalized_shape:
            n *= d
        self.scale = (
            self.create_parameter(
                param_attr,
                [n],
                dtype,
                default_initializer=ConstantInitializer(1.0),
                suffix="scale",
            )
            if scale
            else None
        )
        self.bias = (
            self.create_parameter(
                bias_attr, [n], dtype, is_bias=True, suffix="offset"
            )
            if shift
            else None
        )

    def forward(self, x: VarBase) -> VarBase:
        y = _first(
            self._trace(
                "layer_norm",
                {
                    "X": [x],
                    "Scale": [self.scale] if self.scale is not None else [],
                    "Bias": [self.bias] if self.bias is not None else [],
                },
                {
                    "epsilon": self._epsilon,
                    "begin_norm_axis": self._begin_norm_axis,
                },
            ),
            "Y",
        )
        if self._act:
            y = _first(self._trace(self._act, {"X": [y]}, {}), "Out")
        return y


class GroupNorm(Layer):
    """reference: dygraph/nn.py GroupNorm (operators/group_norm_op.cc)."""

    def __init__(
        self,
        name_scope,
        channels,
        groups,
        epsilon=1e-5,
        param_attr=None,
        bias_attr=None,
        act=None,
        dtype="float32",
    ):
        super().__init__(name_scope, dtype)
        self._groups = groups
        self._epsilon = epsilon
        self._act = act
        self.scale = self.create_parameter(
            param_attr,
            [channels],
            dtype,
            default_initializer=ConstantInitializer(1.0),
            suffix="scale",
        )
        self.bias = self.create_parameter(
            bias_attr, [channels], dtype, is_bias=True, suffix="offset"
        )

    def forward(self, x: VarBase) -> VarBase:
        y = _first(
            self._trace(
                "group_norm",
                {"X": [x], "Scale": [self.scale], "Bias": [self.bias]},
                {"groups": self._groups, "epsilon": self._epsilon},
            ),
            "Y",
        )
        if self._act:
            y = _first(self._trace(self._act, {"X": [y]}, {}), "Out")
        return y


class PRelu(Layer):
    """reference: dygraph/nn.py PRelu (operators/prelu_op.cc)."""

    def __init__(
        self, name_scope, mode="all", channel=None, input_shape=None,
        param_attr=None, dtype="float32",
    ):
        super().__init__(name_scope, dtype)
        self._mode = mode
        if mode == "all":
            shape = [1]
        elif mode == "channel":
            shape = [channel]
        elif mode == "element":
            shape = list(input_shape[1:])
        else:
            raise ValueError(f"unknown prelu mode {mode!r}")
        self.alpha = self.create_parameter(
            param_attr,
            shape,
            dtype,
            default_initializer=ConstantInitializer(0.25),
            suffix="alpha",
        )

    def forward(self, x: VarBase) -> VarBase:
        return _first(
            self._trace(
                "prelu", {"X": [x], "Alpha": [self.alpha]}, {"mode": self._mode}
            ),
            "Out",
        )


class GRUUnit(Layer):
    """One-step GRU cell (reference: dygraph/nn.py GRUUnit)."""

    def __init__(
        self,
        name_scope,
        size,
        param_attr=None,
        bias_attr=None,
        activation="tanh",
        gate_activation="sigmoid",
        dtype="float32",
    ):
        super().__init__(name_scope, dtype)
        if size % 3 != 0:
            raise ValueError("GRUUnit size must be 3 * hidden")
        h = size // 3
        self._attrs = {
            "activation": activation,
            "gate_activation": gate_activation,
        }
        self.weight = self.create_parameter(param_attr, [h, 3 * h], dtype)
        self.bias = self.create_parameter(
            bias_attr, [3 * h], dtype, is_bias=True
        )

    def forward(self, x: VarBase, hidden: VarBase):
        outs = self._trace(
            "gru_unit",
            {
                "Input": [x],
                "HiddenPrev": [hidden],
                "Weight": [self.weight],
                "Bias": [self.bias] if self.bias is not None else [],
            },
            dict(self._attrs),
        )
        return outs["Hidden"][0], outs["ResetHiddenPrev"][0], outs["Gate"][0]


class Dropout(Layer):
    """Eager dropout honoring train/eval mode."""

    def __init__(self, name_scope, p=0.5, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._p = p

    def forward(self, x: VarBase) -> VarBase:
        return _first(
            self._trace(
                "dropout",
                {"X": [x]},
                {"dropout_prob": self._p, "is_test": not self.training},
            ),
            "Out",
        )


class Conv3D(Layer):
    """reference: dygraph/nn.py Conv3D (operators/conv_op.cc 3-D)."""

    def __init__(self, name_scope, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        trip = lambda v: (tuple(v) if isinstance(v, (list, tuple))
                          else (v,) * 3)
        self._num_filters = num_filters
        self._filter_size = trip(filter_size)
        self._stride = trip(stride)
        self._padding = trip(padding)
        self._dilation = trip(dilation)
        self._groups = groups or 1
        self._act = act
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._filter = None
        self._bias = None

    def _build_once(self, x):
        cin = x.shape[1]
        self._filter = self.create_parameter(
            self._param_attr,
            [self._num_filters, cin // self._groups] + list(
                self._filter_size), self._dtype)
        self._bias = self.create_parameter(
            self._bias_attr, [self._num_filters], self._dtype, is_bias=True)

    _OP = "conv3d"

    def forward(self, x):
        if self._filter is None:
            self._build_once(x)
        outs = self._trace(
            self._OP, {"Input": [x], "Filter": [self._filter]},
            {"strides": list(self._stride), "paddings": list(self._padding),
             "dilations": list(self._dilation), "groups": self._groups})
        y = _first(outs, "Output")
        if self._bias is not None:
            y = _first(self._trace("elementwise_add",
                                   {"X": [y], "Y": [self._bias]},
                                   {"axis": 1}), "Out")
        if self._act:
            y = _first(self._trace(self._act, {"X": [y]}, {}), "Out")
        return y


class Conv3DTranspose(Conv3D):
    """reference: dygraph/nn.py Conv3DTranspose."""

    _OP = "conv3d_transpose"

    def _build_once(self, x):
        cin = x.shape[1]
        self._filter = self.create_parameter(
            self._param_attr,
            [cin, self._num_filters // self._groups] + list(
                self._filter_size), self._dtype)
        self._bias = self.create_parameter(
            self._bias_attr, [self._num_filters], self._dtype, is_bias=True)


class NCE(Layer):
    """reference: dygraph/nn.py NCE (operators/nce_op.cc)."""

    def __init__(self, name_scope, num_total_classes, param_attr=None,
                 bias_attr=None, num_neg_samples=10, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._num_total_classes = num_total_classes
        self._num_neg = num_neg_samples
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._w = None
        self._b = None

    def forward(self, input, label):
        if self._w is None:
            d = input.shape[-1]
            self._w = self.create_parameter(
                self._param_attr, [self._num_total_classes, d], self._dtype)
            self._b = self.create_parameter(
                self._bias_attr, [self._num_total_classes], self._dtype,
                is_bias=True)
        ins = {"Input": [input], "Label": [label], "Weight": [self._w]}
        if self._b is not None:
            ins["Bias"] = [self._b]
        return _first(self._trace(
            "nce", ins, {"num_neg_samples": self._num_neg}), "Cost")


class BilinearTensorProduct(Layer):
    """reference: dygraph/nn.py BilinearTensorProduct."""

    def __init__(self, name_scope, size, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._size = size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act
        self._w = None
        self._b = None

    def forward(self, x, y):
        if self._w is None:
            self._w = self.create_parameter(
                self._param_attr,
                [self._size, x.shape[-1], y.shape[-1]], self._dtype)
            self._b = self.create_parameter(
                self._bias_attr, [self._size], self._dtype, is_bias=True)
        ins = {"X": [x], "Y": [y], "Weight": [self._w]}
        if self._b is not None:
            ins["Bias"] = [self._b]
        out = _first(self._trace("bilinear_tensor_product", ins, {}), "Out")
        if self._act:
            out = _first(self._trace(self._act, {"X": [out]}, {}), "Out")
        return out


class SequenceConv(Layer):
    """reference: dygraph/nn.py SequenceConv (context-window conv over
    padded [b, t, d] batches — the dense LoD redesign)."""

    def __init__(self, name_scope, num_filters, filter_size=3,
                 filter_stride=1, padding=None, bias_attr=None,
                 param_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._num_filters = num_filters
        self._filter_size = filter_size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act
        self._filter = None
        self._bias = None

    def forward(self, x):
        if self._filter is None:
            d = x.shape[-1]
            self._filter = self.create_parameter(
                self._param_attr, [self._filter_size * d,
                                   self._num_filters], self._dtype)
            self._bias = self.create_parameter(
                self._bias_attr, [self._num_filters], self._dtype,
                is_bias=True)
        outs = self._trace(
            "sequence_conv", {"X": [x], "Filter": [self._filter]},
            {"contextLength": self._filter_size, "contextStart":
             -(self._filter_size // 2), "contextStride": 1})
        y = _first(outs, "Out")
        if self._bias is not None:
            y = _first(self._trace("elementwise_add",
                                   {"X": [y], "Y": [self._bias]},
                                   {"axis": 2}), "Out")
        if self._act:
            y = _first(self._trace(self._act, {"X": [y]}, {}), "Out")
        return y


class RowConv(Layer):
    """reference: dygraph/nn.py RowConv (operators/row_conv_op.cc)."""

    def __init__(self, name_scope, future_context_size, param_attr=None,
                 act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._future = future_context_size
        self._param_attr = param_attr
        self._act = act
        self._filter = None

    def forward(self, x):
        if self._filter is None:
            self._filter = self.create_parameter(
                self._param_attr, [self._future + 1, x.shape[-1]],
                self._dtype)
        y = _first(self._trace(
            "row_conv", {"X": [x], "Filter": [self._filter]}, {}), "Out")
        if self._act:
            y = _first(self._trace(self._act, {"X": [y]}, {}), "Out")
        return y


class SpectralNorm(Layer):
    """reference: dygraph/nn.py SpectralNorm (operators/spectral_norm_op.cc).
    The power-iteration vectors persist as non-trainable state, as the
    static path does."""

    def __init__(self, name_scope, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        self._u = None
        self._v = None

    def forward(self, weight):
        if self._u is None:
            h = weight.shape[self._dim]
            w = 1
            for i, s in enumerate(weight.shape):
                if i != self._dim:
                    w *= s
            self._u = self.create_parameter(
                None, [h], self._dtype,
                default_initializer=NormalInitializer(0.0, 1.0))
            self._v = self.create_parameter(
                None, [w], self._dtype,
                default_initializer=NormalInitializer(0.0, 1.0))
        outs = self._trace(
            "spectral_norm",
            {"Weight": [weight], "U": [self._u], "V": [self._v]},
            {"dim": self._dim, "power_iters": self._power_iters,
             "eps": self._eps})
        return _first(outs, "Out")


class TreeConv(Layer):
    """reference: dygraph/nn.py TreeConv (operators/tree_conv_op.cc)."""

    def __init__(self, name_scope, output_size, num_filters=1, max_depth=2,
                 act="tanh", param_attr=None, bias_attr=None,
                 name=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._output_size = output_size
        self._num_filters = num_filters
        self._max_depth = max_depth
        self._act = act
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._w = None

    def forward(self, nodes_vector, edge_set):
        if self._w is None:
            f = nodes_vector.shape[2]
            self._w = self.create_parameter(
                self._param_attr,
                [f, 3, self._output_size, self._num_filters], self._dtype)
        out = _first(self._trace(
            "tree_conv",
            {"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
             "Filter": [self._w]},
            {"max_depth": self._max_depth}), "Out")
        if self._act:
            out = _first(self._trace(self._act, {"X": [out]}, {}), "Out")
        return out
