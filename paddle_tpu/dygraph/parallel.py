"""Eager data parallelism over the local device mesh.

The TPU-native counterpart of the reference's dygraph ``DataParallel``
(reference: python/paddle/fluid/dygraph/parallel.py:84), which wraps a
Layer, scales the loss by trainer count, and all-reduces gradients over
NCCL after ``backward()``. Here none of that choreography is manual:

- parameters are placed REPLICATED over a ``jax.sharding.Mesh`` of the
  local devices;
- inputs are placed batch-sharded (``P('data')`` on dim 0);
- every eager op then executes as an SPMD computation on the sharded
  arrays, and the taped backward's parameter cotangents contract over the
  sharded batch dimension — XLA inserts the all-reduce itself, so the
  gradients arriving at the optimizer are already global and replicated.

``scale_loss``/``apply_collective_grads`` are therefore identity
operations kept for reference API compatibility (loss ops average over
the GLOBAL batch here, unlike per-trainer local batches + summing
all-reduce in the reference).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.dygraph.layers import Layer
from paddle_tpu.dygraph.tracer import VarBase


class ParallelEnv:
    """Reference-API shim (dygraph/parallel.py ParallelEnv): local rank /
    world size of the eager data-parallel run. Single-process multi-device
    on TPU, so rank is 0 and nranks is the device count."""

    def __init__(self, mesh: Optional[Mesh] = None):
        self.nranks = (
            int(np.prod(list(mesh.shape.values()))) if mesh is not None
            else jax.local_device_count()
        )
        self.local_rank = 0
        self.dev_id = 0


def _default_mesh(data_axis: str) -> Mesh:
    devs = np.asarray(jax.devices())
    return Mesh(devs, (data_axis,))


class DataParallel(Layer):
    """Wrap a dygraph Layer for multi-device eager training.

    Usage (mirrors the reference)::

        model = DataParallel(MLP())
        loss = model(x, label)
        loss = model.scale_loss(loss)       # identity, API parity
        loss.backward()
        model.apply_collective_grads()      # identity, API parity
        optimizer.minimize(loss, parameter_list=model.parameters())
    """

    def __init__(self, layer: Layer, strategy=None,
                 mesh: Optional[Mesh] = None, data_axis: str = "data"):
        super().__init__()
        self._layers = layer
        self._data_axis = data_axis
        self._mesh = mesh if mesh is not None else _default_mesh(data_axis)
        self._env = ParallelEnv(self._mesh)
        self._replicated = NamedSharding(self._mesh, P())
        self._batch_sharded = NamedSharding(self._mesh, P(data_axis))
        # replicate parameters across the mesh; optimizer updates preserve
        # the placement (replicated op on replicated operands). Layers
        # that build parameters lazily (FC on first forward) are re-placed
        # after the first call — see forward().
        self._placed_n_params = -1
        self._replicate_params()

    def _replicate_params(self):
        for p in self._layers.parameters():
            p._value = jax.device_put(p._value, self._replicated)

    # --- Layer surface delegates to the wrapped module ---

    def parameters(self, include_sublayers: bool = True):
        return self._layers.parameters(include_sublayers)

    def sublayers(self, include_sublayers: bool = True):
        return self._layers.sublayers(include_sublayers)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_dict(self, *a, **kw):
        return self._layers.set_dict(*a, **kw)

    load_dict = set_dict

    def shard_input(self, value):
        """Batch-shard an input (VarBase / ndarray) over the data axis."""
        if isinstance(value, VarBase):
            value._value = jax.device_put(value._value, self._batch_sharded)
            return value
        return VarBase(jax.device_put(np.asarray(value), self._batch_sharded),
                       stop_gradient=True)

    def forward(self, *inputs, **kwargs):
        sharded = [
            self.shard_input(x)
            if isinstance(x, (VarBase, np.ndarray)) else x
            for x in inputs
        ]
        out = self._layers(*sharded, **kwargs)
        # Lazily-built parameters (FC et al. materialize weights on their
        # first call) must be pinned replicated. Sublayers may keep lazy-
        # building on LATER calls (shape-dependent builds), so re-pin
        # whenever the parameter count grows — device_put on an already-
        # replicated array is cheap.
        n_params = len(self._layers.parameters())
        if n_params != self._placed_n_params:
            self._replicate_params()
            self._placed_n_params = n_params
        return out

    def scale_loss(self, loss: VarBase) -> VarBase:
        """Identity: losses here average over the GLOBAL sharded batch,
        so no 1/nranks scaling is needed (reference scales because each
        trainer averages only its local batch)."""
        return loss

    def apply_collective_grads(self):
        """Identity: XLA already reduced the parameter cotangents across
        the batch shards during backward."""
        return None
