"""Dygraph (imperative) engine: eager op execution with taped autograd.

The TPU-native analog of the reference's imperative tracer
(reference: paddle/fluid/imperative/tracer.cc:138, imperative/layer.cc:426,
python/paddle/fluid/dygraph/tracer.py:32). Design differences:

- Ops run *eagerly through the same op registry* used by the static-graph
  executor: a traced op simply calls the registered JAX kernel on the
  underlying ``jax.Array`` values, so every registered op works in dygraph
  with zero extra code (the reference re-dispatches into the same C++
  kernels for the same reason).
- The tape records (op_def, input arrays, output arrays, attrs) per traced
  op. ``backward()`` walks the tape in reverse and calls the mechanically
  vjp-derived grad kernel (core/autodiff.make_grad_compute) — the eager twin
  of ``OpBase::ApplyGrad`` (reference: imperative/layer.cc:257).
- RNG: stochastic ops (dropout) draw stateless PRNG keys from the tracer;
  the tape stores the key so the grad replay sees identical randomness
  (the reference stores per-op seeds for the same purpose).
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional

import jax
import numpy as np
import jax.numpy as jnp

from paddle_tpu import unique_name
from paddle_tpu.core import autodiff
from paddle_tpu.core.autodiff import GRAD_SLOT_PREFIX
from paddle_tpu.core.registry import OpDef, get_op_def


class VarBase:
    """Eager variable: a jax.Array plus autograd metadata
    (reference: imperative/layer.h:116 ``VarBase``)."""

    def __init__(
        self,
        value,
        name: Optional[str] = None,
        stop_gradient: bool = False,
        persistable: bool = False,
    ):
        self._value = jnp.asarray(value)
        self.name = name or unique_name.generate("dy_var")
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self._grad = None  # cotangent filled in by backward()

    # --- array-ish surface ---

    @property
    def shape(self):
        return tuple(self._value.shape)

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def ndim(self):
        return self._value.ndim

    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    def gradient(self) -> Optional[np.ndarray]:
        return None if self._grad is None else np.asarray(self._grad)

    def clear_gradient(self):
        self._grad = None

    def detach(self) -> "VarBase":
        return VarBase(self._value, stop_gradient=True)

    def astype(self, dtype) -> "VarBase":
        return _trace1("cast", {"X": [self]}, attrs={"out_dtype": str(dtype)})

    def backward(self):
        get_tracer().run_backward(self)

    def __repr__(self):
        return (
            f"VarBase({self.name}, shape={self.shape}, dtype={self.dtype}"
            + (", stop_gradient" if self.stop_gradient else "")
            + ")"
        )

    __str__ = __repr__

    # --- arithmetic sugar (traced so gradients flow) ---

    def _binary(self, other, op_type, reverse=False):
        if not isinstance(other, VarBase):
            other = VarBase(
                jnp.asarray(other, self.dtype), stop_gradient=True
            )
        a, b = (other, self) if reverse else (self, other)
        return _trace1(op_type, {"X": [a], "Y": [b]}, attrs={"axis": -1})

    def __add__(self, o):
        return self._binary(o, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "elementwise_sub")

    def __rsub__(self, o):
        return self._binary(o, "elementwise_sub", reverse=True)

    def __mul__(self, o):
        return self._binary(o, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "elementwise_div")

    def __rtruediv__(self, o):
        return self._binary(o, "elementwise_div", reverse=True)

    def __neg__(self):
        return _trace1("scale", {"X": [self]}, attrs={"scale": -1.0})

    def __matmul__(self, o):
        return _trace1("matmul", {"X": [self], "Y": [o]}, attrs={})


class _TapeEntry:
    __slots__ = ("op_def", "ins", "attrs", "in_vars", "out_vars", "rng")

    def __init__(self, op_def, ins, attrs, in_vars, out_vars, rng):
        self.op_def = op_def      # OpDef of the forward op
        self.ins = ins            # {slot: [jax.Array|None]} forward inputs
        self.attrs = attrs
        self.in_vars = in_vars    # {slot: [VarBase|None]}
        self.out_vars = out_vars  # {slot: [VarBase|None]}
        self.rng = rng            # PRNG key used (or None)


class Tracer:
    """Runs ops eagerly and records the tape
    (reference: imperative/tracer.cc:138 ``Tracer::Trace``)."""

    def __init__(self, seed: int = 0):
        self._tape: List[_TapeEntry] = []
        self._tape_warned = False
        self._grad_enabled = True
        self._key = jax.random.PRNGKey(seed)
        self._op_count = 0
        self.train_mode = True
        self._grad_compute_cache: Dict[str, Any] = {}

    def seed(self, seed: int):
        self._key = jax.random.PRNGKey(seed)
        self._op_count = 0

    def reset(self):
        self._tape.clear()

    @contextlib.contextmanager
    def no_grad(self):
        old = self._grad_enabled
        self._grad_enabled = False
        try:
            yield
        finally:
            self._grad_enabled = old

    # --- forward ---

    def trace_op(
        self,
        op_type: str,
        ins: Dict[str, List[VarBase]],
        attrs: Optional[Dict[str, Any]] = None,
        out_slots: Optional[List[str]] = None,
    ) -> Dict[str, List[VarBase]]:
        """Run ``op_type`` eagerly on VarBase inputs; returns VarBase outputs.

        ``ins`` values may be VarBase, None, or lists thereof.
        """
        op_def: OpDef = get_op_def(op_type)
        attrs = dict(attrs or {})

        norm_ins: Dict[str, List[Optional[VarBase]]] = {}
        for slot, vals in ins.items():
            if vals is None:
                norm_ins[slot] = []
                continue
            if isinstance(vals, VarBase):
                vals = [vals]
            norm_ins[slot] = list(vals)

        arr_ins = {
            slot: [None if v is None else v._value for v in vals]
            for slot, vals in norm_ins.items()
        }

        kwargs = {}
        rng = None
        if op_def.needs_rng:
            self._op_count += 1
            rng = jax.random.fold_in(self._key, self._op_count)
            kwargs["rng"] = rng

        outs = op_def.compute(arr_ins, attrs, **kwargs)

        out_vars: Dict[str, List[Optional[VarBase]]] = {}
        requires_grad = (
            self._grad_enabled
            and not op_def.no_grad
            and any(
                v is not None and not v.stop_gradient
                for vals in norm_ins.values()
                for v in vals
            )
        )
        for slot, vals in outs.items():
            out_vars[slot] = [
                None
                if v is None
                else VarBase(v, stop_gradient=not requires_grad)
                for v in vals
            ]

        if requires_grad:
            self._tape.append(
                _TapeEntry(op_def, arr_ins, attrs, norm_ins, out_vars, rng)
            )
            # Forward-only loops (inference without no_grad) would retain
            # every activation forever; warn once so the leak is visible.
            if len(self._tape) > 100_000 and not self._tape_warned:
                self._tape_warned = True
                import warnings

                warnings.warn(
                    "dygraph tape exceeds 100k entries without backward(); "
                    "wrap inference in dygraph.no_grad() or call "
                    "get_tracer().reset() to release held activations"
                )
        return out_vars

    # --- backward ---

    def _grad_compute(self, op_def: OpDef):
        fn = self._grad_compute_cache.get(op_def.type)
        if fn is None:
            fn = autodiff.make_grad_compute(op_def)
            self._grad_compute_cache[op_def.type] = fn
        return fn

    def run_backward(self, root: VarBase):
        """Reverse-walk the tape accumulating cotangents
        (reference: imperative/layer.cc:426 ``VarBase::RunBackward``)."""
        if not jnp.issubdtype(root.dtype, jnp.floating):
            raise TypeError("backward() root must be floating point")
        cot: Dict[int, Any] = {id(root): jnp.ones_like(root._value)}
        # id -> VarBase, to push final grads back onto vars
        var_of: Dict[int, VarBase] = {id(root): root}

        for entry in reversed(self._tape):
            out_has_grad = any(
                v is not None and id(v) in cot
                for vals in entry.out_vars.values()
                for v in vals
            )
            if not out_has_grad:
                continue

            in_slots = list(entry.in_vars.keys())
            out_slots = list(entry.out_vars.keys())
            gins: Dict[str, List[Any]] = {}
            for s in in_slots:
                gins[s] = list(entry.ins[s])
            for s in out_slots:
                gins[s] = [
                    None if v is None else v._value
                    for v in entry.out_vars[s]
                ]
                gins[GRAD_SLOT_PREFIX + s] = [
                    None if v is None else cot.get(id(v))
                    for v in entry.out_vars[s]
                ]
            gattrs = dict(entry.attrs)
            gattrs["fwd_input_slots"] = in_slots
            gattrs["fwd_output_slots"] = out_slots
            gattrs["forward_op_idx"] = 0

            # custom grad_makers are a static-graph construct; the eager
            # engine always uses the vjp-derived kernel, which is valid for
            # every op whose forward is a pure JAX function.
            kwargs = {"rng": entry.rng} if entry.op_def.needs_rng else {}
            grad_fn = self._grad_compute(entry.op_def)
            gouts = grad_fn(gins, gattrs, **kwargs)

            for s in in_slots:
                gvals = gouts.get(GRAD_SLOT_PREFIX + s)
                if not gvals:
                    continue
                for v, g in zip(entry.in_vars[s], gvals):
                    if v is None or g is None or v.stop_gradient:
                        continue
                    prev = cot.get(id(v))
                    cot[id(v)] = g if prev is None else prev + g
                    var_of[id(v)] = v

        for vid, g in cot.items():
            v = var_of[vid]
            v._grad = g if v._grad is None else v._grad + g
        # Tape consumed (reference releases OpBase traces after RunBackward).
        self._tape.clear()


# ---------------------------------------------------------------------------

_tracer: Optional[Tracer] = None


def get_tracer() -> Tracer:
    global _tracer
    if _tracer is None:
        _tracer = Tracer()
    return _tracer


def _trace1(op_type, ins, attrs=None, out_slot: Optional[str] = None):
    """Trace an op and return its single primary output VarBase."""
    outs = get_tracer().trace_op(op_type, ins, attrs)
    if out_slot is None:
        for slot in ("Out", "Y", "Output"):
            if slot in outs and outs[slot]:
                return outs[slot][0]
        # fall back to the first populated slot
        for slot, vals in outs.items():
            if vals:
                return vals[0]
        raise RuntimeError(f"op '{op_type}' produced no outputs")
    return outs[out_slot][0]
