"""Executor and Scope.

API parity with the reference's ``fluid.Executor`` (reference:
python/paddle/fluid/executor.py:550) but execution is whole-block XLA
compilation (see core/lowering.py) instead of injecting feed/fetch ops and
interpreting. The compiled-function cache keyed on
(program version, feed signature, fetch list) replaces the reference's
prepared-context cache (reference: executor.py:704).
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time

from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import analysis as _analysis
from paddle_tpu import compile_cache as _ccache
from paddle_tpu import faults as _faults
from paddle_tpu import monitor as _monitor
from paddle_tpu import numerics as _numerics
from paddle_tpu import roofline as _roofline
from paddle_tpu.core import lowering
from paddle_tpu.framework import (
    CPUPlace,
    TPUPlace,
    Variable,
    default_main_program,
)

# Telemetry instruments (no-ops while the 'telemetry' flag is off — one
# boolean check per call, zero allocations; see monitor.py).
_M_CACHE_HITS = _monitor.counter(
    "pt_executor_cache_hits_total", "compiled-step cache hits")
_M_CACHE_MISSES = _monitor.counter(
    "pt_executor_cache_misses_total",
    "compiled-step cache misses (fresh compiles)")
_M_CACHE_EVICTIONS = _monitor.counter(
    "pt_executor_cache_evictions_total",
    "compiled-step cache entries evicted at capacity")
_M_DONATED_DROPS = _monitor.counter(
    "pt_executor_donated_drops_total",
    "donated state buffers dropped after a failed step")
_M_STEPS = _monitor.counter(
    "pt_executor_steps_total",
    "executor steps run (run_steps windows count each inner step)")
_M_FEED_BYTES = _monitor.counter(
    "pt_executor_feed_bytes_total",
    "bytes across feed arrays per step (an upper bound on host->device "
    "transfer: device-resident or staging-cached feeds count too)")
_M_FETCH_BYTES = _monitor.counter(
    "pt_executor_fetch_bytes_total", "bytes across fetch arrays per step")
_M_NAN_FAILS = _monitor.counter(
    "pt_executor_nan_check_failures_total",
    "check_nan_inf scans that found non-finite values")

# chaos hook (faults.py): armed plans can delay the step body (the fleet
# straggler drill — the sleep lands in the dispatch phase) or raise a
# synthetic RESOURCE_EXHAUSTED (the OOM-forensics drill)
_F_STEP = _faults.site("executor.step")
# deferred-fetch materialization (LazyFetches.wait): a raised
# RESOURCE_EXHAUSTED here drills the async-dispatch error path — the
# device failure that surfaces only when the fetch lands
_F_FETCH = _faults.site("executor.fetch")


def _stage_feeds(feed_vals):
    """Host->device staging for the sampled phase path: ``device_put``
    every non-resident feed so the feed phase measures the real
    host->device transfer. An all-``jax.Array`` feed dict (a
    DeviceLoader-prefetched batch) returns the SAME dict with zero
    ``device_put`` calls — the staging-skip contract the prefetch
    pipeline relies on (and tests spy on)."""
    for v in feed_vals.values():
        if not isinstance(v, jax.Array):
            break
    else:
        return feed_vals
    return {k: v if isinstance(v, jax.Array) else jax.device_put(v)
            for k, v in feed_vals.items()}


class LazyFetches:
    """Deferred fetch results (``Executor.run``/``run_steps`` with
    ``async_fetch=True``): list-like, one element per ``fetch_list``
    entry, already converted to numpy by the time an element is read.

    Construction issues every device->host copy without blocking
    (``copy_to_host_async`` — the two-pass idiom proven in
    parallel/checkpoint.py's async snapshot); the numpy conversion
    happens on first element access (or an explicit ``wait()``), so
    step N's fetch materializes under step N+1's host dispatch. A
    deferred device error surfacing at materialization runs the same
    donated-buffer hygiene + OOM forensics as the synchronous commit
    sites, exactly once, then re-raises."""

    __slots__ = ("_arrays", "_values", "_on_error", "_t0")

    def __init__(self, arrays, on_error=None):
        self._arrays = list(arrays)
        self._values = None
        self._on_error = on_error
        for a in self._arrays:
            try:
                a.copy_to_host_async()
            except AttributeError:
                pass  # host numpy / older jax: np.asarray below copies
        self._t0 = time.perf_counter() if _monitor.enabled() else 0.0

    @property
    def ready(self) -> bool:
        """Whether the fetches have already materialized to numpy."""
        return self._values is not None

    def wait(self) -> list:
        """Materialize every fetch to numpy (idempotent)."""
        if self._values is None:
            try:
                _F_FETCH.hit()
                self._values = [np.asarray(a) for a in self._arrays]
            except Exception as e:
                cb, self._on_error = self._on_error, None
                if cb is not None:
                    cb(e)
                raise
            self._arrays = None  # release the device buffers
            self._on_error = None
            if self._t0:
                _monitor.fetch_overlap(time.perf_counter() - self._t0)
        return self._values

    def __len__(self):
        vals = self._values
        return len(vals if vals is not None else self._arrays)

    def __getitem__(self, i):
        return self.wait()[i]

    def __iter__(self):
        return iter(self.wait())

    def __repr__(self):
        state = "ready" if self.ready else "pending"
        return f"LazyFetches({len(self)} fetches, {state})"


def _sum_nbytes(vals) -> int:
    total = 0
    for v in vals:
        n = getattr(v, "nbytes", None)
        if n is not None:
            total += int(n)
    return total


def _strategy_id(strategy) -> Optional[str]:
    """Compact SPMD strategy label for step logs: mesh axes x sizes."""
    if strategy is None:
        return None
    mesh = getattr(strategy, "mesh", None)
    if mesh is None:
        return "strategy"
    return ",".join(f"{a}={mesh.shape[a]}" for a in mesh.axis_names)


class Scope:
    """name -> device array container (reference: framework/scope.h:45).

    Values live as committed JAX arrays (device-resident between steps); numpy
    values are accepted and converted lazily.
    """

    _uid_counter = 0

    def __init__(self):
        self._vars: Dict[str, Any] = {}
        Scope._uid_counter += 1
        self._uid = Scope._uid_counter

    def set(self, name: str, value):
        self._vars[name] = value

    def find_var(self, name: str):
        return self._vars.get(name)

    def var_names(self) -> List[str]:
        return list(self._vars)

    def has(self, name: str) -> bool:
        return name in self._vars

    def drop(self, name: str):
        self._vars.pop(name, None)

    def clear(self):
        self._vars.clear()


_global_scope = Scope()


class _ScopeTLS(threading.local):
    def __init__(self):
        self.stack: List[Scope] = []


_scope_tls = _ScopeTLS()


def global_scope() -> Scope:
    stack = _scope_tls.stack
    return stack[-1] if stack else _global_scope


@contextlib.contextmanager
def scope_guard(scope: Scope):
    """Swap the ambient scope (reference: fluid.executor.scope_guard).

    A guard entered on a worker thread is THREAD-LOCAL: concurrent
    engines (serving-fleet replicas each driving their own supervisor
    loop thread) must not resolve each other's scopes through a shared
    global — a torn swap hands one engine another engine's decode
    state, or a stateless scope mid-step. The main thread keeps the
    legacy process-global swap so unguarded worker threads still
    inherit the main thread's guarded scope."""
    if threading.current_thread() is threading.main_thread():
        global _global_scope
        old, _global_scope = _global_scope, scope
        try:
            yield
        finally:
            _global_scope = old
    else:
        _scope_tls.stack.append(scope)
        try:
            yield
        finally:
            _scope_tls.stack.pop()


def _prng_impl():
    """Program-level PRNG implementation. On TPU, threefry random-bit
    generation is slow enough to dominate dropout (ablation: 21.5ms of a
    63ms transformer step, benchmarks/ablate.py), so the hardware 'rbg'
    generator is the default there; CPU keeps threefry so test streams
    stay stable. Override with the 'prng_impl' flag."""
    from paddle_tpu import flags as _flags

    choice = _flags.get_flag("prng_impl")
    if choice != "auto":
        return choice
    return "rbg" if jax.default_backend() == "tpu" else None


class Executor:
    """Runs programs. ``place`` selects the default JAX device kind."""

    # staged run_steps feed windows kept device-resident across calls;
    # small on purpose: each entry pins a whole stacked feed window on
    # device, so the cap is an HBM contract, not a perf knob
    STAGED_WINDOW_CAPACITY = 4

    def __init__(self, place: Optional[Union[CPUPlace, TPUPlace]] = None):
        self.place = place if place is not None else TPUPlace(0)
        self._cache: Dict[tuple, Any] = {}
        self._step = 0
        self._base_keys: Dict[tuple, Any] = {}
        # keyed LRU of run_steps feed stagings: id-tuple of the host
        # arrays -> {"arrs": pinned host refs (id identity stays valid),
        # "stacked": device window, "owner": compiled-cache key}.
        # Replaces the old single-slot cache so alternating feed
        # rotations (stage window B while window A executes) stop
        # thrashing the slot. Evicting a compiled entry drops the staged
        # windows it owns (stale staging would pin device-resident feed
        # windows after the entry is gone).
        self._staged: "collections.OrderedDict[tuple, dict]" = (
            collections.OrderedDict())

    # --- public API ---

    def run(
        self,
        program=None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence[Union[str, Variable]]] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,
        async_fetch: bool = False,
    ):
        from paddle_tpu.compiler import CompiledProgram

        tele = _monitor.enabled()
        # wall_ms covers the WHOLE call, feed conversion/staging included
        t_run0 = time.perf_counter() if tele else 0.0
        compiled = None
        if isinstance(program, CompiledProgram):
            compiled = program
            program = compiled.program
        if program is None:
            program = default_main_program()
        scope = scope or global_scope()
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        fetch_names = [
            f.name if isinstance(f, Variable) else str(f) for f in fetch_list
        ]

        feed_items = sorted(feed.items())
        feed_names = [k for k, _ in feed_items]
        feed_vals = {}
        for k, v in feed_items:
            arr = np.asarray(v) if not isinstance(v, jax.Array) else v
            feed_vals[k] = arr

        # Device-side numerics (numerics.py): an instrumented program's
        # stats bundle rides the SAME compiled step as one extra fetch,
        # decoded after the run on sampled steps. Resolved before the
        # cache key — plan attachment bumps the program version.
        nplan = _numerics.plan_for(program) if _numerics.active() else None
        run_fetch_names = fetch_names if nplan is None else (
            fetch_names + [nplan.bundle_var])

        sig = tuple(
            (k, tuple(np.shape(v)), str(jnp.result_type(v))) for k, v in feed_vals.items()
        )
        # Canonical fingerprint (compile_cache.program_fingerprint):
        # content-keyed, shared with the lint-once cache, the compile
        # report cache_key, and the persistent disk tier. The memo keyed
        # by this cheap identity tuple keeps the hot path at one dict
        # read (program._amp is identity-relevant: flipping it does NOT
        # bump the version).
        ident = (
            program._uid,
            program.version,
            getattr(program, "_amp", False),
            compiled._uid if compiled is not None else 0,
            sig,
            tuple(run_fetch_names),
        )
        fp = _ccache.fingerprint_for(ident, program, compiled=compiled,
                                     feed_sig=sig,
                                     fetch_names=run_fetch_names)
        key = (fp, scope._uid)

        def build():
            return self._compile(
                program, compiled, feed_names, run_fetch_names, scope
            )

        def pure_build(lowered):
            # the donation-free twin the disk tier stores (see
            # _cache_entry / _jit_for)
            return self._jit_for(lowered, compiled, donate_state=False)

        spec_factory = None
        if use_program_cache and _ccache.active():
            # level-2 disk tier: the spec (state avals gathered from the
            # scope, digest, example args) is only built on a level-1
            # miss — see _cache_entry
            def spec_factory():
                return _ccache.executor_spec(
                    program, feed_vals=feed_vals,
                    fetch_names=run_fetch_names, scope=scope,
                    base_key=self._base_key_for(program),
                    fingerprint=fp, compiled=compiled)

        if _analysis.lint_active():
            # static verifier BEFORE the first compile of this signature
            # (static_lint flag: warn logs findings, error raises; the
            # off path is the one boolean check above, zero allocations).
            # Gated on the verifier's OWN fingerprint cache, not this
            # executor's compile cache: a static_lint mode flip must
            # re-lint signatures another gate would consider warm.
            _analysis.lint_before_compile(
                program, feed_names, run_fetch_names,
                strategy=compiled._strategy if compiled is not None
                else None,
                site="executor.run")
        if (tele and _monitor.memory_budget_bytes() > 0
                and (not use_program_cache or key not in self._cache)):
            # pre-flight BEFORE paying for the compile: a program whose
            # static estimate already exceeds the device budget warns now
            _monitor.check_memory_budget(
                program, {k: np.shape(v) for k, v in feed_vals.items()})
        if use_program_cache:
            entry, outcome, evictions, compile_ms = self._cache_entry(
                key, build, spec_factory, program, pure_build=pure_build)
        else:
            entry, compile_ms = self._timed_build(build, program)
            outcome, evictions = "miss", 0
        cache_hit = outcome != "miss"
        fn, lowered = entry

        state = self._gather_state(scope, lowered)
        # typed base key (rbg on TPU), created ONCE per (seed, impl): the
        # per-step fold_in happens INSIDE the compiled step (the step index
        # rides along as a scalar arg), because two extra host-side jit
        # dispatches per step measured ~10 ms/step through the hosted-TPU
        # tunnel — more than 15% of a transformer-base training step.
        base_key = self._base_key_for(program)
        step_idx = self._step
        self._step += 1

        # Phase attribution timestamps (perf_counter; 0.0 = not reached,
        # so a step that failed before commit logs a record without
        # phases — truncated phase durations would skew the verdict
        # window). Phases: feed = host->device staging, dispatch =
        # Python + tracing overhead (both segments around the staged
        # feed), device = delta to block_until_ready, fetch =
        # device->host + decode in _commit. Gated separately from
        # `tele`: the device phase costs a per-step sync, and the
        # step_phases / step_phases_every_n flags let metrics-only (or
        # merely steady-state) telemetry keep async dispatch — only a
        # SAMPLED step pays the honest-device-timing block_until_ready.
        ph = tele and _monitor.phases_active()
        sampled = ph and _monitor.phases_sampled(step_idx)
        t_f0 = t_f1 = t_c1 = t_b1 = t_x0 = t_x1 = 0.0
        if sampled:
            t_f0 = time.perf_counter()
        if compiled is not None:
            state, feed_vals = compiled.shard_inputs(state, feed_vals)
        if sampled:
            if compiled is None:
                # stage feeds explicitly so the feed phase measures the
                # real host->device transfer instead of hiding it inside
                # the jitted call's dispatch (the transfer happens either
                # way; committed default-device arrays are what jit would
                # produce; an already-device-resident feed dict skips
                # staging entirely — see _stage_feeds). The compiled
                # path keeps shard_inputs as its staging step — an extra
                # unsharded device_put would fight the jit's
                # in_shardings.
                feed_vals = _stage_feeds(feed_vals)
            jax.block_until_ready(list(feed_vals.values()))
            t_f1 = time.perf_counter()

        # Ops needing explicit collectives (ring attention, sharded tables)
        # read the SPMD context at trace time, which happens inside the
        # first jitted call.
        from paddle_tpu.core import interp as _interp

        strategy = compiled._strategy if compiled is not None else None
        rec = None
        if tele:
            # plain data parallelism has a mesh but no DistributedStrategy
            # object; the mesh axes are the strategy id either way
            strat_src = strategy
            if (strat_src is None and compiled is not None
                    and compiled.mesh is not None):
                strat_src = compiled
            strat_label = _strategy_id(strat_src)
            _M_STEPS.inc()
            feed_bytes = _sum_nbytes(feed_vals.values())
            _M_FEED_BYTES.inc(feed_bytes)
            if not cache_hit and _monitor.compile_reports_active():
                # fresh compile: produce the cost/memory report BEFORE
                # the step executes (lowering only reads avals; after
                # the call the donated state buffers are deleted). The
                # SPMD context scope matters: collective ops read it at
                # trace time.
                with _interp.spmd_ctx_scope(strategy):
                    _monitor.record_compile_report(
                        lowering.build_compile_report(
                            fn, lowered,
                            (state, feed_vals, base_key,
                             np.uint32(step_idx)),
                            program=program, kind="step",
                            compile_ms=compile_ms,
                            strategy=strat_label,
                            cache_key=fp))
            if _monitor.step_records_active():
                rec = {
                    "kind": "step",
                    "step": step_idx,
                    "compile_ms": compile_ms,
                    "cache": outcome,
                    "evictions": evictions,
                    "feed_bytes": feed_bytes,
                    "fetch_bytes": 0,
                    "nan_check": None,
                    "strategy": strat_label,
                }
                if ph:
                    # phase plane on: mark whether THIS step paid the
                    # honest sync (sampled=False walls are host-only —
                    # /trace and the fleet digest medians filter on it)
                    rec["sampled"] = sampled
        # Roofline plane (roofline.py): profiles ride phase-SAMPLED
        # steps — the honest device phase below supplies device time;
        # take_sample counts them PER PROGRAM so the cadence is every
        # Nth one, whatever else interleaves. Off (the default) this is
        # the short-circuited `sampled` check.
        roof = sampled and _roofline.take_sample(program)
        cap = _roofline.begin_capture() if roof else None
        try:
            with _interp.spmd_ctx_scope(strategy), \
                    _monitor.span("executor.run_step"):
                try:
                    _F_STEP.hit()
                    fetches, new_state = fn(state, feed_vals, base_key,
                                            np.uint32(step_idx))
                except Exception as e:
                    self._drop_donated(scope, lowered)
                    _monitor.maybe_record_oom(e, program=program,
                                              phase="run")
                    raise
            if sampled:
                t_c1 = time.perf_counter()
                # device phase: drain the async dispatch queue. A
                # deferred device error surfaces here instead of inside
                # _commit — same donated-buffer hygiene as a failed call.
                try:
                    jax.block_until_ready((fetches, new_state))
                except Exception as e:
                    self._drop_donated(scope, lowered)
                    _monitor.maybe_record_oom(e, program=program,
                                              phase="run")
                    raise
                t_b1 = time.perf_counter()
            bundle = None
            if nplan is not None:
                bundle, fetches = fetches[-1], fetches[:-1]
            try:
                if sampled:
                    t_x0 = time.perf_counter()
                try:
                    out = self._commit(
                        scope, fetch_names, fetches, new_state,
                        return_numpy, rec, async_fetch=async_fetch,
                        error_cb=self._fetch_error_cb(
                            scope, lowered, program)
                        if async_fetch else None)
                except Exception as e:
                    # with phases off/unsampled there is no pre-commit
                    # block_until_ready: an async-dispatched device
                    # failure surfaces HERE, in the commit transfer —
                    # same donated-buffer hygiene + OOM hook as the
                    # dispatch/device sites above
                    self._drop_donated(scope, lowered)
                    _monitor.maybe_record_oom(e, program=program,
                                              phase="run")
                    raise
                if sampled:  # only a COMMITTED step is phase-attributed
                    t_x1 = time.perf_counter()
                return out
            finally:
                # decoded even when check_nan_inf raises — the provenance
                # record is most valuable exactly then
                if bundle is not None and _numerics.should_sample(step_idx):
                    summary = _numerics.decode(program, nplan, bundle,
                                               step_idx, kind="step")
                    if rec is not None:
                        rec["numerics"] = summary
        finally:
            # logged even when the step raises (NaN scan, device/runtime
            # error): the crashed step's record is the one an operator
            # needs for postmortem, and must be the last line of the log
            if roof:
                if t_b1 > 0.0:  # device drain completed: honest timing
                    _roofline.note_step(
                        program, lowered,
                        device_s=t_b1 - t_c1,
                        wall_s=time.perf_counter() - t_run0,
                        capture=cap)
                elif cap is not None:  # failed step: abandon the capture
                    cap.stop()
                    cap.cleanup()
            if tele:
                # watermarks read AFTER the step (success or failure):
                # the post-step high-water is the number an OOM
                # post-mortem wants; self-gating on the sampling period
                _monitor.sample_device_memory(step_idx)
            if rec is not None:
                rec["wall_ms"] = (time.perf_counter() - t_run0) * 1e3
                if t_x1 > 0.0:  # phases only for steps that completed
                    self._attribute_phases(
                        rec, step_idx, t_run0, t_f0, t_f1, t_c1, t_b1,
                        t_x0, t_x1, scored=(outcome == "hit"))
                elif ph:
                    # unsampled (or failed) step: its input waits must
                    # not pile into the next sampled step's verdict
                    _monitor.discard_input_wait()
                _monitor.log_step(rec)

    def run_steps(
        self,
        program=None,
        feed_list: Optional[Sequence[Dict[str, Any]]] = None,
        steps: int = 1,
        fetch_list: Optional[Sequence[Union[str, Variable]]] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        async_fetch: bool = False,
    ):
        """Run ``steps`` training iterations as ONE compiled XLA program,
        rotating over ``feed_list`` (a list of same-signature feed dicts;
        step i consumes feed ``i % len(feed_list)``).

        The whole-loop analog of the reference's ``RunFromDataset`` hot
        loop (reference: framework/executor.cc:120-147): no per-step
        Python dispatch, PRNG streams bit-identical to ``steps``
        successive ``run`` calls (the per-step fold_in index keeps
        advancing ``self._step``). Returns the LAST step's fetches.
        """
        from paddle_tpu.compiler import CompiledProgram

        if isinstance(program, CompiledProgram):
            raise TypeError(
                "run_steps does not support CompiledProgram (sharded "
                "inputs/SPMD context are per-step concerns); use run()")
        if not feed_list:
            raise ValueError("run_steps needs a non-empty feed_list")
        tele = _monitor.enabled()
        # started before feed stacking: device_put of the whole window is
        # often the dominant host cost, and wall_ms must show it
        t_run0 = time.perf_counter() if tele else 0.0
        if program is None:
            program = default_main_program()
        scope = scope or global_scope()
        fetch_list = list(fetch_list or [])
        fetch_names = [
            f.name if isinstance(f, Variable) else str(f) for f in fetch_list
        ]
        feed_names = sorted(feed_list[0])
        from paddle_tpu import flags as _flags_mod

        # Per-step in-graph finiteness tracking (core/lowering.py): the
        # compiled window carries the index of the first bad step, so a
        # failure names the step, not just the window. Part of the cache
        # key — flipping the flag compiles the other variant.
        nan_track = bool(_flags_mod.get_flag("check_nan_inf"))
        nplan = _numerics.plan_for(program) if _numerics.active() else None
        run_fetch_names = fetch_names if nplan is None else (
            fetch_names + [nplan.bundle_var])
        # Stacking device_puts every feed; cache by array IDENTITY so a
        # repeated feed_list (the bench window pattern) stages once. The
        # cache only engages when every feed is IMMUTABLE — a jax.Array,
        # or an OWNING numpy array (base is None) with writeable=False —
        # because identity of a mutable buffer says nothing about its
        # contents: the standard preallocated-loader pattern refills the
        # same buffer in place, and a stale identity hit would silently
        # reuse old device data. A frozen VIEW does not qualify: its
        # contents still change through a writeable base. Mutable numpy
        # feeds are re-staged every call (same contract as run()); pass
        # jax.Arrays or owning frozen copies to get one-time staging.
        # The cache is a small keyed LRU (STAGED_WINDOW_CAPACITY), so
        # alternating rotations stay staged — the next rotation's
        # device_put overlaps the current window's device work instead
        # of thrashing a single slot.
        # Phase marks (see run()): the stacking below IS the window's
        # feed phase — device_put of the whole window dominates host
        # cost, and the breakdown must show it.
        ph = tele and _monitor.phases_active()
        sampled = ph and _monitor.phases_sampled(self._step, int(steps))
        t_f0 = t_f1 = t_c1 = t_b1 = t_x0 = t_x1 = 0.0
        if sampled:
            t_f0 = time.perf_counter()
        arrs = [fb[k] for fb in feed_list for k in feed_names]
        cacheable = all(
            isinstance(a, jax.Array)
            or (isinstance(a, np.ndarray) and a.base is None
                and not a.flags.writeable)
            for a in arrs
        )
        stacked = None
        staged_key = tuple(map(id, arrs)) if cacheable else None
        if staged_key is not None:
            entry = self._staged.get(staged_key)
            # the pinned refs keep the id()s valid; the `is` sweep makes
            # the hit exact even so
            if entry is not None and len(entry["arrs"]) == len(arrs) \
                    and all(a is b for a, b in zip(entry["arrs"], arrs)):
                stacked = entry["stacked"]
                self._staged.move_to_end(staged_key)
        if stacked is None:
            stacked = {
                k: jnp.stack([jnp.asarray(fb[k]) for fb in feed_list])
                for k in feed_names
            }
            if staged_key is not None:
                # host array refs pinned inside the entry — id() reuse
                # after GC could otherwise alias a fresh array to a
                # stale key. An uncacheable call leaves existing entries
                # alone: each can only hit on its own pinned arrs.
                self._staged[staged_key] = {
                    "arrs": arrs, "stacked": stacked, "owner": None}
                while len(self._staged) > self.STAGED_WINDOW_CAPACITY:
                    self._staged.popitem(last=False)
        if sampled:
            jax.block_until_ready(list(stacked.values()))
            t_f1 = time.perf_counter()
        sig = tuple(
            (k, tuple(v.shape), str(v.dtype)) for k, v in sorted(
                stacked.items())
        )
        # Canonical fingerprint (see run()); the window variant folds in
        # the feed-rotation length and the nan-track flavor. ``steps``
        # rides the L1 KEY, not the fingerprint content hash: the jit
        # treats it as a static argument, but a disk-resolved executable
        # bakes it in, so entries must be steps-distinct end to end.
        ident = (
            "multi", program._uid, program.version,
            getattr(program, "_amp", False), len(feed_list), sig,
            tuple(run_fetch_names), nan_track,
        )
        fp = _ccache.fingerprint_for(
            ident, program, feed_sig=sig, fetch_names=run_fetch_names,
            extra=("multi", len(feed_list), bool(nan_track)))
        key = (fp, scope._uid, int(steps))
        if staged_key is not None and staged_key in self._staged:
            # eviction coupling: remember which compiled entry owns the
            # staged window (see _cache_entry)
            self._staged[staged_key]["owner"] = key

        def build():
            lowered = lowering.lower_block(program, 0, feed_names,
                                           run_fetch_names)
            return (lowering.jit_lowered_multi(lowered, len(feed_list),
                                               track_nonfinite=nan_track),
                    lowered)

        def pure_build(lowered):
            # donation-free twin for the disk tier (see _cache_entry)
            return lowering.jit_lowered_multi(
                lowered, len(feed_list), track_nonfinite=nan_track,
                donate_state=False)

        spec_factory = None
        if _ccache.active():
            # level-2 disk tier (see run()): built only on a level-1 miss
            def spec_factory():
                return _ccache.executor_spec(
                    program, feed_vals=stacked,
                    fetch_names=run_fetch_names, scope=scope,
                    base_key=self._base_key_for(program),
                    fingerprint=fp, window_steps=int(steps),
                    n_feeds=len(feed_list), nan_track=nan_track)

        if _analysis.lint_active():
            # static verifier before the window's first compile (run()
            # twin; the whole-window donation/dataflow semantics are the
            # same single-step block repeated). Gated on the verifier's
            # own fingerprint cache — see run().
            _analysis.lint_before_compile(
                program, feed_names, run_fetch_names,
                site="executor.run_steps")
        if (tele and _monitor.memory_budget_bytes() > 0
                and key not in self._cache):
            # per-step feed shapes: drop the stacked window axis
            _monitor.check_memory_budget(
                program,
                {k: tuple(v.shape[1:]) for k, v in stacked.items()})
        entry, outcome, evictions, compile_ms = self._cache_entry(
            key, build, spec_factory, program, pure_build=pure_build)
        cache_hit = outcome != "miss"
        fn, lowered = entry
        state = self._gather_state(scope, lowered)
        base_key = self._base_key_for(program)
        start = self._step
        self._step += int(steps)
        rec = None
        if tele:
            _M_STEPS.inc(int(steps))
            feed_bytes = _sum_nbytes(stacked.values())
            _M_FEED_BYTES.inc(feed_bytes)
            if not cache_hit and _monitor.compile_reports_active():
                _monitor.record_compile_report(
                    lowering.build_compile_report(
                        fn, lowered,
                        (state, stacked, base_key, np.uint32(start),
                         int(steps)),
                        program=program, kind="window",
                        compile_ms=compile_ms, strategy=None,
                        cache_key=fp, window_steps=int(steps)))
            if _monitor.step_records_active():
                rec = {
                    "kind": "window",
                    "step": start,
                    "steps": int(steps),
                    "compile_ms": compile_ms,
                    "cache": outcome,
                    "evictions": evictions,
                    "feed_bytes": feed_bytes,
                    "fetch_bytes": 0,
                    "nan_check": None,
                    "strategy": None,
                }
                if ph:
                    rec["sampled"] = sampled
        # roofline plane: window samples ride phase-sampled calls (see
        # run(), one take_sample per window); the profile covers the
        # whole window's steps
        roof = sampled and _roofline.take_sample(program)
        cap = _roofline.begin_capture() if roof else None
        # under check_nan_inf the window tracks per-step finiteness
        # IN-GRAPH (track_nonfinite): the compiled loop stays one
        # dispatch, yet a failure names the exact step inside it
        try:
            first_bad = None
            with _monitor.span("executor.run_window"):
                try:
                    _F_STEP.hit()
                    if nan_track:
                        fetches, new_state, first_bad = fn(
                            state, stacked, base_key, np.uint32(start),
                            int(steps))
                    else:
                        fetches, new_state = fn(state, stacked, base_key,
                                                np.uint32(start),
                                                int(steps))
                except Exception as e:
                    self._drop_donated(scope, lowered)
                    _monitor.maybe_record_oom(e, program=program,
                                              phase="run")
                    raise
            if sampled:
                t_c1 = time.perf_counter()
                try:
                    jax.block_until_ready((fetches, new_state, first_bad))
                except Exception as e:
                    self._drop_donated(scope, lowered)
                    _monitor.maybe_record_oom(e, program=program,
                                              phase="run")
                    raise
                t_b1 = time.perf_counter()
            bundle = None
            if nplan is not None:
                bundle, fetches = fetches[-1], fetches[:-1]
            try:
                if sampled:
                    t_x0 = time.perf_counter()
                try:
                    out = self._commit(
                        scope, fetch_names, fetches, new_state,
                        return_numpy, rec, nan_first_bad=first_bad,
                        window=(start, int(steps)),
                        async_fetch=async_fetch,
                        error_cb=self._fetch_error_cb(
                            scope, lowered, program)
                        if async_fetch else None)
                except Exception as e:
                    # with phases off/unsampled there is no pre-commit
                    # block_until_ready: an async-dispatched device
                    # failure surfaces HERE, in the commit transfer —
                    # same donated-buffer hygiene + OOM hook as the
                    # dispatch/device sites above
                    self._drop_donated(scope, lowered)
                    _monitor.maybe_record_oom(e, program=program,
                                              phase="run")
                    raise
                if sampled:  # only a COMMITTED window is attributed
                    t_x1 = time.perf_counter()
                return out
            finally:
                if bundle is not None and _numerics.should_sample_window(
                        start, int(steps)):
                    # the bundle holds the LAST step's stats; nan_step
                    # (when the in-graph tracker fired) names the first
                    # bad step of the window
                    last = start + int(steps) - 1
                    summary = _numerics.decode(
                        program, nplan, bundle, last, kind="window",
                        nan_step=rec.get("nan_step") if rec else None)
                    if rec is not None:
                        rec["numerics"] = summary
        finally:
            # logged even when the window raises (see run())
            if roof:
                if t_b1 > 0.0:
                    _roofline.note_step(
                        program, lowered, steps=int(steps),
                        device_s=t_b1 - t_c1,
                        wall_s=time.perf_counter() - t_run0,
                        capture=cap)
                elif cap is not None:
                    cap.stop()
                    cap.cleanup()
            if tele:
                _monitor.sample_device_memory(start, int(steps))
            if rec is not None:
                rec["wall_ms"] = (time.perf_counter() - t_run0) * 1e3
                if t_x1 > 0.0:  # whole-window totals, one verdict entry
                    self._attribute_phases(
                        rec, start, t_run0, t_f0, t_f1, t_c1, t_b1,
                        t_x0, t_x1, steps=int(steps),
                        scored=(outcome == "hit"))
                elif ph:
                    # unsampled (or failed) window: see run()
                    _monitor.discard_input_wait()
                _monitor.log_step(rec)

    # --- shared plumbing for run()/run_steps() ---

    def _cache_entry(self, key, build, spec_factory=None, program=None,
                     pure_build=None):
        """LRU lookup-or-build with the capacity eviction policy and the
        persistent level-2 tier (compile_cache.py) between them.

        Returns ``(entry, outcome, evictions, compile_ms)`` where
        ``outcome`` is ``"hit"`` (in-memory), ``"disk"`` (executable
        deserialized from the persistent cache — no trace, no XLA
        compile; ``compile_ms`` is then the load time) or ``"miss"``
        (fresh compile). The outcome rides the return value (not
        instance state) so the step-log assembly can never read a stale
        previous call's outcome. ``spec_factory`` — passed only while
        the disk tier is active — builds the disk-resolution spec
        lazily: a level-1 hit never pays for it."""
        entry = self._cache.get(key)
        if entry is not None:
            self._cache.pop(key)
            self._cache[key] = entry  # refresh so eviction drops coldest
            _M_CACHE_HITS.inc()
            return entry, "hit", 0, None
        _M_CACHE_MISSES.inc()
        outcome = "miss"
        entry = compile_ms = None
        spec = spec_factory() if spec_factory is not None else None
        if spec is not None:
            loaded = _ccache.load(spec)
            if loaded is not None:
                fn, compile_ms = loaded
                # block analysis only — a disk hit never traces
                entry = (fn, spec.make_lowered())
                outcome = "disk"
        if entry is None:
            if spec is not None:
                # disk miss with the tier on: AOT-compile through the
                # spec (one trace + one XLA compile — the same cost the
                # eager jit would pay lazily) and persist the executable
                # for the next process; an AOT failure keeps the eager
                # jit and stores nothing. The AOT twin is built WITHOUT
                # input donation (``pure_build``): a deserialized
                # donating executable corrupts buffer ownership from its
                # second call on (jax 0.4.x flaky use-after-free), and a
                # stored entry must execute correctly in every process —
                # the memory win of donation is not worth wrong values.
                def build_aot(_build=build):
                    fn, lowered = _build()
                    target = (pure_build(lowered)
                              if pure_build is not None else fn)
                    aot = _ccache.aot_build(spec, target)
                    return (fn if aot is None else aot), lowered

                entry, compile_ms = self._timed_build(build_aot, program)
            else:
                entry, compile_ms = self._timed_build(build, program)
        self._cache[key] = entry
        from paddle_tpu import flags as _flags_mod

        cap = _flags_mod.get_flag("executor_cache_capacity")
        evicted = 0
        while cap > 0 and len(self._cache) > cap:
            victim = next(iter(self._cache))
            self._cache.pop(victim)
            # staged feed windows must not outlive their owning compiled
            # entry (see _staged)
            for sk in [k for k, e in self._staged.items()
                       if e["owner"] == victim]:
                self._staged.pop(sk)
            evicted += 1
        if evicted:
            _M_CACHE_EVICTIONS.inc(evicted)
        return entry, outcome, evicted, compile_ms

    def _timed_build(self, build, program=None):
        """Compile under the unified span; returns ``(entry,
        compile_ms)`` (perf_counter interval) for the step log."""
        with _monitor.span("executor.compile"):
            t0 = time.perf_counter()
            try:
                entry = build()
            except Exception as e:
                # compile-time RESOURCE_EXHAUSTED: the forensics hook's
                # other half (run-time OOMs are caught at the call sites)
                _monitor.maybe_record_oom(e, program=program,
                                          phase="compile")
                raise
            t1 = time.perf_counter()
            # compiles get their own timeline track: a recompile storm
            # reads as a dense compile row, not as mystery-long steps
            _monitor.trace_event("executor.compile", "compile", t0, t1)
            return entry, (t1 - t0) * 1e3

    def _attribute_phases(self, rec, step_idx, t_run0, t_f0, t_f1, t_c1,
                          t_b1, t_x0, t_x1, steps=1, scored=True):
        """Fold a completed step's perf_counter marks into the phase
        breakdown: ``rec['phases']`` (ms), ``rec['bound']`` (the rolling
        window's boundedness verdict), the ``pt_step_phase_seconds``
        histograms, and — on trace-sampled steps — one timeline event
        per phase segment (dispatch is two segments: host work before
        feed staging and the jitted call itself). ``scored=False``
        (fresh compile / disk load): phases are recorded but the step
        stays out of the verdict window — compile time in the dispatch
        segment would otherwise pollute the boundedness verdict."""
        feed_s = t_f1 - t_f0
        disp_s = (t_f0 - t_run0) + (t_c1 - t_f1)
        dev_s = t_b1 - t_c1
        fetch_s = t_x1 - t_x0
        rec["phases"] = {"feed": feed_s * 1e3, "dispatch": disp_s * 1e3,
                         "device": dev_s * 1e3, "fetch": fetch_s * 1e3}
        verdict = _monitor.record_step_phases(feed_s, disp_s, dev_s,
                                              fetch_s, scored=scored)
        if verdict is not None:
            rec["bound"] = verdict
        if _monitor.trace_step_sampled(step_idx, steps):
            step = {"step": step_idx}
            _monitor.trace_event("dispatch", "phase", t_run0, t_f0,
                                 args=step)
            _monitor.trace_event("feed", "phase", t_f0, t_f1, args=step)
            _monitor.trace_event("dispatch", "phase", t_f1, t_c1,
                                 args=step)
            _monitor.trace_event("device", "phase", t_c1, t_b1, args=step)
            _monitor.trace_event("fetch", "phase", t_x0, t_x1, args=step)

    def _gather_state(self, scope, lowered):
        state = {}
        for n in lowered.state_in_names:
            v = scope.find_var(n)
            if v is None:
                raise RuntimeError(
                    f"variable '{n}' used by the program is not initialized "
                    f"in the scope — run the startup program first"
                )
            state[n] = v
        return state

    def _base_key_for(self, program):
        seed = program.random_seed if program.random_seed is not None else 0
        impl = _prng_impl()
        base_key = self._base_keys.get((seed, impl))
        if base_key is None:
            base_key = jax.random.key(seed, impl=impl)
            self._base_keys[(seed, impl)] = base_key
        return base_key

    def _drop_donated(self, scope, lowered):
        """After a failed jitted call: donated state buffers that were
        consumed are deleted; drop them so later use fails loudly."""
        for n in lowered.state_in_names:
            v = scope.find_var(n)
            if isinstance(v, jax.Array) and v.is_deleted():
                scope.drop(n)
                _M_DONATED_DROPS.inc()

    def _fetch_error_cb(self, scope, lowered, program):
        """Deferred-fetch failure hygiene (LazyFetches): the same
        donated-buffer drop + OOM forensics the synchronous commit
        sites run, delayed to materialization time."""
        def on_error(e):
            self._drop_donated(scope, lowered)
            _monitor.maybe_record_oom(e, program=program, phase="fetch")
        return on_error

    def _commit(self, scope, fetch_names, fetches, new_state,
                return_numpy, rec=None, nan_first_bad=None, window=None,
                async_fetch=False, error_cb=None):
        from paddle_tpu import flags as _flags

        if _flags.get_flag("benchmark"):
            # honest timing: wait for device work (reference:
            # FLAGS_benchmark forced Wait, operator.cc:946)
            jax.block_until_ready((fetches, new_state))
        # Commit new state BEFORE any post-step check can raise: the old
        # buffers were donated and already deleted.
        for n, v in new_state.items():
            scope.set(n, v)
        if rec is not None:
            rec["fetch_bytes"] = _sum_nbytes(fetches)
            _M_FETCH_BYTES.inc(rec["fetch_bytes"])
        elif _monitor.enabled():
            _M_FETCH_BYTES.inc(_sum_nbytes(fetches))
        if _flags.get_flag("check_nan_inf"):
            if nan_first_bad is not None and window is not None:
                # compiled window: the in-graph tracker names the FIRST
                # failing step (jit_lowered_multi track_nonfinite)
                start, steps = window
                idx = int(np.asarray(nan_first_bad))
                if idx < steps:
                    _M_NAN_FAILS.inc()
                    if rec is not None:
                        rec["nan_check"] = "fail"
                        rec["nan_step"] = start + idx
                    raise FloatingPointError(
                        f"check_nan_inf: step {start + idx} (index {idx} "
                        f"of this {steps}-step compiled window) produced "
                        f"non-finite values (set flag 'check_nan_inf' to "
                        f"False to disable)")
                if rec is not None:
                    rec["nan_check"] = "ok"
            else:
                try:
                    self._check_nan_inf(fetch_names, fetches, new_state)
                except FloatingPointError:
                    _M_NAN_FAILS.inc()
                    if rec is not None:
                        rec["nan_check"] = "fail"
                    raise
                if rec is not None:
                    rec["nan_check"] = "ok"
        if return_numpy:
            if async_fetch:
                # overlapped fetch: the device->host copies are issued
                # now (copy_to_host_async) but materialize lazily — the
                # caller reads them after dispatching the next step
                return LazyFetches(fetches, on_error=error_cb)
            fetches = [np.asarray(x) for x in fetches]
        return fetches

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Drive a Dataset (InMemory/Queue, dataset_api.py) through the
        compiled train step (reference: executor.py:846
        ``train_from_dataset``).

        The reference spins thread-per-core device workers consuming a
        C++ data-feed channel; here the host side is a DeviceLoader
        prefetching ``thread``-deep onto the device while the step's XLA
        program runs — the whole-program-compilation analog of the
        Downpour/Hogwild entry point. Returns the number of steps run.
        """
        if dataset is None:
            raise ValueError("train_from_dataset needs a dataset")
        from paddle_tpu.reader.pipeline import DeviceLoader

        fetch_list = list(fetch_list or [])
        names = [f.name if isinstance(f, Variable) else str(f)
                 for f in fetch_list]
        info = list(fetch_info or names)
        # thread=0 means "use the dataset's configured thread num"
        # (reference train_from_dataset convention)
        depth = int(thread or 0) or int(
            getattr(dataset, "_thread_num", 0) or 0)
        loader = DeviceLoader(
            dataset.batch_reader(),
            feed_names=list(getattr(dataset, "_use_var_names", []) or []),
            depth=max(2, depth),
        )
        steps = 0
        for feed in loader:
            fetches = self.run(program, feed=feed, fetch_list=fetch_list,
                               scope=scope)
            steps += 1
            if debug and fetch_list and steps % print_period == 0:
                msg = ", ".join(
                    f"{k}={np.asarray(v).ravel()[:4]}"
                    for k, v in zip(info, fetches))
                print(f"[train_from_dataset] step {steps}: {msg}")
        return steps

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Inference twin of ``train_from_dataset`` (reference:
        executor.py ``infer_from_dataset``): identical drive loop — the
        program simply contains no optimizer ops."""
        return self.train_from_dataset(program, dataset, scope, thread,
                                       debug, fetch_list, fetch_info,
                                       print_period)

    def close(self):
        self._cache.clear()
        # staging follows its owning entries out (see _cache_entry)
        self._staged.clear()

    def release_scope(self, scope) -> int:
        """Drop every compiled entry (and the staged feed windows it
        owns) keyed to ``scope`` — the per-tenant half of close() for
        executors shared by several predictors/serving engines: one
        replica's retirement must not cold-start its neighbors. Returns
        the number of entries released."""
        uid = scope._uid
        victims = [k for k in self._cache if len(k) > 1 and k[1] == uid]
        for k in victims:
            self._cache.pop(k, None)
            for sk in [s for s, e in self._staged.items()
                       if e["owner"] == k]:
                self._staged.pop(sk, None)
        return len(victims)

    @staticmethod
    def _check_nan_inf(fetch_names, fetches, new_state):
        """Per-step NaN/Inf scan of fetches + updated state
        (reference: FLAGS_check_nan_inf scan, operator.cc:950)."""
        bad = []
        for name, v in list(zip(fetch_names, fetches)) + list(
            new_state.items()
        ):
            try:
                if jnp.issubdtype(jnp.result_type(v), jnp.floating) and not bool(
                    jnp.isfinite(v).all()
                ):
                    bad.append(name)
            except TypeError:
                continue
        if bad:
            raise FloatingPointError(
                f"check_nan_inf: non-finite values in {bad} after this "
                f"step (set flag 'check_nan_inf' to False to disable)"
            )

    # --- internals ---

    def _compile(self, program, compiled, feed_names, fetch_names, scope):
        lowered = lowering.lower_block(program, 0, feed_names, fetch_names)
        return self._jit_for(lowered, compiled), lowered

    @staticmethod
    def _jit_for(lowered, compiled, donate_state=True):
        """jax.jit wrapper in the executor call convention.
        ``donate_state=False`` builds the serialization-safe twin the
        persistent compile cache stores (see compile_cache.aot_build):
        deserialized DONATING executables corrupt buffer ownership from
        their second call on (jax 0.4.x use-after-free), so disk-tier
        executables run without input donation."""
        in_shardings = out_shardings = None
        if compiled is not None:
            in_shardings, out_shardings = compiled.shardings(lowered)
            if in_shardings is not None:
                # align with fn(state, feeds, key, step)
                repl = in_shardings[2]
                in_shardings = (*in_shardings, repl)
        return lowering.jit_lowered(
            lowered, in_shardings=in_shardings, out_shardings=out_shardings,
            fold_step=True, donate_state=donate_state,
        )
