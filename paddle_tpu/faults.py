"""Deterministic fault injection: named sites armed by a seeded plan.

Chaos engineering for the fault-tolerance plane (SURVEY.md section 5):
production code declares *sites* — module-level
``_F_X = faults.site("ckpt.write_shards")`` objects whose ``hit()`` sits
at the failure-prone point — and a *plan* arms specific sites to fail in
a specific way at a specific hit. Because triggering is a pure function
of (plan, seed, per-site hit count), a chaos run reproduces its fault
sequence exactly: the same plan string replays the same crash.

Plan syntax (the ``fault_plan`` flag / ``PT_FLAGS_fault_plan`` env)::

    plan    := entry (';' entry)*
    entry   := site ':' action '@' trigger (',' trigger)*
    action  := 'raise' | 'raise(message)'
             | 'delay(seconds)'        -- sleep, simulating a slow dep
             | 'truncate(bytes)'       -- torn write: truncate the file
                                          the site passed via hit(path=)
    trigger := N        -- fire at the Nth hit of the site (1-based)
             | 'p' F    -- fire each hit with probability F, drawn from
                           a per-site stream seeded by the fault_seed
                           flag (deterministic given seed + hit order)

Disabled path contract (same as monitor.py): while no plan is armed,
``Site.hit()`` is one module-boolean check and allocates nothing —
sites are safe to leave in hot code.

Every injected fault counts into ``pt_fault_injected_total{site=}`` and
appends a record (site, hit number, action) readable via ``records()``.
"""

from __future__ import annotations

import random
import re
import threading
import time
import warnings
from typing import Dict, List, Optional

from paddle_tpu import flags as _flags
from paddle_tpu import monitor as _monitor

_M_INJECTED = _monitor.counter(
    "pt_fault_injected_total",
    "faults injected by the chaos plan, by site")

# THE fast-path flag: Site.hit reads this one module boolean and returns
# before touching any other state while no plan is armed.
_armed = False
# whether the live plan came from the fault_plan flag (the flag watcher
# may only disarm plans it armed itself)
_armed_from_flag = False

_LOCK = threading.Lock()
_sites: Dict[str, "Site"] = {}
_records: List[dict] = []
_MAX_RECORDS = 256


class InjectedFault(RuntimeError):
    """Raised by a site whose plan says ``raise``. Distinct from organic
    failures so chaos tests can assert the *injected* fault (and only
    it) propagated."""

    def __init__(self, site: str, hit: int, message: str = ""):
        self.site = site
        self.hit = hit
        super().__init__(
            message or f"injected fault at site {site!r} (hit {hit})")


class _Rule:
    """One parsed plan entry bound to a site: when + what."""

    __slots__ = ("action", "arg", "at", "prob")

    def __init__(self, action: str, arg, at: frozenset, prob: Optional[float]):
        self.action = action  # 'raise' | 'delay' | 'truncate'
        self.arg = arg        # message | seconds | bytes
        self.at = at          # hit numbers (1-based), possibly empty
        self.prob = prob      # per-hit probability, or None

    def fires(self, hit: int, rng: Optional[random.Random]) -> bool:
        if hit in self.at:
            return True
        if self.prob is not None and rng is not None:
            # one draw per hit per probabilistic rule — the stream is
            # positional, so determinism needs the same hit sequence
            return rng.random() < self.prob
        return False


class Site:
    """A named fault-injection point. Create once at module level;
    call ``hit()`` (optionally with the path of the file just written,
    enabling ``truncate``) where the failure would bite."""

    __slots__ = ("name", "hits", "_rules", "_rng")

    def __init__(self, name: str):
        self.name = name
        self.hits = 0
        self._rules: List[_Rule] = []
        self._rng: Optional[random.Random] = None

    def hit(self, path: Optional[str] = None):
        if not _armed:
            return
        self._hit_slow(path)

    def _hit_slow(self, path: Optional[str]):
        with _LOCK:
            self.hits += 1
            hit = self.hits
            fired = [r for r in self._rules if r.fires(hit, self._rng)]
        for r in fired:
            _M_INJECTED.inc(labels={"site": self.name})
            with _LOCK:
                if len(_records) >= _MAX_RECORDS:
                    del _records[0]
                _records.append(
                    {"site": self.name, "hit": hit, "action": r.action})
            if r.action == "delay":
                time.sleep(float(r.arg))
            elif r.action == "truncate":
                if path is not None:
                    with open(path, "r+b") as f:
                        f.truncate(int(r.arg))
                else:
                    # still counted as injected above — but a chaos run
                    # must not believe it tore a file it never touched
                    warnings.warn(
                        f"truncate fault fired at site {self.name!r} "
                        f"(hit {hit}) but the site passed no file path; "
                        f"nothing was truncated", RuntimeWarning)
            else:  # raise
                raise InjectedFault(self.name, hit, str(r.arg or ""))


# The production sites, for plan authors (each is created by its
# declaring module's import; `import paddle_tpu` pulls in all of them).
# Keep in sync with the declarations — tests/test_faults.py proves every
# name here resolves to a registered site.
BUILTIN_SITES = {
    "ckpt.write_shards": "checkpoint shard .npz written, pre-commit "
                         "(parallel/checkpoint.py; truncate = torn shard)",
    "ckpt.commit": "checkpoint COMMIT-marker write on process 0 "
                   "(parallel/checkpoint.py; delay = slow commit, "
                   "proving async-save overlap)",
    "ckpt.read": "restore path: each manifest parse AND each shard-file "
                 "read (parallel/checkpoint.py _read_raw; raise/truncate "
                 "= torn restore, validation treats the serial invalid)",
    "fleet.connect": "coord-server connect attempt (fleet_base)",
    "fleet.kv_get": "coord KV get attempt (fleet_base; also the "
                    "commit-barrier ack/publish waits)",
    "fleet.kv_put": "coord KV put attempt (fleet_base; also the "
                    "commit-barrier acks)",
    "fleet.heartbeat": "worker heartbeat RPC (fleet_base)",
    "fleet.resize": "elastic-resize planning after dead-worker "
                    "detection (fleet_base.plan_resize)",
    "fleet.join": "scale-out admission on the JOINER (fleet_base."
                  "join_world): hit 1 = the announce, hit 2 = plan "
                  "adoption — chaos plans can tear an admission at "
                  "either seam",
    "executor.step": "executor step/window body, pre-dispatch "
                     "(executor.py; delay = a slowed rank for the fleet "
                     "straggler drill — the sleep lands in the dispatch "
                     "phase; raise(RESOURCE_EXHAUSTED ...) = synthetic "
                     "device OOM for forensics drills)",
    "reader.next": "trainer batch fetch (contrib/trainer.py)",
    "pipeline.prefetch": "device-feed prefetch worker, per batch before "
                         "its device_put (reader/pipeline.py; "
                         "raise(RESOURCE_EXHAUSTED ...) = infeed OOM "
                         "drill — surfaces in the consumer with OOM "
                         "forensics; delay = slow host pipeline driving "
                         "the input_bound verdict)",
    "executor.fetch": "deferred-fetch materialization (executor.py "
                      "LazyFetches.wait; raise(RESOURCE_EXHAUSTED ...) "
                      "= a device failure surfacing only at the async "
                      "fetch boundary — must still run donated-buffer "
                      "hygiene + OOM forensics)",
    "io.export": "inference-model export publish (io.py)",
    "ccache.load": "persistent compile-cache entry read, pre-deserialize "
                   "(compile_cache.load; truncate = corrupt published "
                   "entry, which must degrade to a metered miss)",
    "ccache.store": "persistent compile-cache staged write, pre-rename "
                    "(compile_cache.store; raise/truncate = torn store — "
                    "the atomic publish must leave no torn entry)",
    "serve.enqueue": "serving request intake, pre-queue (serving.py "
                     "ServingEngine.submit; raise = failed admission "
                     "path — the request must surface the error, not "
                     "hang)",
    "serve.prefill": "serving admission, pre-prefill of the popped "
                     "request (serving.py _admit; raise = torn "
                     "admission — the handle finishes 'error' before "
                     "the exception propagates, the engine keeps "
                     "serving)",
    "serve.decode": "serving decode loop, pre-dispatch of each "
                    "single-token step (serving.py; delay = a "
                    "stalled/wedged decode loop for SLO + supervisor "
                    "drills; raise(slot=N[,M]) = a CONTAINED poisoned-"
                    "slot fault — only the named slots are evicted "
                    "(outcome 'evicted', partial output kept) and the "
                    "engine keeps decoding; a raise WITHOUT a slot hint "
                    "drills an unattributable device error: the engine "
                    "fails and an EngineSupervisor warm-restarts it)",
    "serve.fetch": "serving token materialization, pre-wait of the "
                   "double-buffered decode step's LazyFetches "
                   "(serving.py _process_ready; raise(slot=N) = "
                   "contained eviction with the step's remaining "
                   "fetches retried once; unhinted raise = engine-"
                   "fatal, the supervisor-restart seam)",
    "router.route": "fleet router replica selection, per submit() "
                    "(fleet_serving.py ServingFleet.submit; raise = a "
                    "routing-plane failure the caller must see — no "
                    "replica is charged; delay = slow routing under "
                    "the deadline budget)",
    "router.replica_crash": "fleet pump tick, once per tick "
                            "(fleet_serving.py; raise(replica=N) = "
                            "hard-kill the N-th live replica (id "
                            "order, default 0) mid-flight — the kill-"
                            "one-replica drill: its supervisor is "
                            "harvested and every in-flight request "
                            "replays on survivors byte-identically)",
    "router.handoff": "rolling-rollout drain of one replica, pre-"
                      "handoff (fleet_serving.py _retire_replica; "
                      "raise = the drain tears mid-rollout — the "
                      "replica is hard-harvested instead and its "
                      "requests still re-home on survivors; delay = "
                      "slow handoff under the rollout timeout)",
}


def site(name: str) -> Site:
    """Get-or-create the named site (module-level singleton)."""
    with _LOCK:
        s = _sites.get(name)
        if s is None:
            s = _sites[name] = Site(name)
            s._rules = _plan_rules.get(name, [])
            if s._rules and _seed is not None:
                s._rng = random.Random(f"{_seed}:{name}")
        return s


# parsed plan: site name -> rules (kept so sites created AFTER arm()
# still bind their rules)
_plan_rules: Dict[str, List[_Rule]] = {}
_seed: Optional[int] = None

_ACTION_RE = re.compile(r"^(raise|delay|truncate)(?:\((.*)\))?$")


def _parse_entry(entry: str):
    entry = entry.strip()
    if not entry:
        return None
    site_name, sep, rest = entry.partition(":")
    if not sep or "@" not in rest:
        raise ValueError(
            f"bad fault-plan entry {entry!r}: want 'site:action@trigger'")
    action_s, _, trig_s = rest.partition("@")
    m = _ACTION_RE.match(action_s.strip())
    if not m:
        raise ValueError(
            f"bad fault-plan action {action_s!r} in {entry!r} "
            f"(want raise[(msg)] / delay(seconds) / truncate(bytes))")
    action, arg = m.group(1), m.group(2)
    if action == "delay":
        arg = float(arg if arg is not None else 0.0)
    elif action == "truncate":
        arg = int(arg if arg is not None else 0)
    at, prob = set(), None
    for t in trig_s.split(","):
        t = t.strip()
        if not t:
            continue
        if t[0] in "pP":
            prob = float(t[1:])
        else:
            at.add(int(t))
    if not at and prob is None:
        raise ValueError(f"fault-plan entry {entry!r} has no trigger")
    return site_name.strip(), _Rule(action, arg, frozenset(at), prob)


def arm(plan: str, seed: Optional[int] = None, _from_flag: bool = False):
    """Parse ``plan`` and arm its sites. Hit counters reset so the plan's
    Nth-hit triggers count from here; ``seed`` (default: the
    ``fault_seed`` flag) fixes the probabilistic streams."""
    global _armed, _seed, _armed_from_flag
    rules: Dict[str, List[_Rule]] = {}
    for entry in plan.split(";"):
        parsed = _parse_entry(entry)
        if parsed is None:
            continue
        name, rule = parsed
        rules.setdefault(name, []).append(rule)
    if not rules:
        disarm()
        return
    with _LOCK:
        _seed = int(_flags.get_flag("fault_seed")) if seed is None else seed
        _armed_from_flag = _from_flag
        _plan_rules.clear()
        _plan_rules.update(rules)
        _records.clear()  # fresh log per plan; survives disarm()
        for s in _sites.values():
            s.hits = 0
            s._rules = _plan_rules.get(s.name, [])
            s._rng = (random.Random(f"{_seed}:{s.name}")
                      if s._rules else None)
        _armed = True


def disarm():
    """Drop the plan: every site back to the one-boolean disabled path.
    The injected-fault log survives (post-mortems read ``records()``
    AFTER disarming); the next ``arm()`` starts a fresh log."""
    global _armed, _armed_from_flag
    with _LOCK:
        _armed = False
        _armed_from_flag = False
        _plan_rules.clear()
        for s in _sites.values():
            s.hits = 0
            s._rules = []
            s._rng = None


def active() -> bool:
    return _armed


def records() -> List[dict]:
    """Injected-fault log (site, hit, action), oldest first, bounded."""
    with _LOCK:
        return list(_records)


def sites() -> List[str]:
    with _LOCK:
        return sorted(_sites)


def _sync_plan(_value=None):
    plan = _flags.get_flag("fault_plan")
    if plan:
        arm(plan, _from_flag=True)
    elif _armed and _armed_from_flag:
        # only un-arm what the flag armed: a watcher firing on an
        # unrelated flag write (e.g. set_flags({'fault_seed': 7}) with
        # fault_plan still "") must not drop a faults.arm()'d plan
        disarm()


# env-set plans (PT_FLAGS_fault_plan) arm at import; later set_flags
# calls re-arm / disarm live
_flags.watch_flag("fault_plan", _sync_plan)
_flags.watch_flag("fault_seed", _sync_plan)
