"""Process-wide flag plane (reference: gflags — 41 ``DEFINE_*`` sites in
core C++ — bootstrapped from whitelisted env vars in
fluid/__init__.py:106-164 via ``init_gflags``/pybind.cc:954).

Flags are typed, defaulted, and settable three ways: env vars
``PT_FLAGS_<name>`` at import, ``set_flags({...})`` at runtime, or the
reference-style ``FLAGS_<name>`` env spelling. Unknown names raise —
a typo'd flag silently doing nothing is the failure mode gflags avoids.
"""

from __future__ import annotations

import os
from typing import Any, Dict

# name -> (type, default, doc)
_DEFS: Dict[str, tuple] = {
    # per-step NaN/Inf scan of updated state + fetches
    # (reference: FLAGS_check_nan_inf, operator.cc:950)
    "check_nan_inf": (bool, False, "scan step outputs for NaN/Inf"),
    # block until device work finishes each step, for honest timing
    # (reference: FLAGS_benchmark forced dev_ctx->Wait, operator.cc:946)
    "benchmark": (bool, False, "synchronize after every step"),
    # executor compile-cache capacity (entries); 0 = unbounded
    "executor_cache_capacity": (int, 0, "compiled-step cache entries"),
    # program-level PRNG: auto = rbg on TPU (fast hardware generator),
    # threefry elsewhere; or force 'threefry2x32' / 'rbg' / 'unsafe_rbg'
    "prng_impl": (str, "auto", "PRNG implementation for program RNG"),
    # coordination-service RPC deadline (reference: FLAGS_rpc_deadline,
    # default 180s). Generous default: rendezvous keys are often published
    # only after a peer's multi-minute first compile. Pass timeout_ms=-1
    # to a specific call for block-forever.
    "rpc_deadline_ms": (int, 600_000, "coord/KV operation deadline"),
    # runtime telemetry plane (monitor.py): metrics registry + structured
    # step logs + span histograms. Off by default — with it off every
    # instrument call is one boolean check.
    "telemetry": (bool, False, "enable the monitor.py telemetry plane"),
    # one JSONL record per Executor.run / run_steps call (monitor.py
    # STEP_LOG_FIELDS schema); empty = no step log even with telemetry on
    "step_log_path": (str, "", "JSONL step-log file path"),
    # monitor.dump_metrics() target; also dumped at process exit while
    # telemetry is on
    "metrics_dump_path": (str, "", "metrics export file path"),
    # per-program compile reports (monitor.COMPILE_REPORT_FIELDS schema):
    # one JSON file per fresh executor compile, written here. Costs one
    # extra AOT lower+compile per cache miss (jax shares no compile cache
    # between the analysis path and the eager jit); empty = off
    "compile_report_dir": (str, "", "per-compile JSON report directory"),
    # live observability endpoint (monitor.serve): /metrics /healthz
    # /steps /compile on this port; 0 = no server. Needs `telemetry`.
    "metrics_port": (int, 0, "HTTP port for the live /metrics endpoint"),
    # pre-flight memory budget: before a fresh compile the executor runs
    # monitor.estimate_memory and warns when the static estimate exceeds
    # this many bytes; 0 = no pre-flight
    "device_memory_budget_bytes": (int, 0,
                                   "warn threshold for pre-compile "
                                   "memory estimates"),
    # collective stall watchdog: guarded blocking sections (fleet
    # barriers/rendezvous, ring-attention / pipeline dispatch) that
    # exceed this deadline increment pt_stall_total and log a structured
    # stall record; 0 = watchdog disarmed
    "stall_timeout_ms": (int, 0, "watchdog deadline for collectives"),
    # on a stall, also dump the flight recorder (step ring buffer +
    # metrics snapshot + stall record) as JSON into this directory
    "stall_dump_dir": (str, "", "flight-recorder dump dir on stall"),
    # per-step phase attribution (feed/dispatch/device/fetch): on by
    # default with telemetry, but separately disablable because honest
    # device timing costs a jax.block_until_ready per step — a user who
    # wants only cheap counters/step-logs can keep async dispatch
    "step_phases": (bool, True,
                    "measure per-step phases (adds a device sync)"),
    # sample the phase marks every N executor steps: only sampled steps
    # pay the honest-device-timing block_until_ready; unsampled steps
    # dispatch fully async (their records carry sampled=False and no
    # phases). 1 = every step (the pre-sampling behavior)
    "step_phases_every_n": (int, 16, "step-phase sampling period"),
    # device-feed prefetch depth for Trainer.train/test's DeviceLoader:
    # batch N+1's host->device transfer overlaps batch N's device phase;
    # 0 = stage feeds synchronously through DataFeeder (the old path)
    "prefetch_depth": (int, 2, "trainer device-feed prefetch depth"),
    # trace-event timeline (monitor.py): host spans, executor step
    # phases, compiles and stall records buffered as Chrome-trace events
    # and written as trace-<host>-<pid>.json into this directory at
    # process exit (or monitor.export_trace()); empty = no file, but the
    # /trace route still serves the ring while the live endpoint is up
    "trace_dir": (str, "", "Chrome-trace timeline output directory"),
    # sample step-phase trace events every N executor steps (spans,
    # compiles and stalls are always traced while tracing is active —
    # phase events are the per-step volume this bounds); 1 = every step
    "trace_every_n_steps": (int, 1, "step-phase trace sampling period"),
    # device-side numerics plane (numerics.py): executors fetch + decode
    # the in-graph tensor-stats bundle of instrumented programs into
    # pt_tensor_* / pt_nonfinite_* instruments and NaN-provenance
    # records. Needs `telemetry`; off = the one-boolean-check hot path.
    "numerics": (bool, False, "decode in-graph tensor-stats bundles"),
    # sample the numerics bundle every N executor steps (the stats are
    # computed on device every step either way — sampling bounds the
    # device->host transfer + decode cost); 1 = every step
    "numerics_every_n_steps": (int, 1, "numerics decode sampling period"),
    # comma-separated fnmatch patterns selecting which vars the
    # instrument_numerics pass instruments (e.g. '*@GRAD,fc_*'); empty =
    # every float activation/gradient/parameter
    "numerics_vars": (str, "", "var-name filter for instrument_numerics"),
    # deterministic fault-injection plan (faults.py):
    # 'site:action@trigger[,trigger];site2:...' — e.g.
    # 'ckpt.write_shards:raise@2;fleet.kv_get:delay(0.05)@1,3'. Actions:
    # raise[(msg)] / delay(seconds) / truncate(bytes); triggers: Nth hit
    # (1-based) or pFLOAT (per-hit probability from the fault_seed
    # stream). Empty = injection disarmed (the one-boolean hot path).
    "fault_plan": (str, "", "deterministic fault-injection plan"),
    # seed for pFLOAT plan triggers: the per-site random stream is
    # derived from (seed, site name), so a seeded chaos run reproduces
    # its fault sequence exactly
    "fault_seed": (int, 0, "seed for probabilistic fault-plan triggers"),
    # fleet observability plane (fleet_monitor.py): minimum gap between
    # registry-digest publishes into fleet KV, piggybacked on heartbeat
    # calls (needs `telemetry` and a multi-worker fleet); 0 = publish on
    # every heartbeat
    "fleet_metrics_interval_ms": (int, 1_000,
                                  "min gap between fleet metric-digest "
                                  "publishes"),
    # cross-rank straggler detector (fleet_monitor.py): a rank is named
    # a straggler when its rolling step time exceeds BOTH the alive-rank
    # median times this factor AND the median plus the _min_ms floor
    # (the floor keeps sub-millisecond jitter from naming stragglers on
    # fast steps)
    "fleet_straggler_factor": (float, 2.0,
                               "straggler threshold vs median step time"),
    "fleet_straggler_min_ms": (int, 20,
                               "absolute step-time skew floor for the "
                               "straggler detector"),
    # device-memory watermarks (monitor.py): sample guarded
    # Device.memory_stats() into pt_device_bytes_in_use/peak every N
    # executor steps (CPU/backends without the API degrade silently);
    # 0 = off. Needs `telemetry`.
    "device_memory_every_n_steps": (int, 16,
                                    "device-memory watermark sampling "
                                    "period"),
    # device-time roofline attribution (roofline.py): build a per-program
    # device profile (top-K ops by device seconds, roofline verdict,
    # measured MFU) every N phase-sampled executor steps; 0 = off (the
    # executor hot path is one boolean check). Needs `telemetry` and
    # `step_phases` (the device phase supplies the honest device time).
    "device_profile_every_n_steps": (int, 0,
                                     "device-profile sampling period"),
    # how many ops the profile's top-ops list (and the
    # pt_device_op_seconds{op=} gauge) keeps, by device seconds
    "device_profile_top_k": (int, 10, "device-profile top-ops list size"),
    # capture a jax.profiler xplane trace around each sampled step and
    # parse per-op device timings from it (source: "xplane"); off = the
    # profile is compile-report-derived (source: "estimate"). Parse
    # failures / backends without a device plane (e.g. CPU) degrade to
    # the estimate path with one warning.
    "device_profile_xplane": (bool, False,
                              "capture + parse xplane around sampled "
                              "steps"),
    # roofline peaks: override the backend table (roofline.BACKEND_PEAKS)
    # when the attached device differs from the defaults; 0 = auto
    "device_peak_flops": (float, 0.0,
                          "peak device FLOP/s for roofline verdicts "
                          "(0 = backend default)"),
    "device_peak_bytes_per_sec": (float, 0.0,
                                  "peak device memory bandwidth for "
                                  "roofline verdicts (0 = backend "
                                  "default)"),
    # persistent level-2 compile cache (compile_cache.py): serialized
    # AOT executables resolved from this directory BEFORE tracing, so a
    # fresh process warm-starts a known program in seconds instead of
    # minutes; entries are keyed by a canonical content fingerprint +
    # environment token and written atomically. Also points jax's own
    # persistent compilation cache at <dir>/xla as a fallback tier.
    # Empty = disabled (the executor hot path is one boolean check).
    "compile_cache_dir": (str, "", "persistent compile-cache directory"),
    # disk budget for compile_cache_dir: after each store the cache runs
    # a size-capped LRU-by-mtime sweep (loads refresh mtime, so the
    # least-recently-USED entries go first; evictions metered by
    # pt_compile_cache_evictions_total); 0 = unbounded
    "compile_cache_max_bytes": (int, 0,
                                "disk size cap for the persistent "
                                "compile cache (LRU-by-mtime sweep)"),
    # pre-compile static program verifier (analysis.py): 'warn' lints
    # every program before its first compile and logs warning/error
    # findings; 'error' additionally raises LintError on error-severity
    # findings; 'off' disables the verifier entirely (the executor hot
    # path is then one boolean check, zero allocations)
    "static_lint": (str, "warn",
                    "pre-compile static verifier: off|warn|error"),
    # serving plane (serving.py): request-queue backpressure — submit()
    # raises QueueFull (and counts the request rejected) once this many
    # requests are waiting for a batch slot
    "serve_queue_depth": (int, 64, "serving request-queue capacity"),
    # default per-request deadline for serving engines: a request still
    # decoding past its deadline is evicted at the next token boundary
    # (outcome 'expired', partial output kept); 0 = no deadline. A
    # submit(deadline_ms=) overrides per request.
    "serve_deadline_ms": (int, 0, "default serving request deadline"),
    # deadline-aware admission control (serving.py): when a request
    # carries a deadline and the engine's measured per-token latency x
    # its estimated queue position says even the FIRST token cannot land
    # before it, submit() refuses the request up front (outcome
    # 'rejected_early', DeadlineUnmeetable raised) instead of queueing
    # doomed work
    "serve_admission_control": (bool, True,
                                "refuse unmeetable-deadline requests at "
                                "submit time"),
    # EngineSupervisor wedge detection: a busy engine whose decode-loop
    # heartbeat is older than this is declared wedged, torn down and
    # warm-restarted through the persistent compile cache; declaring a
    # wedge also emits a monitor stall record for site "serve.decode"
    # (per-dispatch stall_guard deadlines stay on the global
    # stall_timeout_ms flag)
    "serve_wedge_timeout_ms": (int, 30_000,
                               "supervised-engine wedge-detection "
                               "deadline"),
    # lifetime restart budget for one EngineSupervisor: past it the
    # supervisor gives up, finishes every pending handle with outcome
    # 'error' and closes (a permanently failing engine must not restart
    # forever)
    "serve_max_restarts": (int, 3, "EngineSupervisor restart budget"),
    # serving brownout: once the request queue has held at least
    # queue_factor x serve_queue_depth entries for window consecutive
    # scheduler ticks, new admissions have max_new_tokens capped at
    # brownout_max_new_tokens — the engine sheds tokens per request
    # instead of letting queue latency collapse; 0 factor = brownout off
    "serve_brownout_queue_factor": (float, 0.0,
                                    "queue-saturation fraction that "
                                    "engages brownout (0 = off)"),
    "serve_brownout_window": (int, 16,
                              "consecutive saturated ticks before "
                              "brownout engages"),
    "serve_brownout_max_new_tokens": (int, 16,
                                      "max_new_tokens cap applied to "
                                      "admissions during brownout"),
    # request-scoped SLO plane (serving_trace.py): terminal requests are
    # measured against these targets and the pt_slo_* counters burn on
    # every miss — a censored request (terminal before its first token)
    # counts AGAINST the TTFT target, so overload cannot improve the
    # apparent SLO. 0 = no target (the status counters stay empty; the
    # deadline burn rows tick regardless — a request's own deadline IS
    # its SLO).
    "serve_slo_ttft_ms": (float, 0.0,
                          "time-to-first-token SLO target (0 = none)"),
    "serve_slo_token_ms": (float, 0.0,
                           "per-token decode-latency SLO target "
                           "(0 = none)"),
    # bounded recently-terminated request ring served on the /requests
    # monitor route (per-phase latency breakdowns + deadline attribution
    # per terminal request)
    "serve_recent_requests": (int, 256,
                              "recently-terminated request ring "
                              "capacity on /requests"),
    # serving fleet (fleet_serving.py): the router's autoscaler. Off by
    # default — a ServingFleet holds the replica count it was built
    # with; on, the pump scales up when the aggregate queue occupancy
    # across serving replicas has been >= scale_up_queue_factor of
    # aggregate queue capacity for autoscale_window consecutive pump
    # ticks (up to max_replicas), and drains-then-retires one replica
    # after scale_down_idle_ticks consecutive fully-idle ticks (down to
    # min_replicas). Spin-up goes through the persistent compile cache:
    # a warm replica joins with zero fresh XLA compiles.
    "serve_fleet_autoscale": (bool, False,
                              "ServingFleet queue-pressure autoscaling"),
    "serve_fleet_min_replicas": (int, 1,
                                 "autoscale floor on fleet replicas"),
    "serve_fleet_max_replicas": (int, 8,
                                 "autoscale ceiling on fleet replicas"),
    "serve_fleet_scale_up_queue_factor": (
        float, 0.75, "aggregate queue-occupancy fraction that counts a "
                     "pump tick as saturated"),
    "serve_fleet_autoscale_window": (int, 8,
                                     "consecutive saturated pump ticks "
                                     "before a replica spins up"),
    "serve_fleet_scale_down_idle_ticks": (
        int, 64, "consecutive idle pump ticks before one replica is "
                 "drained and retired"),
    # rolling-rollout / retire drain budget: a draining replica gets
    # this long to finish its in-flight set before the router harvests
    # the leftovers and re-homes them on survivors
    "serve_fleet_handoff_timeout_ms": (int, 30_000,
                                       "fleet drain-handoff budget per "
                                       "replica"),
    # unified retry policy (retry.py) used by fleet connect/kv/heartbeat:
    # first backoff sleep; subsequent sleeps take decorrelated jitter in
    # [base, 3*prev] capped at retry_max_delay_ms
    "retry_base_delay_ms": (int, 100, "retry backoff base delay"),
    "retry_max_delay_ms": (int, 5_000, "retry backoff delay cap"),
    # attempts cap per retried call; 0 = bounded only by the call's
    # deadline budget (rpc_deadline_ms or the caller's timeout)
    "retry_max_attempts": (int, 0, "retry attempt cap (0 = deadline-only)"),
}

_values: Dict[str, Any] = {}

# name -> [callbacks]; notified on every set_flags change to that flag
# (and once on registration) so modules can cache hot flag values instead
# of doing a dict lookup per call — monitor.py's enabled() fast path.
_watchers: Dict[str, list] = {}


def _parse(ty, raw: str):
    if ty is bool:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    return ty(raw)


def _bootstrap():
    for name, (ty, default, _doc) in _DEFS.items():
        raw = os.environ.get(f"PT_FLAGS_{name}")
        if raw is None:
            raw = os.environ.get(f"FLAGS_{name}")
        _values[name] = _parse(ty, raw) if raw is not None else default


def get_flag(name: str):
    if name not in _DEFS:
        raise KeyError(f"unknown flag '{name}'; known: {sorted(_DEFS)}")
    return _values[name]


def get_flags(names=None) -> Dict[str, Any]:
    if names is None:
        return dict(_values)
    return {n: get_flag(n) for n in names}


def set_flags(flags: Dict[str, Any]):
    for name, v in flags.items():
        if name not in _DEFS:
            raise KeyError(f"unknown flag '{name}'; known: {sorted(_DEFS)}")
        ty = _DEFS[name][0]
        _values[name] = _parse(ty, v) if isinstance(v, str) else ty(v)
        for cb in _watchers.get(name, ()):
            cb(_values[name])


def watch_flag(name: str, callback):
    """Call ``callback(value)`` now and on every subsequent change to
    ``name`` via set_flags — the cached-hot-flag pattern (monitor.py)."""
    if name not in _DEFS:
        raise KeyError(f"unknown flag '{name}'; known: {sorted(_DEFS)}")
    _watchers.setdefault(name, []).append(callback)
    callback(_values[name])


def describe_flags() -> list:
    """Self-documenting flag table: one dict per registered flag with
    ``name``/``type``/``default``/``doc``/``value`` (current), sorted by
    name — so flag docs are reachable without reading this source."""
    return [
        {
            "name": name,
            "type": ty.__name__,
            "default": default,
            "doc": doc,
            "value": _values[name],
        }
        for name, (ty, default, doc) in sorted(_DEFS.items())
    ]


_bootstrap()
