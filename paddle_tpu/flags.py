"""Process-wide flag plane (reference: gflags — 41 ``DEFINE_*`` sites in
core C++ — bootstrapped from whitelisted env vars in
fluid/__init__.py:106-164 via ``init_gflags``/pybind.cc:954).

Flags are typed, defaulted, and settable three ways: env vars
``PT_FLAGS_<name>`` at import, ``set_flags({...})`` at runtime, or the
reference-style ``FLAGS_<name>`` env spelling. Unknown names raise —
a typo'd flag silently doing nothing is the failure mode gflags avoids.
"""

from __future__ import annotations

import os
from typing import Any, Dict

# name -> (type, default, doc)
_DEFS: Dict[str, tuple] = {
    # per-step NaN/Inf scan of updated state + fetches
    # (reference: FLAGS_check_nan_inf, operator.cc:950)
    "check_nan_inf": (bool, False, "scan step outputs for NaN/Inf"),
    # block until device work finishes each step, for honest timing
    # (reference: FLAGS_benchmark forced dev_ctx->Wait, operator.cc:946)
    "benchmark": (bool, False, "synchronize after every step"),
    # executor compile-cache capacity (entries); 0 = unbounded
    "executor_cache_capacity": (int, 0, "compiled-step cache entries"),
    # program-level PRNG: auto = rbg on TPU (fast hardware generator),
    # threefry elsewhere; or force 'threefry2x32' / 'rbg' / 'unsafe_rbg'
    "prng_impl": (str, "auto", "PRNG implementation for program RNG"),
    # coordination-service RPC deadline (reference: FLAGS_rpc_deadline,
    # default 180s). Generous default: rendezvous keys are often published
    # only after a peer's multi-minute first compile. Pass timeout_ms=-1
    # to a specific call for block-forever.
    "rpc_deadline_ms": (int, 600_000, "coord/KV operation deadline"),
}

_values: Dict[str, Any] = {}


def _parse(ty, raw: str):
    if ty is bool:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    return ty(raw)


def _bootstrap():
    for name, (ty, default, _doc) in _DEFS.items():
        raw = os.environ.get(f"PT_FLAGS_{name}")
        if raw is None:
            raw = os.environ.get(f"FLAGS_{name}")
        _values[name] = _parse(ty, raw) if raw is not None else default


def get_flag(name: str):
    if name not in _DEFS:
        raise KeyError(f"unknown flag '{name}'; known: {sorted(_DEFS)}")
    return _values[name]


def get_flags(names=None) -> Dict[str, Any]:
    if names is None:
        return dict(_values)
    return {n: get_flag(n) for n in names}


def set_flags(flags: Dict[str, Any]):
    for name, v in flags.items():
        if name not in _DEFS:
            raise KeyError(f"unknown flag '{name}'; known: {sorted(_DEFS)}")
        ty = _DEFS[name][0]
        _values[name] = _parse(ty, v) if isinstance(v, str) else ty(v)


_bootstrap()
