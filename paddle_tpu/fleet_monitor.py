"""Fleet-wide observability: cross-rank metric aggregation, straggler
detection, and the /fleet cluster view.

PRs 1-4 built a per-process telemetry plane (monitor.py); every view it
serves is localhost-scoped — a multi-host job has N disconnected
``/metrics`` endpoints and no way to answer "which rank is slow, and
why" without ssh-ing into each worker. This module is the fleet half,
in three pieces:

1. **Digest publish** — every worker periodically serializes a compact
   registry digest (counter/gauge values, histogram sums/counts, the
   last step record with phases + boundedness verdict, trailing
   step-time medians; schema: ``monitor.FLEET_DIGEST_FIELDS``) into fleet
   KV under ``fleet/metrics/g<gen>/<rank>``. Publishes piggyback on the
   existing ``Fleet.heartbeat`` cadence (rate-limited by the
   ``fleet_metrics_interval_ms`` flag) under the quick heartbeat-style
   retry policy — a KV hiccup drops ONE digest, never stalls a step.

2. **Aggregation + cluster view** — rank 0 (or any caller) resolves the
   per-rank digests into one view: per-rank step time, phase breakdown,
   boundedness verdict, barrier waits, heartbeat age — with a rank
   whose digest aged past the staleness window marked ``dead`` instead
   of serving its stale row. Served at the monitor endpoint's
   ``/fleet`` route; ``/metrics?fleet=1`` is the merged Prometheus
   exposition (every rank's digest samples labelled ``rank=``).

3. **Straggler detection** — a rolling cross-rank skew detector over
   the digests' trailing step-time medians: an alive rank whose step time
   exceeds BOTH ``fleet_straggler_factor`` x the alive-rank median AND
   the median + ``fleet_straggler_min_ms`` is named a straggler, with
   the inflated phase attributed by the largest per-phase delta vs the
   cross-rank median phase profile. Detections count into
   ``pt_fleet_straggler_total{rank=}``, append structured records
   (``monitor.STRAGGLER_RECORD_SCHEMA_VERSION``) surfaced at ``/fleet``
   and in stall-watchdog flight-recorder dumps, and warn once per
   (rank, phase) streak.

Disabled-path contract (the monitor.py house rule): with telemetry off
or no multi-worker fleet attached, every entry point returns after one
boolean/None check and allocates nothing — ``Fleet.heartbeat`` gates
the publish call on ``monitor.enabled()`` before this module is even
reached.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import warnings
from statistics import median as _median
from typing import Any, Dict, List, Optional

from paddle_tpu import flags as _flags
from paddle_tpu import monitor as _monitor
from paddle_tpu import retry as _retry

# Publishes ride the heartbeat cadence, so they get the heartbeat's
# retry shape: a few quick attempts, never a long backoff that would
# age the heartbeat itself.
_PUBLISH_POLICY = _retry.RetryPolicy(
    base_delay=0.05, max_delay=0.5, max_attempts=3, retry_on=(OSError,))

_M_PUBLISHED = _monitor.counter(
    "pt_fleet_digests_published_total",
    "metric digests published into fleet KV (piggybacked on heartbeats)")
_M_PUBLISH_DROPS = _monitor.counter(
    "pt_fleet_digest_publish_drops_total",
    "digest publishes dropped after the quick-retry budget (a drop "
    "skips ONE digest; the next heartbeat publishes fresh)")
_M_STRAGGLERS = _monitor.counter(
    "pt_fleet_straggler_total",
    "straggler streaks named by the cross-rank skew detector, by rank "
    "(ticks once per (rank, phase) streak, not per aggregation pass)")

# KV key prefix; generation-scoped so an elastic resize starts a fresh
# namespace instead of mixing digests across worlds.
KV_PREFIX = "fleet/metrics"

# Trailing step-record window the digest medians are computed over: small
# for the same reason monitor.BOUND_WINDOW is — the straggler detector
# must track the CURRENT skew, not average a warmup compile into it.
DIGEST_WINDOW = 8

_LOCK = threading.Lock()

# the Fleet object whose client the /fleet route aggregates through;
# set by maybe_publish (workers) or attach (rank 0 / tests)
_fleet = None

# Aggregation runs on whatever thread asks (the /fleet HTTP handler,
# the trainer's epoch summary, the worker's own loop) but the coord
# client is ONE socket speaking a request/response protocol — two
# threads interleaving frames on it corrupt the stream for good. So
# aggregation (a) takes its own dedicated connection to the coord
# server when the role exposes an endpoint, and (b) serializes every
# pass under one lock. The worker's own client stays untouched by this
# module's readers.
_AGG_LOCK = threading.Lock()
_agg_client = None


def _agg_client_for(fleet):
    """The aggregation-side coord connection (caller holds _AGG_LOCK):
    a lazily-created dedicated socket when the fleet's role knows the
    endpoint, else the fleet's own client (the stub-client tests drive
    aggregation single-threaded)."""
    global _agg_client
    endpoint = None
    role = getattr(fleet, "_role", None)
    ep_fn = getattr(role, "coord_endpoint", None)
    if callable(ep_fn):
        endpoint = ep_fn()
    if not endpoint:
        return fleet._client
    if _agg_client is None:
        from paddle_tpu import native

        host, port = endpoint.rsplit(":", 1)
        _agg_client = native.CoordClient(host, int(port))
    return _agg_client


def _drop_agg_client():
    """Caller holds _AGG_LOCK: a failed socket reconnects next pass."""
    global _agg_client
    client, _agg_client = _agg_client, None
    if client is not None:
        try:
            client.close()
        except OSError:
            pass

_pub_seq = 0
_last_publish_perf = 0.0

_last_view: Optional[Dict[str, Any]] = None
_STRAGGLER_RECORDS: collections.deque = collections.deque(maxlen=64)
# (rank, phase) of the previous detection pass, to warn once per streak
_last_named: frozenset = frozenset()

# aggregator-side digest observation history: (gen, rank) -> [seq,
# local perf_counter of the first pass that saw this seq]. Digest age
# is measured against THIS clock once a rank has history — the
# publisher's wall-clock ts is trusted only for the very first sight
# of a rank, so cross-host clock skew cannot keep flagging a healthy
# publisher dead (or keep a dead rank's future-stamped digest fresh).
_seen: Dict[tuple, list] = {}

# cached hot flag values (flags.watch_flag pattern)
_interval_ms = 1000
_factor = 2.0
_min_ms = 20


def _sync_interval(value):
    global _interval_ms
    _interval_ms = int(value)


def _sync_factor(value):
    global _factor
    _factor = float(value)


def _sync_min_ms(value):
    global _min_ms
    _min_ms = int(value)


_flags.watch_flag("fleet_metrics_interval_ms", _sync_interval)
_flags.watch_flag("fleet_straggler_factor", _sync_factor)
_flags.watch_flag("fleet_straggler_min_ms", _sync_min_ms)


# ---------------------------------------------------------------------------
# digest assembly
# ---------------------------------------------------------------------------

def registry_digest(rank: int = 0, world: int = 1,
                    gen: int = 0) -> Dict[str, Any]:
    """One worker's compact telemetry digest
    (``monitor.FLEET_DIGEST_FIELDS``): counter/gauge cells, histogram
    sum/count cells (no buckets — the digest must stay KV-sized), the
    last step record, the boundedness verdict, and trailing step-time /
    phase medians for the straggler detector."""
    global _pub_seq
    counters: Dict[str, list] = {}
    gauges: Dict[str, list] = {}
    hists: Dict[str, list] = {}
    for name, m in _monitor.snapshot().items():
        cells = m["values"]
        if not cells:
            continue
        if m["kind"] == "counter":
            counters[name] = [{"labels": c["labels"], "value": c["value"]}
                              for c in cells]
        elif m["kind"] == "gauge":
            gauges[name] = [{"labels": c["labels"], "value": c["value"]}
                            for c in cells]
        else:
            hists[name] = [{"labels": c["labels"], "sum": c["sum"],
                            "count": c["count"]} for c in cells]
    recs = _monitor.recent_steps(DIGEST_WINDOW)
    # window MEDIANS, not means: one compile-inflated warmup step in the
    # trailing window would otherwise skew every rank's signal by ITS
    # compile time, and compile durations vary enough across ranks to
    # fake (or mask) a straggler during the first post-warmup steps
    # sampled=False records dispatched fully async: their wall_ms is
    # host-only (no device time) and would drag the median toward zero —
    # only phase-sampled (or pre-sampling-era) records carry honest walls
    walls = [r["wall_ms"] for r in recs
             if isinstance(r.get("wall_ms"), (int, float))
             and r.get("sampled") is not False]
    phase_recs = [r["phases"] for r in recs if isinstance(
        r.get("phases"), dict)]
    phases_ms: Optional[Dict[str, float]] = None
    if phase_recs:
        phases_ms = {}
        for ph in _monitor.STEP_PHASES:
            vals = [p[ph] for p in phase_recs
                    if isinstance(p.get(ph), (int, float))]
            if vals:
                phases_ms[ph] = _median(vals)
    with _LOCK:
        seq = _pub_seq
        _pub_seq += 1
    # roofline rollup (optional field, schema stays v1): per-program
    # measured MFU + verdict, so /fleet names each rank's MFU without
    # shipping whole profiles through KV. Lazy via sys.modules — a
    # worker that never loaded the plane publishes no section.
    import sys as _sys

    rl = _sys.modules.get("paddle_tpu.roofline")
    roofline = rl.digest_section() if rl is not None else None
    # serving rollup (same optional-field pattern): per-replica engine
    # rows + TTFT/token quantiles + SLO counts — the /fleet row a
    # multi-replica router selects replicas on. Absent on ranks that
    # never served.
    st = _sys.modules.get("paddle_tpu.serving_trace")
    serving_sec = st.digest_section() if st is not None else None
    digest = {
        "v": _monitor.FLEET_DIGEST_SCHEMA_VERSION,
        "ts": time.time(),
        "seq": seq,
        "rank": int(rank),
        "world": int(world),
        "gen": int(gen),
        "host": _monitor._HOSTNAME,
        "pid": os.getpid(),
        "counters": counters,
        "gauges": gauges,
        "hists": hists,
        "last_step": recs[-1] if recs else None,
        "bound": _monitor.boundedness(),
        "step_wall_ms": _median(walls) if walls else None,
        "phases_ms": phases_ms,
        "steps": int(_monitor.counter(
            "pt_executor_steps_total").value()),
    }
    if roofline is not None:
        digest["roofline"] = roofline
    if serving_sec is not None:
        digest["serving"] = serving_sec
    return digest


# ---------------------------------------------------------------------------
# publish (piggybacked on Fleet.heartbeat)
# ---------------------------------------------------------------------------

def attach(fleet):
    """Register the Fleet whose KV client the aggregation side reads
    through (done automatically by the first publish)."""
    global _fleet
    _fleet = fleet


def maybe_publish(fleet, force: bool = False):
    """Publish this worker's registry digest into fleet KV, rate-limited
    to one publish per ``fleet_metrics_interval_ms`` (0 = every call).
    Callers gate on ``monitor.enabled()`` — the disabled hot path never
    enters this module. A publish failure past the quick-retry budget
    drops THIS digest (metered + warned once), never raises: telemetry
    must not fail a step."""
    global _last_publish_perf
    client = getattr(fleet, "_client", None)
    if client is None:
        return  # single-worker: nothing to publish, nobody to read it
    if _fleet is not fleet:
        attach(fleet)
    now = time.perf_counter()
    if (not force and _last_publish_perf
            and (now - _last_publish_perf) * 1e3 < _interval_ms):
        return
    _last_publish_perf = now
    digest = registry_digest(rank=fleet.worker_index(),
                             world=fleet.worker_num(),
                             gen=fleet.generation())
    payload = json.dumps(digest, default=str).encode()
    key = f"{KV_PREFIX}/g{digest['gen']}/{digest['rank']}"
    try:
        _retry.call(lambda: client.put(key, payload),
                    site="fleet.metrics_publish", policy=_PUBLISH_POLICY)
        _M_PUBLISHED.inc()
    except Exception as e:
        _M_PUBLISH_DROPS.inc()
        if _M_PUBLISH_DROPS.value() == 1.0:
            warnings.warn(
                f"fleet metric-digest publish failed ({type(e).__name__}:"
                f" {e}); this digest is dropped, the next heartbeat "
                f"publishes fresh", RuntimeWarning)


# ---------------------------------------------------------------------------
# aggregation: the cluster view
# ---------------------------------------------------------------------------

def _staleness_ms(max_age_ms: Optional[int]) -> int:
    """Dead threshold for digest age: explicit, else 4 publish intervals
    floored at 10 s. Publishes ride heartbeats and heartbeats ride the
    STEP cadence, so the floor must tolerate multi-second steps — a
    healthy 5 s-step job must not flap every rank dead between steps
    (callers with slower cadences pass ``max_age_ms`` explicitly;
    ``Fleet.dead_workers`` keeps its own, looser 30 s default)."""
    if max_age_ms is not None:
        return int(max_age_ms)
    return max(10_000, 4 * _interval_ms)


def aggregate(fleet=None, max_age_ms: Optional[int] = None) -> Dict[str, Any]:
    """Resolve every rank's digest from fleet KV into one cluster view:

    ``{ts, gen, world, ranks: {rank: digest + age_ms + dead}, missing:
    [ranks with no digest yet], stragglers: [...], dead: [...]}``

    A rank is ``dead`` when its digest age exceeds the staleness window
    OR the coord service reports its heartbeat stale — the view marks it
    instead of serving its stale row as live. Runs the cross-rank skew
    detector over the alive rows. Uses non-blocking KV reads: the view
    reflects what has been published, it never waits for a peer."""
    fleet = fleet if fleet is not None else _fleet
    if fleet is None or getattr(fleet, "_client", None) is None:
        return _local_view()
    gen = fleet.generation()
    world = fleet.worker_num()
    stale_ms = _staleness_ms(max_age_ms)
    now = time.time()
    ranks: Dict[str, Any] = {}
    missing: List[int] = []
    with _AGG_LOCK:
        client = _agg_client_for(fleet)
        try:
            hb_dead = {str(d) for d in client.dead_peers(stale_ms)}
        except OSError:
            # the dropped client is CLOSED — it must not serve the rank
            # loop below (a get on a closed native handle is undefined
            # behavior, not an error); the whole pass degrades to
            # missing and the next aggregate reconnects
            hb_dead = set()
            _drop_agg_client()
            client = None
        for r in range(world):
            if client is None:
                missing.append(r)
                continue
            try:
                raw = client.get(f"{KV_PREFIX}/g{gen}/{r}", timeout_ms=0)
            except TimeoutError:
                missing.append(r)
                continue
            except OSError:
                missing.append(r)
                _drop_agg_client()
                client = None
                continue
            try:
                digest = json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                missing.append(r)
                continue
            pnow = time.perf_counter()
            ent = _seen.get((gen, r))
            if ent is None:
                # first sight: the publisher's self-reported ts is the
                # only age signal (best-effort under clock skew). The
                # anchor is BACKDATED by that age — an already-stale
                # digest must keep aging on later passes, not resurrect
                # as alive because the anchor said "just seen"
                age_ms = max(0.0,
                             (now - float(digest.get("ts", 0.0))) * 1e3)
                _seen[(gen, r)] = [digest.get("seq"), pnow - age_ms / 1e3]
            elif ent[0] != digest.get("seq"):
                # a fresh publish was OBSERVED — fresh by the
                # aggregator's own clock, whatever the publisher's says
                ent[0], ent[1] = digest.get("seq"), pnow
                age_ms = 0.0
            else:
                age_ms = (pnow - ent[1]) * 1e3
            row = dict(digest)
            row["age_ms"] = age_ms
            row["dead"] = bool(age_ms > stale_ms
                               or f"worker-{r}" in hb_dead)
            ranks[str(r)] = row
    stragglers = _detect_stragglers(ranks, world)
    view = {
        "ts": now,
        "gen": gen,
        "world": world,
        "ranks": ranks,
        "missing": missing,
        "dead": sorted(int(r) for r, row in ranks.items() if row["dead"]),
        "stragglers": stragglers,
        "oom_reports": _monitor.oom_records(),
    }
    global _last_view
    with _LOCK:
        _last_view = view
    return view


def _local_view() -> Dict[str, Any]:
    """Single-process fallback for /fleet: one live row (rank 0) from
    the local registry — the route answers the same shape whether or
    not a multi-worker fleet is up."""
    digest = registry_digest()
    digest["age_ms"] = 0.0
    digest["dead"] = False
    return {
        "ts": digest["ts"],
        "gen": 0,
        "world": 1,
        "ranks": {"0": digest},
        "missing": [],
        "dead": [],
        "stragglers": straggler_records(),
        "oom_reports": _monitor.oom_records(),
    }


def cluster_view(refresh: bool = True) -> Dict[str, Any]:
    """The /fleet route body: re-aggregate through the attached fleet
    when possible (``refresh``), else the last cached view, else the
    local single-rank view."""
    if refresh:
        try:
            return aggregate()
        except Exception as e:
            warnings.warn(f"fleet aggregation failed: {e!r}",
                          RuntimeWarning)
    with _LOCK:
        if _last_view is not None:
            return dict(_last_view)
    return _local_view()


# ---------------------------------------------------------------------------
# straggler detection
# ---------------------------------------------------------------------------

def _detect_stragglers(ranks: Dict[str, Any],
                       world: int) -> List[Dict[str, Any]]:
    """Rolling cross-rank skew pass over the alive rows' trailing
    step-time medians. Returns this pass's records (also appended to
    the bounded module buffer + counted into pt_fleet_straggler_total).
    Attribution: the phase whose median inflates most over the
    cross-rank median phase profile — the seeded delay drill lands its
    sleep in one phase, and THIS is what names it. Detection state
    (record buffer, warn-once streaks) mutates under _LOCK: passes run
    concurrently from the /fleet handler, the trainer's epoch summary
    and the aggregator's own loop."""
    global _last_named
    alive = {int(r): row for r, row in ranks.items()
             if not row.get("dead")
             and isinstance(row.get("step_wall_ms"), (int, float))}
    if len(alive) < 2:
        with _LOCK:
            _last_named = frozenset()
        return []
    med = _median([row["step_wall_ms"] for row in alive.values()])
    # cross-rank median per phase, for attribution deltas
    phase_med: Dict[str, float] = {}
    for ph in _monitor.STEP_PHASES:
        vals = [row["phases_ms"][ph] for row in alive.values()
                if isinstance(row.get("phases_ms"), dict)
                and isinstance(row["phases_ms"].get(ph), (int, float))]
        if vals:
            phase_med[ph] = _median(vals)
    records: List[Dict[str, Any]] = []
    named = set()
    fresh: List[Dict[str, Any]] = []
    for r, row in sorted(alive.items()):
        wall = float(row["step_wall_ms"])
        if wall <= med * _factor or wall - med <= _min_ms:
            continue
        deltas: Dict[str, float] = {}
        if isinstance(row.get("phases_ms"), dict):
            for ph, m in phase_med.items():
                v = row["phases_ms"].get(ph)
                if isinstance(v, (int, float)):
                    deltas[ph] = float(v) - m
        phase = (max(deltas, key=deltas.get) if deltas else "unknown")
        rec = {
            "v": _monitor.STRAGGLER_RECORD_SCHEMA_VERSION,
            "ts": time.time(),
            "rank": r,
            "phase": phase,
            "step_wall_ms": wall,
            "median_wall_ms": med,
            "factor": wall / med if med > 0 else float("inf"),
            "steps": int(row.get("steps", 0)),
            "world": int(world),
            "deltas_ms": deltas,
        }
        records.append(rec)
        named.add((r, phase))
    # the counter, the bounded record buffer and the warning all tick
    # once per (rank, phase) STREAK — aggregation runs on every /fleet
    # scrape, and per-pass accounting would make the metric's rate a
    # function of whoever is polling (and flood the flight-recorder
    # buffer with duplicates of the current streak). The returned
    # records still reflect THIS pass, so the live view always shows
    # the current stragglers.
    with _LOCK:
        fresh = [rec for rec in records
                 if (rec["rank"], rec["phase"]) not in _last_named]
        _STRAGGLER_RECORDS.extend(fresh)
        _last_named = frozenset(named)
    for rec in fresh:
        _M_STRAGGLERS.inc(labels={"rank": rec["rank"]})
        warnings.warn(
            f"fleet straggler: rank {rec['rank']} step time "
            f"{rec['step_wall_ms']:.1f} ms vs cluster median "
            f"{rec['median_wall_ms']:.1f} ms ({rec['factor']:.1f}x); "
            f"inflated phase: {rec['phase']}",
            RuntimeWarning)
    return records


def straggler_records() -> List[Dict[str, Any]]:
    """Buffered straggler records, oldest first (bounded)."""
    with _LOCK:
        return [dict(r) for r in _STRAGGLER_RECORDS]


def summary() -> Dict[str, Any]:
    """The stall watchdog's flight-recorder section: the last cluster
    view (if any) + the straggler record buffer."""
    with _LOCK:
        view = dict(_last_view) if _last_view is not None else None
    return {"view": view, "stragglers": straggler_records()}


# ---------------------------------------------------------------------------
# merged Prometheus exposition (/metrics?fleet=1)
# ---------------------------------------------------------------------------

def to_prometheus_fleet(view: Optional[Dict[str, Any]] = None) -> str:
    """Merge the latest aggregated digests into one Prometheus text
    exposition: every rank's counter/gauge cells re-labelled with
    ``rank=``; histograms as ``_sum``/``_count`` pairs (buckets stay on
    each worker's own /metrics). Docs/types come from the local
    registry when the metric is registered here too."""
    view = cluster_view() if view is None else view

    def _labels(cell, r):
        # publisher rank labels every merged sample; a metric's OWN
        # rank label (pt_fleet_straggler_total{rank=}) must survive as
        # exported_rank (the Prometheus-federation convention), not be
        # clobbered into naming the publisher
        labels = dict(cell["labels"])
        if "rank" in labels:
            labels["exported_rank"] = labels.pop("rank")
        labels["rank"] = r
        return labels

    # name -> (kind, [(labels+rank, value-or-(sum,count))])
    merged: Dict[str, tuple] = {}
    for r, row in sorted(view.get("ranks", {}).items(),
                         key=lambda kv: int(kv[0])):
        for name, cells in sorted(row.get("counters", {}).items()):
            merged.setdefault(name, ("counter", []))[1].extend(
                (_labels(c, r), c["value"]) for c in cells)
        for name, cells in sorted(row.get("gauges", {}).items()):
            merged.setdefault(name, ("gauge", []))[1].extend(
                (_labels(c, r), c["value"]) for c in cells)
        for name, cells in sorted(row.get("hists", {}).items()):
            merged.setdefault(name, ("histogram", []))[1].extend(
                (_labels(c, r), (c["sum"], c["count"]))
                for c in cells)
    lines: List[str] = []
    for name, (kind, cells) in sorted(merged.items()):
        local = _monitor._REGISTRY.get(name)
        if local is not None and local.doc:
            lines.append(f"# HELP {name} {local.doc}")
        lines.append(f"# TYPE {name} {'untyped' if kind == 'histogram' else kind}")
        for labels, val in cells:
            if kind == "histogram":
                s, c = val
                lines.append(
                    f"{name}_sum{_monitor._prom_labels(labels)} {s}")
                lines.append(
                    f"{name}_count{_monitor._prom_labels(labels)} {c}")
            else:
                lines.append(
                    f"{name}{_monitor._prom_labels(labels)} {val}")
    return "\n".join(lines) + ("\n" if lines else "")


# ---------------------------------------------------------------------------
# trainer epoch summary + test isolation
# ---------------------------------------------------------------------------

def epoch_summary_line() -> Optional[str]:
    """One fleet-summary line for the trainer's per-epoch log, or None
    when there is nothing fleet-wide to say (single worker, no fleet
    attached, or not rank 0 — only the aggregator prints, or N workers
    would log N copies)."""
    fleet = _fleet
    if (fleet is None or getattr(fleet, "_client", None) is None
            or fleet.worker_num() <= 1 or fleet.worker_index() != 0):
        return None
    view = aggregate(fleet)
    ranks = view["ranks"]
    walls = sorted(
        (row["step_wall_ms"], int(r)) for r, row in ranks.items()
        if not row["dead"]
        and isinstance(row.get("step_wall_ms"), (int, float)))
    span = ""
    if walls:
        lo, lo_r = walls[0]
        hi, hi_r = walls[-1]
        span = (f", step ms min {lo:.1f} (rank {lo_r}) / "
                f"max {hi:.1f} (rank {hi_r})")
    streak = {f"rank {rec['rank']} ({rec['phase']})"
              for rec in view["stragglers"]}
    lagline = ("stragglers: " + ", ".join(sorted(streak))
               if streak else "stragglers: none")
    n_alive = len(ranks) - len(view["dead"])
    return (f"fleet: {n_alive}/{view['world']} ranks alive"
            + (f", dead {view['dead']}" if view["dead"] else "")
            + (f", missing {view['missing']}" if view["missing"] else "")
            + span + ", " + lagline)


def reset():
    """Test isolation (called from monitor.reset): drop the attached
    fleet, cached view, straggler buffer and publish cursor."""
    global _fleet, _last_view, _pub_seq, _last_publish_perf, _last_named
    with _LOCK:
        _fleet = None
        _last_view = None
        _pub_seq = 0
        _last_publish_perf = 0.0
        _last_named = frozenset()
        _STRAGGLER_RECORDS.clear()
    with _AGG_LOCK:
        # _seen is aggregation state mutated under _AGG_LOCK — clearing
        # it under _LOCK would race an in-flight aggregate() pass
        # reinserting pre-reset entries after the clear
        _seen.clear()
        _drop_agg_client()
