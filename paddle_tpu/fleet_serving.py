"""Fleet front door: routed multi-replica serving behind one address.

PRs 13-16 built one resilient serving replica (continuous batching,
supervised warm restart, overload shedding, request-scoped tracing with
deadline attribution). This module is the layer the north star actually
needs: a ``ServingFleet`` router/scheduler that owns N
``EngineSupervisor`` replicas behind a single ``submit()``, so a
replica dying, wedging, being upgraded, or being added under load is
invisible to every in-flight request.

- **Load/deadline-aware routing**: each submit scores the serving
  replicas with the signals the replica plane already measures — the
  admission per-token EWMA times the remaining-token backlog (the same
  arithmetic as the engine's own ``_estimate_first_token_s``), plus an
  EWMA of the replica's recently MEASURED queue waits (the PR 16
  ``queue_wait`` deadline-attribution phase, read off terminal
  requests) — and picks the lowest estimated time-to-first-token. A
  replica that refuses (QueueFull / DeadlineUnmeetable / racing a
  restart) just moves the request to the next candidate; the fleet
  sheds only when EVERY replica refuses
  (``pt_fleet_serve_shed_total``).
- **Failover replay**: the router journals every admitted request
  (the handle itself carries the prompt, sampling params, and tokens
  already streamed). When a replica crashes, wedges past its
  supervisor's watchdog budget, or exhausts ``serve_max_restarts``
  (the supervisor's ``on_handoff`` seam), its pending requests are
  harvested and re-enqueued on survivors through the same replay
  intake a supervised restart uses. Greedy decode is deterministic, so
  the replay re-derives the byte-identical stream; the fleet handle
  (``FleetRequest``) snapshots the already-streamed tokens before the
  wipe-at-re-prefill and serves a MONOTONE view — the client-visible
  stream continues without duplication or gap, on one trace tid
  (the ServeRequest handle, and with it the pinned track, survives).
- **Autoscaling** (``serve_fleet_autoscale``): sustained aggregate
  queue saturation over a window of pump ticks spins up a replica —
  warm, through the persistent/multi-host compile cache (zero fresh
  XLA compiles; see tests/fleet_serve_worker.py) — and sustained
  idleness drains-then-retires one. A custom ``replica_factory`` is
  the seam for spinning replicas on OTHER hosts via the fleet join
  machinery (fleet_base.join_world); the default factory builds local
  supervisors.
- **Zero-downtime rolling rollout**: ``rollout(new_weights)`` bumps
  the fleet generation and rotates replicas ONE at a time —
  replacement first (warm start), then the old replica drains: it
  admits nothing new, finishes its in-flight set within
  ``serve_fleet_handoff_timeout_ms``, and hands queued + leftover
  requests to survivors instead of rejecting them. Every response
  carries the generation tag of the replica that served it
  (``FleetRequest.generation``), so mixed-fleet serving is detectable
  request by request.

Chaos plan sites (faults.py): ``router.route`` (submit-path failure),
``router.replica_crash`` (hard-kill the N-th replica —
``raise(replica=N)`` — at a deterministic pump tick),
``router.handoff`` (tear a rolling-rollout drain mid-handoff).

Observability: ``pt_fleet_serve_*`` metrics ride the monitor registry
and the ``/fleet`` route grows a ``serving_fleet`` section (per-replica
state, queue depth, generation, last-heartbeat age) via
``fleet_view()``.
"""

from __future__ import annotations

import collections
import itertools
import re
import threading
import time
import warnings
import weakref
from typing import Dict, List, Optional, Sequence

from paddle_tpu import faults as _faults
from paddle_tpu import flags as _flags
from paddle_tpu import monitor as _monitor
from paddle_tpu import serving as _serving

# --- telemetry (no-ops while the 'telemetry' flag is off) ---

_M_REPLICAS = _monitor.gauge(
    "pt_fleet_serve_replicas",
    "serving-fleet replicas by lifecycle state (serving / draining)")
_M_ROUTED = _monitor.counter(
    "pt_fleet_serve_routed_total",
    "requests admitted through the fleet router (per-replica split in "
    "the /fleet serving_fleet section)")
_M_SHED = _monitor.counter(
    "pt_fleet_serve_shed_total",
    "fleet submits refused by EVERY replica, by kind (queue_full / "
    "deadline / no_replica)")
_M_FAILOVERS = _monitor.counter(
    "pt_fleet_serve_failovers_total",
    "replicas removed from the fleet with requests re-homed, by cause "
    "(crash = chaos kill or wedge past the supervisor, giveup = "
    "restart budget exhausted, handoff = rollout/retire drain)")
_M_REPLAYED = _monitor.counter(
    "pt_fleet_serve_replayed_total",
    "requests re-homed onto a surviving replica's replay intake after "
    "a failover or drain handoff (greedy decode keeps the client-"
    "visible stream byte-identical)")
_M_SCALE = _monitor.counter(
    "pt_fleet_serve_scale_total",
    "autoscaler actions by direction (up = warm replica spin-up under "
    "sustained queue saturation, down = drain-then-retire under "
    "sustained idleness)")
_M_ROLLOUTS = _monitor.counter(
    "pt_fleet_serve_rollouts_total",
    "completed rolling weight rollouts (every replica rotated to the "
    "new generation with zero rejected-for-rollout requests)")
_M_GENERATION = _monitor.gauge(
    "pt_fleet_serve_generation",
    "current fleet weight generation (responses tag the generation "
    "that served them, so a mixed fleet mid-rollout is detectable)")

# chaos hooks — see BUILTIN_SITES in faults.py for the drill semantics
_F_ROUTE = _faults.site("router.route")
_F_CRASH = _faults.site("router.replica_crash")
_F_HANDOFF = _faults.site("router.handoff")

# the chaos plan's raise(replica=N) attribution (mirrors the serving
# plane's slot-hint protocol)
_REPLICA_HINT_RE = re.compile(r"replica\s*[=:]\s*(\d+)")

_FLEETS: "weakref.WeakSet[ServingFleet]" = weakref.WeakSet()


class FleetClosed(RuntimeError):
    pass


class NoReplicaAvailable(RuntimeError):
    """Raised by submit() when the fleet has no serving replica at all
    (every replica draining/retired and autoscaling off)."""


class FleetRequest:
    """Fleet-level request handle: wraps the ONE ServeRequest that
    survives failover (the handle — and with it the trace tid, the
    original submit timestamp, and the partial output — is re-homed
    across replicas, never recreated).

    ``tokens`` is the client-visible stream: a monotone view over the
    underlying handle. The router snapshots the already-streamed
    tokens before a replay's wipe-at-re-prefill; because greedy decode
    re-derives the identical prefix, the view never shrinks and never
    duplicates — the stream continues exactly where the dead replica
    left it."""

    __slots__ = ("_sr", "replica_id", "generation", "failovers",
                 "_streamed")

    def __init__(self, sr: "_serving.ServeRequest", replica_id: int,
                 generation: int):
        self._sr = sr
        self.replica_id = replica_id    # replica currently serving it
        self.generation = generation    # weight generation tag
        self.failovers = 0              # fleet-level re-homes
        self._streamed: List[int] = []

    def _note_streamed(self):
        """Snapshot the tokens the client has already seen — called by
        the router BEFORE a replay can wipe them at re-prefill."""
        cur = list(self._sr.tokens)
        if len(cur) > len(self._streamed):
            self._streamed = cur

    @property
    def tokens(self) -> List[int]:
        cur = list(self._sr.tokens)
        streamed = self._streamed
        return cur if len(cur) >= len(streamed) else list(streamed)

    @property
    def done(self) -> bool:
        return self._sr.done

    @property
    def outcome(self) -> Optional[str]:
        return self._sr.outcome

    @property
    def trace_id(self) -> str:
        return self._sr.trace_id

    @property
    def trace_tid(self) -> Optional[int]:
        return self._sr.trace_tid

    @property
    def replays(self) -> int:
        return self._sr.replays

    @property
    def ttft_s(self) -> Optional[float]:
        return self._sr.ttft_s

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until terminal; returns the monotone token view."""
        self._sr.result(timeout)
        return self.tokens


class _Replica:
    """Router-side view of one EngineSupervisor replica."""

    __slots__ = ("id", "sup", "generation", "state", "routed",
                 "qwait_ewma_s", "created_ts")

    def __init__(self, rid: int, sup: "_serving.EngineSupervisor",
                 generation: int):
        self.id = rid
        self.sup = sup
        self.generation = generation
        self.state = "serving"          # serving | draining
        self.routed = 0
        # EWMA of MEASURED queue waits off this replica's terminal
        # requests — the PR 16 deadline-attribution phase feeding back
        # into routing
        self.qwait_ewma_s = 0.0
        self.created_ts = time.perf_counter()


class ServingFleet:
    """N supervised serving replicas behind one submit() address.

    ``replica_factory`` (optional) builds one replica's supervisor:
    ``factory(cfg, weights, on_handoff=..., **engine_kwargs) ->
    EngineSupervisor``-shaped object. The default builds a local
    EngineSupervisor; a multi-host deployment plugs the fleet join
    machinery in here. All replicas should share ``compile_cache_dir``
    so spin-ups and rollout rejoins are warm (zero fresh compiles)."""

    def __init__(self, cfg, weights, *, replicas: int = 2,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 autoscale: Optional[bool] = None,
                 handoff_timeout_s: Optional[float] = None,
                 poll_s: float = 0.02,
                 replica_factory=None, **engine_kwargs):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self._cfg = cfg
        self._weights = weights
        self._engine_kwargs = dict(engine_kwargs)
        self._factory = replica_factory
        self._poll_s = float(poll_s)
        self.min_replicas = (
            int(_flags.get_flag("serve_fleet_min_replicas"))
            if min_replicas is None else int(min_replicas))
        self.max_replicas = (
            int(_flags.get_flag("serve_fleet_max_replicas"))
            if max_replicas is None else int(max_replicas))
        self._autoscale = autoscale
        self.handoff_timeout_s = (
            float(_flags.get_flag("serve_fleet_handoff_timeout_ms"))
            / 1e3 if handoff_timeout_s is None
            else float(handoff_timeout_s))
        self.generation = 0
        self.failovers = 0
        self.replayed = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.rollouts = 0
        self._shed = 0
        self._rid = itertools.count(1)
        self._lock = threading.RLock()
        self._replicas: "collections.OrderedDict[int, _Replica]" = \
            collections.OrderedDict()
        self._closed = False
        # journal of live admitted requests: sr.id -> FleetRequest.
        # Guarded by its OWN lock — the supervisor on_handoff callback
        # runs under the supervisor's lock and must never wait on the
        # fleet lock (lock order there is supervisor -> journal only).
        self._journal_lock = threading.Lock()
        self._journal: Dict[int, FleetRequest] = {}
        # requests handed off by a terminally-failing supervisor
        # (deque appends are atomic; drained by the pump thread)
        self._orphans: "collections.deque" = collections.deque()
        self._saturated_ticks = 0
        self._idle_ticks = 0
        for _ in range(replicas):
            self._spawn_replica()
        _FLEETS.add(self)
        self._pump_thread = threading.Thread(
            target=self._pump, name="pt-fleet-router", daemon=True)
        self._pump_thread.start()

    # --- replica lifecycle ---

    def _build_supervisor(self):
        factory = self._factory
        if factory is None:
            factory = _serving.EngineSupervisor
        return factory(self._cfg, self._weights,
                       on_handoff=self._accept_orphans,
                       **self._engine_kwargs)

    def _spawn_replica(self) -> _Replica:
        rep = _Replica(next(self._rid), self._build_supervisor(),
                       self.generation)
        with self._lock:
            self._replicas[rep.id] = rep
            self._publish_replicas_locked()
        return rep

    def _publish_replicas_locked(self):
        counts = {"serving": 0, "draining": 0}
        for rep in self._replicas.values():
            counts[rep.state] = counts.get(rep.state, 0) + 1
        _M_REPLICAS.replace(
            [({"state": state}, float(n))
             for state, n in sorted(counts.items())])

    def _remove_replica(self, rep: _Replica, cause: str):
        """Hard failover: harvest the replica's pending set and re-home
        it on survivors. The supervisor may already be closed (giveup
        path — its pending arrived through on_handoff)."""
        with self._lock:
            self._replicas.pop(rep.id, None)
            self._publish_replicas_locked()
        pending = rep.sup.harvest()
        self.failovers += 1
        _M_FAILOVERS.inc(labels={"cause": cause})
        warnings.warn(
            f"serving fleet: replica {rep.id} removed ({cause}); "
            f"re-homing {len(pending)} in-flight request(s)",
            RuntimeWarning)
        self._requeue(pending)

    # --- routing ---

    def _serving_replicas(self) -> List[_Replica]:
        with self._lock:
            return [r for r in self._replicas.values()
                    if r.state == "serving"]

    def _score(self, rep: _Replica) -> float:
        """Estimated time-to-first-token on this replica: the
        admission EWMA times the remaining-token backlog (queue +
        in-flight), plus the replica's measured queue-wait EWMA.
        Racy unlocked reads — this is a routing hint, the replica's
        own admission control is the authority."""
        try:
            eng = rep.sup.engine
        except Exception:
            return float("inf")
        ewma = eng._token_ewma_s or 0.0
        outstanding = 0
        with eng._lock:
            backlog = 0
            for r in eng._queue:
                backlog += r.max_new_tokens
                outstanding += 1
            for s in eng._slots:
                r = s.request
                if r is not None and r.outcome is None:
                    backlog += max(0, r.max_new_tokens - len(r.tokens))
                    outstanding += 1
        eta = ewma * (backlog / float(eng.slots) + 1.0)
        # the epsilon term spreads a COLD fleet (no EWMA yet — every
        # eta is 0) by outstanding request count instead of letting a
        # stable sort pile everything on the first replica
        return eta + rep.qwait_ewma_s + 1e-6 * outstanding

    def submit(self, src_ids: Sequence[int],
               src_pad=None, max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> FleetRequest:
        """Route one request onto the best serving replica. Tries
        replicas in ascending estimated-TTFT order; a refusal
        (QueueFull / DeadlineUnmeetable / racing a restart) moves on
        to the next. Raises the LAST refusal only when every serving
        replica refused (the fleet-level shed)."""
        if self._closed:
            raise FleetClosed("submit() on a closed fleet")
        _F_ROUTE.hit()
        candidates = sorted(self._serving_replicas(), key=self._score)
        if not candidates:
            _M_SHED.inc(labels={"kind": "no_replica"})
            self._shed += 1
            raise NoReplicaAvailable(
                "no serving replica (all draining or retired)")
        last: Optional[BaseException] = None
        for rep in candidates:
            try:
                sr = rep.sup.submit(
                    src_ids, src_pad=src_pad,
                    max_new_tokens=max_new_tokens,
                    deadline_ms=deadline_ms)
            except (_serving.QueueFull, _serving.DeadlineUnmeetable,
                    _serving.EngineClosed,
                    _serving.EngineFailed) as e:
                last = e
                continue
            rep.routed += 1
            fr = FleetRequest(sr, rep.id, rep.generation)
            with self._journal_lock:
                self._journal[sr.id] = fr
            _M_ROUTED.inc()
            return fr
        self._shed += 1
        _M_SHED.inc(labels={
            "kind": ("queue_full"
                     if isinstance(last, _serving.QueueFull)
                     else "deadline"
                     if isinstance(last, _serving.DeadlineUnmeetable)
                     else "no_replica")})
        raise last

    # --- failover replay ---

    def _accept_orphans(self, requests) -> bool:
        """EngineSupervisor on_handoff seam: a terminally-failing
        supervisor offers its pending set. Runs UNDER the supervisor's
        lock — only snapshot + enqueue here; the pump thread does the
        actual re-homing."""
        if self._closed:
            return False
        with self._journal_lock:
            for sr in requests:
                fr = self._journal.get(sr.id)
                if fr is not None:
                    fr._note_streamed()
        self._orphans.extend(requests)
        return True

    def _requeue(self, pending) -> int:
        """Re-home harvested requests on surviving replicas through the
        supervised-replay intake. Requests that cannot land anywhere
        finish 'error' (result() must never hang on a dead fleet)."""
        moved = 0
        for sr in pending:
            if sr.outcome is not None:
                continue
            with self._journal_lock:
                fr = self._journal.get(sr.id)
            if fr is not None:
                fr._note_streamed()
            placed = False
            for rep in sorted(self._serving_replicas(),
                              key=self._score):
                if rep.sup.enqueue_replay(sr):
                    placed = True
                    moved += 1
                    self.replayed += 1
                    _M_REPLAYED.inc()
                    if fr is not None:
                        fr.failovers += 1
                        fr.replica_id = rep.id
                        fr.generation = rep.generation
                    break
            if not placed and sr.outcome is None:
                sr._finish("error")
        return moved

    # --- the router pump ---

    def _pump(self):
        while not self._closed:
            try:
                self._pump_tick()
            except Exception as e:  # the pump must survive anything
                warnings.warn(
                    f"serving fleet: pump error "
                    f"{type(e).__name__}: {e}", RuntimeWarning)
            time.sleep(self._poll_s)

    def _pump_tick(self):
        # 1. the kill-one-replica chaos drill
        try:
            _F_CRASH.hit()
        except _faults.InjectedFault as e:
            self._chaos_kill(e)
        # 2. dead-supervisor detection: a supervisor that went
        # terminal on its own (budget exhausted, rebuild failed) — its
        # pending set already arrived via on_handoff; drop the corpse
        for rep in list(self._serving_replicas()):
            if rep.sup.state == "closed":
                with self._lock:
                    self._replicas.pop(rep.id, None)
                    self._publish_replicas_locked()
                self.failovers += 1
                _M_FAILOVERS.inc(labels={"cause": "giveup"})
        # 3. re-home orphans handed off by terminal supervisors
        orphans = []
        while True:
            try:
                orphans.append(self._orphans.popleft())
            except IndexError:
                break
        if orphans:
            self._requeue(orphans)
        # 4. prune the journal + feed measured queue waits back into
        # the routing score
        self._prune_journal()
        # 5. autoscale
        auto = (self._autoscale if self._autoscale is not None
                else bool(_flags.get_flag("serve_fleet_autoscale")))
        if auto:
            self.autoscale_tick()

    def _chaos_kill(self, exc):
        live = self._serving_replicas()
        if not live:
            return
        m = _REPLICA_HINT_RE.search(str(exc))
        idx = int(m.group(1)) if m else 0
        live.sort(key=lambda r: r.id)
        if idx >= len(live):
            warnings.warn(
                f"serving fleet: chaos kill hint replica={idx} out of "
                f"range ({len(live)} live); killing replica 0",
                RuntimeWarning)
            idx = 0
        self._remove_replica(live[idx], cause="crash")

    def _prune_journal(self):
        with self._journal_lock:
            done = [(rid, fr) for rid, fr in self._journal.items()
                    if fr.done]
            for rid, _fr in done:
                del self._journal[rid]
        if not done:
            return
        with self._lock:
            reps = dict(self._replicas)
        for _rid, fr in done:
            rep = reps.get(fr.replica_id)
            qw = fr._sr.queue_wait_s
            if rep is not None and qw is not None:
                rep.qwait_ewma_s += 0.2 * (qw - rep.qwait_ewma_s)

    # --- autoscaling ---

    def autoscale_tick(self) -> Optional[str]:
        """One deterministic autoscaler evaluation (the pump calls
        this when ``serve_fleet_autoscale`` is on; tests call it
        directly). Returns 'up' / 'down' when it acted."""
        serving = self._serving_replicas()
        if not serving:
            return None
        queued = capacity = 0
        busy = False
        for rep in serving:
            try:
                eng = rep.sup.engine
            except Exception:
                continue
            with eng._lock:
                queued += len(eng._queue)
            capacity += eng.queue_depth
            busy = busy or rep.sup.busy()
        factor = float(
            _flags.get_flag("serve_fleet_scale_up_queue_factor"))
        window = int(_flags.get_flag("serve_fleet_autoscale_window"))
        idle_after = int(
            _flags.get_flag("serve_fleet_scale_down_idle_ticks"))
        if capacity and queued >= factor * capacity:
            self._saturated_ticks += 1
            self._idle_ticks = 0
            if (self._saturated_ticks >= window
                    and len(serving) < self.max_replicas):
                self._saturated_ticks = 0
                self._spawn_replica()
                self.scale_ups += 1
                _M_SCALE.inc(labels={"direction": "up"})
                return "up"
            return None
        self._saturated_ticks = 0
        if busy or queued:
            self._idle_ticks = 0
            return None
        self._idle_ticks += 1
        if (self._idle_ticks >= idle_after
                and len(serving) > self.min_replicas):
            self._idle_ticks = 0
            # retire the newest replica (oldest keep their warm EWMAs)
            victim = max(serving, key=lambda r: r.id)
            self._retire_replica(victim, cause="handoff")
            self.scale_downs += 1
            _M_SCALE.inc(labels={"direction": "down"})
            return "down"
        return None

    # --- drain handoff + rolling rollout ---

    def _retire_replica(self, rep: _Replica, cause: str):
        """Drain-then-retire: the replica admits nothing new (router
        skips it), finishes its in-flight set within the handoff
        budget, and hands queued + leftover requests to survivors. A
        torn handoff (router.handoff raise) degrades to the hard
        failover path — the requests still re-home."""
        with self._lock:
            if rep.id not in self._replicas:
                return
            rep.state = "draining"
            self._publish_replicas_locked()
        try:
            _F_HANDOFF.hit()
            moved = rep.sup.handoff(timeout_s=self.handoff_timeout_s)
        except _faults.InjectedFault as e:
            warnings.warn(
                f"serving fleet: drain handoff of replica {rep.id} "
                f"torn by chaos plan ({e}); hard-harvesting",
                RuntimeWarning)
            moved = rep.sup.harvest()
        with self._lock:
            self._replicas.pop(rep.id, None)
            self._publish_replicas_locked()
        if moved:
            self.failovers += 1
            _M_FAILOVERS.inc(labels={"cause": cause})
        self._requeue(moved)

    def rollout(self, new_weights, *,
                drain_timeout_s: Optional[float] = None) -> Dict:
        """Zero-downtime rolling weight rollout: bump the fleet
        generation, then rotate replicas one at a time — spawn the
        replacement FIRST (warm via the compile cache, so capacity
        never dips below N), then drain the old replica and re-home
        whatever it could not finish. No request is rejected for the
        rollout's sake; responses carry the generation that served
        them, so the mixed fleet mid-rollout is observable."""
        if self._closed:
            raise FleetClosed("rollout() on a closed fleet")
        if drain_timeout_s is not None:
            budget = float(drain_timeout_s)
        else:
            budget = self.handoff_timeout_s
        with self._lock:
            self.generation += 1
            gen = self.generation
            self._weights = new_weights
            old = [r for r in self._replicas.values()
                   if r.generation < gen]
        _M_GENERATION.set(float(gen))
        rotated = 0
        for rep in old:
            with self._lock:
                if self._closed or rep.id not in self._replicas:
                    continue
            self._spawn_replica()  # joins at the NEW generation
            self._retire_replica(rep, cause="handoff")
            rotated += 1
        self.rollouts += 1
        _M_ROLLOUTS.inc()
        return {"generation": gen, "replicas_rotated": rotated,
                "replicas": len(self._replicas)}

    # --- lifecycle + observability ---

    def busy(self) -> bool:
        if self._orphans:
            return True
        with self._lock:
            reps = list(self._replicas.values())
        return any(rep.sup.busy() for rep in reps)

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Stop nothing fleet-wide (submits keep routing); wait for
        every replica to go idle."""
        t0 = time.perf_counter()
        while self.busy():
            if time.perf_counter() - t0 > timeout_s:
                return False
            time.sleep(self._poll_s)
        return True

    def close(self, drain_timeout_s: float = 30.0):
        """Drain every replica, stop the pump, close supervisors.
        Every still-pending handle is finished — result() never hangs
        on a closed fleet."""
        if self._closed:
            return
        self.drain(drain_timeout_s)
        self._closed = True
        if self._pump_thread is not threading.current_thread():
            self._pump_thread.join(timeout=5.0)
        with self._lock:
            reps = list(self._replicas.values())
            self._replicas.clear()
            self._publish_replicas_locked()
        for rep in reps:
            try:
                rep.sup.close(drain_timeout_s=0.0)
            except Exception:
                pass
        # orphans that raced the shutdown: nobody will replay them
        while True:
            try:
                sr = self._orphans.popleft()
            except IndexError:
                break
            if sr.outcome is None:
                sr._finish("error")
        with self._journal_lock:
            self._journal.clear()
        _FLEETS.discard(self)

    def stats(self) -> Dict:
        """One JSON-able fleet row for the /fleet route."""
        with self._lock:
            reps = list(self._replicas.values())
        rows = []
        for rep in reps:
            try:
                eng = rep.sup.engine
                row = {
                    "replica": rep.id,
                    "engine_id": eng.engine_id,
                    "state": (rep.state if rep.state == "draining"
                              else rep.sup.state),
                    "generation": rep.generation,
                    "queue_depth": len(eng._queue),
                    "slots_active": int(eng._active_mask().sum()),
                    "heartbeat_age_ms": round(
                        eng.heartbeat_age_s() * 1e3, 1),
                    "routed": rep.routed,
                    "restarts": rep.sup.restarts,
                    "qwait_ewma_ms": round(
                        rep.qwait_ewma_s * 1e3, 3),
                }
            except Exception as e:  # a replica mid-teardown
                row = {"replica": rep.id, "state": "unknown",
                       "error": f"{type(e).__name__}: {e}"}
            rows.append(row)
        with self._journal_lock:
            in_flight = len(self._journal)
        return {
            "replicas": rows,
            "replica_count": len(rows),
            "queue_depth": sum(r.get("queue_depth", 0) for r in rows),
            "generation": self.generation,
            "in_flight": in_flight,
            "orphans_pending": len(self._orphans),
            "failovers": self.failovers,
            "replayed": self.replayed,
            "shed": self._shed,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "rollouts": self.rollouts,
        }


def fleet_view() -> Optional[Dict]:
    """The /fleet route's ``serving_fleet`` section: one stats row per
    live ServingFleet, or None when no fleet is up (the route then
    serves the training-fleet view unchanged)."""
    fleets = [f.stats() for f in list(_FLEETS) if not f._closed]
    if not fleets:
        return None
    return {"fleets": fleets, "fleet_count": len(fleets)}


def serve_fleet(cfg, weights, *, replicas: int = 2,
                **kwargs) -> ServingFleet:
    """Front end mirroring serving.serve(): build a routed fleet of
    ``replicas`` supervised engines over shared weights."""
    return ServingFleet(cfg, weights, replicas=replicas, **kwargs)
