"""Program IR: Program / Block / Operator / Variable / Parameter.

TPU-native re-design of the reference's Python graph builder
(reference: python/paddle/fluid/framework.py:366,925,1370,2705,3481).
The programming model is the same define-then-run contract — Python appends
OpDescs into blocks of a serializable Program — but:

- Shape/dtype inference is abstract evaluation of the registered JAX kernel
  (``jax.eval_shape``) instead of per-op C++ InferShape.
- There is no LoD; variable-length data is padded/bucketed host-side and
  carried as dense tensors plus masks (XLA static-shape discipline,
  SURVEY.md section 5).
- Execution happens by lowering a whole block to one XLA computation
  (see core/lowering.py), so the Program is a *staging* IR, not an
  interpreter instruction list.
"""

from __future__ import annotations

import hashlib

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu import unique_name
from paddle_tpu.core.registry import (
    GRAD_OP_SUFFIX,
    GRAD_SUFFIX,
    get_op_def,
    has_op,
)
from paddle_tpu.proto import framework_pb2 as pb

# Sentinel used to stand in for a symbolic (-1) batch dim during abstract
# shape inference. Prime and unlikely to appear as a real static dim.
_BATCH_SENTINEL = 997


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


def convert_np_dtype_to_dtype_(dtype) -> str:
    """Canonicalize any dtype spec to a numpy dtype name string."""
    if isinstance(dtype, str) and dtype in ("bfloat16",):
        return "bfloat16"
    try:
        return np.dtype(dtype).name
    except TypeError:
        # jax dtypes like jnp.bfloat16
        return np.dtype(getattr(dtype, "dtype", dtype)).name


class Variable:
    """A named tensor in a Block (reference: framework.py:366)."""

    def __init__(
        self,
        block: "Block",
        name: str,
        shape: Optional[Sequence[int]] = None,
        dtype: Any = "float32",
        persistable: bool = False,
        stop_gradient: bool = False,
        is_parameter: bool = False,
        trainable: bool = True,
        kind: int = pb.VarDesc.DENSE_TENSOR,
    ):
        self.block = block
        self.name = name
        self.shape = tuple(int(d) for d in shape) if shape is not None else None
        self.dtype = convert_np_dtype_to_dtype_(dtype) if dtype is not None else None
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_parameter = is_parameter
        self.trainable = trainable
        self.kind = kind
        # set by layers that carry a sequence mask alongside padded data
        self.mask_name: Optional[str] = None

    @property
    def grad_name(self) -> str:
        return grad_var_name(self.name)

    def to_proto(self) -> pb.VarDesc:
        d = pb.VarDesc(name=self.name, kind=self.kind)
        if self.dtype is not None:
            d.dtype = self.dtype
        if self.shape is not None:
            d.shape.extend(self.shape)
        d.persistable = self.persistable
        d.stop_gradient = self.stop_gradient
        d.is_parameter = self.is_parameter
        d.trainable = self.trainable
        return d

    def __repr__(self):
        return (
            f"Var({self.name}, shape={self.shape}, dtype={self.dtype}"
            + (", persistable" if self.persistable else "")
            + (", stop_gradient" if self.stop_gradient else "")
            + ")"
        )

    __str__ = __repr__

    # numpy-style conveniences used by model code
    @property
    def ndim(self):
        return len(self.shape) if self.shape is not None else None

    def astype(self, dtype):
        from paddle_tpu import layers

        return layers.cast(self, dtype)

    def _binary(self, other, op, reverse=False):
        from paddle_tpu import layers

        if not isinstance(other, Variable):
            other = layers.fill_constant(
                shape=[1], dtype=self.dtype, value=float(other)
            )
        a, b = (other, self) if reverse else (self, other)
        return layers.elementwise_op(op, a, b)

    def __add__(self, o):
        return self._binary(o, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "elementwise_sub")

    def __rsub__(self, o):
        return self._binary(o, "elementwise_sub", reverse=True)

    def __mul__(self, o):
        return self._binary(o, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "elementwise_div")

    def __rtruediv__(self, o):
        return self._binary(o, "elementwise_div", reverse=True)

    def __neg__(self):
        from paddle_tpu import layers

        return layers.scale(self, scale=-1.0)


class Parameter(Variable):
    """A trainable persistable variable (reference: framework.py:3481)."""

    def __init__(self, block, name, shape, dtype, **kwargs):
        self.initializer = kwargs.pop("initializer", None)
        self.regularizer = kwargs.pop("regularizer", None)
        self.optimize_attr = kwargs.pop("optimize_attr", {"learning_rate": 1.0})
        self.do_model_average = kwargs.pop("do_model_average", None)
        trainable = kwargs.pop("trainable", True)
        super().__init__(
            block,
            name,
            shape=shape,
            dtype=dtype,
            persistable=True,
            stop_gradient=not trainable,
            is_parameter=True,
            trainable=trainable,
            **kwargs,
        )


class Operator:
    """One op invocation: type + slot-keyed inputs/outputs + attrs
    (reference: framework.py:925)."""

    def __init__(
        self,
        block: "Block",
        type: str,
        inputs: Optional[Dict[str, Any]] = None,
        outputs: Optional[Dict[str, Any]] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ):
        self.block = block
        self.type = type
        self.inputs: Dict[str, List[str]] = _normalize_slots(inputs)
        self.outputs: Dict[str, List[str]] = _normalize_slots(outputs)
        self.attrs: Dict[str, Any] = dict(attrs or {})

    def input(self, slot: str) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> List[str]:
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self) -> List[str]:
        return [n for ns in self.inputs.values() for n in ns]

    @property
    def output_arg_names(self) -> List[str]:
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)

    def _set_attr(self, name: str, val):
        self.attrs[name] = val

    def to_proto(self) -> pb.OpDesc:
        d = pb.OpDesc(type=self.type)
        for slot, names in self.inputs.items():
            v = d.inputs.add()
            v.parameter = slot
            v.arguments.extend(names)
        for slot, names in self.outputs.items():
            v = d.outputs.add()
            v.parameter = slot
            v.arguments.extend(names)
        for k, val in self.attrs.items():
            a = d.attrs.add()
            a.name = k
            _attr_to_proto(a, val)
        return d

    def __repr__(self):
        ins = ", ".join(f"{s}={n}" for s, n in self.inputs.items())
        outs = ", ".join(f"{s}={n}" for s, n in self.outputs.items())
        return f"{{{outs}}} = {self.type}({ins}) attrs={self.attrs}"


def _normalize_slots(slots) -> Dict[str, List[str]]:
    out: Dict[str, List[str]] = {}
    for slot, v in (slots or {}).items():
        if v is None:
            continue
        if isinstance(v, (Variable, str)):
            v = [v]
        names = [x.name if isinstance(x, Variable) else str(x) for x in v]
        if names:
            out[slot] = names
    return out


def _attr_to_proto(a: pb.OpDesc.Attr, val):
    if isinstance(val, bool):
        a.type, a.b = pb.BOOLEAN, val
    elif isinstance(val, int):
        a.type, a.l = pb.LONG, val
    elif isinstance(val, float):
        a.type, a.float64 = pb.FLOAT64, val
    elif isinstance(val, str):
        a.type, a.s = pb.STRING, val
    elif isinstance(val, Block):
        a.type, a.block_idx = pb.BLOCK, val.idx
    elif isinstance(val, (list, tuple)):
        if all(isinstance(x, bool) for x in val) and val:
            a.type = pb.BOOLEANS
            a.bools.extend(val)
        elif all(isinstance(x, int) for x in val):
            a.type = pb.LONGS
            a.longs.extend(val)
        elif all(isinstance(x, float) for x in val):
            a.type = pb.FLOATS
            a.floats.extend(float(x) for x in val)
        elif all(isinstance(x, str) for x in val):
            a.type = pb.STRINGS
            a.strings.extend(val)
        elif all(isinstance(x, Block) for x in val):
            a.type = pb.BLOCKS
            a.blocks_idx.extend(b.idx for b in val)
        else:
            raise TypeError(f"unsupported list attr {val!r}")
    else:
        raise TypeError(f"unsupported attr {val!r} ({type(val)})")


def _attr_from_proto(a: pb.OpDesc.Attr, program: "Program"):
    t = a.type
    if t == pb.BOOLEAN:
        return a.b
    if t == pb.LONG:
        return int(a.l)
    if t == pb.INT:
        return int(a.i)
    if t == pb.FLOAT:
        return float(a.f)
    if t == pb.FLOAT64:
        return float(a.float64)
    if t == pb.STRING:
        return a.s
    if t == pb.BLOCK:
        return program.blocks[a.block_idx]
    if t == pb.BOOLEANS:
        return list(a.bools)
    if t == pb.LONGS:
        return [int(x) for x in a.longs]
    if t == pb.INTS:
        return [int(x) for x in a.ints]
    if t == pb.FLOATS:
        return [float(x) for x in a.floats]
    if t == pb.STRINGS:
        return list(a.strings)
    if t == pb.BLOCKS:
        return [program.blocks[i] for i in a.blocks_idx]
    raise TypeError(f"unsupported proto attr type {t}")


def _canonical_attr_bytes(val) -> bytes:
    """Deterministic cross-process rendering of one op attr for
    Program.content_digest. Blocks render as their index (the block
    content itself is digested in block order), arrays as a data digest,
    floats via repr (full precision)."""
    if isinstance(val, Block):
        return f"block:{val.idx}".encode()
    if isinstance(val, np.ndarray):
        return (f"ndarray:{val.shape}:{val.dtype}:"
                f"{hashlib.sha256(np.ascontiguousarray(val).tobytes()).hexdigest()[:16]}"
                ).encode()
    if isinstance(val, (list, tuple)):
        return b"[" + b",".join(_canonical_attr_bytes(x) for x in val) + b"]"
    return repr(val).encode()  # floats via repr: full precision


class Block:
    """An ordered op list + var table (reference: framework.py:1370)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent_block(self) -> Optional["Block"]:
        return None if self.parent_idx < 0 else self.program.blocks[self.parent_idx]

    # --- variables ---

    def create_var(self, name: Optional[str] = None, **kwargs) -> Variable:
        if name is None:
            name = unique_name.generate("tmp")
        if name in self.vars:
            return self.vars[name]
        v = Variable(self, name, **kwargs)
        self.vars[name] = v
        return v

    def create_parameter(self, name, shape, dtype, **kwargs) -> Parameter:
        p = Parameter(self, name, shape, dtype, **kwargs)
        self.vars[name] = p
        return p

    def var(self, name: str) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise KeyError(f"variable '{name}' not found in block {self.idx}")
        return v

    def has_var(self, name: str) -> bool:
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name: str) -> Optional[Variable]:
        b: Optional[Block] = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = b.parent_block
        return None

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # --- ops ---

    def append_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        self.program._bump_version()
        self._infer_shapes(op)
        return op

    def _prepend_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self.program._bump_version()
        self._infer_shapes(op)
        return op

    def _infer_shapes(self, op: Operator):
        """Abstract-eval the kernel to fill output var shapes/dtypes."""
        outs, gap = infer_op_outputs(self, op)
        if outs is None:
            # Previously a silent no-op: ops with no registered shape
            # function (or missing input metadata) left their outputs
            # shapeless with no signal. Record the gap so the static
            # verifier (analysis.py) can report inference coverage
            # honestly, and log once per (op_type, kind) at debug level.
            if gap is not None:
                _note_infer_gap(op.type, gap)
            return
        try:
            apply_inferred_outputs(self, op, outs)
        except Exception as e:
            # a kernel returning a malformed result structure must stay
            # an advisory gap (real shapes resolve at lowering), not a
            # build abort
            _note_infer_gap(op.type,
                            f"eval_failed:{type(e).__name__}: {e}")

    def to_proto(self) -> pb.BlockDesc:
        d = pb.BlockDesc(idx=self.idx, parent_idx=self.parent_idx)
        for v in self.vars.values():
            d.vars.append(v.to_proto())
        for op in self.ops:
            d.ops.append(op.to_proto())
        return d

    def __repr__(self):
        lines = [f"block {self.idx} (parent {self.parent_idx}):"]
        lines += [f"  {v}" for v in self.vars.values()]
        lines += [f"  {op}" for op in self.ops]
        return "\n".join(lines)


# (op_type, gap kind) pairs where abstract shape inference could not run
# — the coverage ledger behind analysis.py's debug-level findings. Kinds:
# 'no_kernel' (op type has no registered compute), 'missing_input_meta'
# (an input var lacks shape/dtype), 'eval_failed:<Error>' (the abstract
# eval itself raised). Bounded by the op-type vocabulary.
_SHAPE_INFER_GAPS: set = set()


def shape_infer_gaps() -> set:
    """Snapshot of recorded inference-coverage gaps (see above)."""
    return set(_SHAPE_INFER_GAPS)


def _note_infer_gap(op_type: str, gap: str):
    # ledger + once-per-signature dedup key on the 'eval_failed:<Type>'
    # prefix; the logged line keeps the full diagnostic message
    sig = (op_type, gap.split(": ", 1)[0])
    if sig in _SHAPE_INFER_GAPS:
        return
    _SHAPE_INFER_GAPS.add(sig)
    import logging

    log = logging.getLogger("paddle_tpu")
    if gap.startswith("eval_failed"):
        # a raising kernel is build-time breakage worth a warning
        log.warning(
            "shape inference failed for op '%s': %s "
            "(advisory; real shapes resolved at lowering)", op_type, gap)
    else:
        log.debug("shape inference skipped for op '%s': %s", op_type, gap)


def infer_op_outputs(block: "Block", op: Operator):
    """Abstract-eval ``op``'s kernel over the block's declared metadata.

    Returns ``(outs, gap)``: ``outs`` maps output slot -> list of
    ShapeDtypeStructs (``None`` when inference could not run, with
    ``gap`` naming why — see ``_SHAPE_INFER_GAPS`` kinds). Shared by
    ``Block._infer_shapes`` (build-time advisory fill) and the static
    verifier's whole-program shape/dtype re-check (analysis.py), so the
    two can never disagree about an op's inferred metadata."""
    if not has_op(op.type):
        if op.type.endswith(GRAD_OP_SUFFIX) and \
                has_op(op.type[: -len(GRAD_OP_SUFFIX)]):
            # derived at lowering by autodiff from the forward kernel;
            # shapes mirror the differentiated inputs
            return None, "autodiff_grad"
        return None, "no_kernel"
    opdef = get_op_def(op.type)
    try:
        import jax

        ins = {}
        for slot, names in op.inputs.items():
            specs = []
            for n in names:
                v = block._find_var_recursive(n)
                if v is None or v.shape is None or v.dtype is None:
                    return None, "missing_input_meta"
                shape = tuple(
                    _BATCH_SENTINEL if d == -1 else d for d in v.shape
                )
                specs.append(jax.ShapeDtypeStruct(shape, np.dtype(v.dtype)))
            ins[slot] = specs

        kwargs = {}
        if opdef.needs_rng:
            kwargs["rng"] = jax.random.PRNGKey(0)

        outs = jax.eval_shape(
            lambda i: opdef.compute(i, dict(op.attrs), **kwargs), ins
        )
        return outs, None
    except Exception as e:
        # the message carries the real diagnostic (broadcast shapes,
        # bad attr, ...); _note_infer_gap dedups on the prefix only
        return None, f"eval_failed:{type(e).__name__}: {e}"


def apply_inferred_outputs(block: "Block", op: Operator, outs) -> None:
    """Write ``infer_op_outputs`` results back into the block's var
    metadata (slot -> list of ShapeDtypeStructs, extra/None entries
    skipped). Raises on malformed kernel results — callers decide
    whether that is advisory (``Block._infer_shapes``) or a reportable
    coverage gap (analysis.py)."""
    for slot, names in op.outputs.items():
        results = outs.get(slot, [])
        for n, r in zip(names, results):
            if r is None:
                continue
            v = block._find_var_recursive(n)
            if v is None:
                v = block.create_var(name=n)
            v.shape = tuple(
                -1 if d == _BATCH_SENTINEL else d for d in r.shape
            )
            v.dtype = np.dtype(r.dtype).name


class Program:
    """A list of blocks; block 0 is global (reference: framework.py:2705)."""

    _uid_counter = 0

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0, -1)]
        self.current_block_idx = 0
        # Monotonic global uid: executor cache keys use this instead of
        # id() (id reuse after GC could alias a stale compiled entry).
        Program._uid_counter += 1
        self._uid = Program._uid_counter
        self._version = 0
        self.random_seed: Optional[int] = None
        # bf16 mixed-precision execution flag (see paddle_tpu/amp.py)
        self._amp = False
        # populated by append_backward: {param_name: grad_name}
        self._param_grad_map: Dict[str, str] = {}
        # version-keyed def-use index cache (analysis.DefUseIndex per
        # block); every _bump_version invalidates it implicitly
        self._def_use_cache: Optional[tuple] = None
        # version-keyed content digest cache (content_digest below)
        self._content_digest_cache: Optional[tuple] = None

    def _bump_version(self):
        self._version += 1

    @property
    def version(self) -> int:
        return self._version

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def _create_block(self, parent_idx: Optional[int] = None) -> Block:
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        return b

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def all_parameters(self) -> List[Parameter]:
        return [v for b in self.blocks for v in b.all_parameters()]

    def def_use_index(self) -> Dict[int, Any]:
        """{block idx -> analysis.DefUseIndex} for the whole program,
        cached on the program and invalidated by any version bump (op
        append/rewrite). The shared substrate every static-verifier
        check walks (analysis.py) — and available to passes that want a
        prebuilt writer/reader map instead of hand-rolling one."""
        if (self._def_use_cache is None
                or self._def_use_cache[0] != self._version):
            from paddle_tpu import analysis

            self._def_use_cache = (
                self._version, analysis.build_def_use(self))
        return self._def_use_cache[1]

    def content_digest(self) -> str:
        """sha256 hex digest of the program CONTENT — blocks, vars, op
        list with slot-keyed args and canonicalized attrs, random_seed —
        with no process-local identity (uids, ids) mixed in, so two
        identically-built programs in two different processes digest
        identically. Cached per version (any op append/rewrite bumps the
        version and invalidates). The canonical program token of
        ``compile_cache.program_fingerprint``."""
        cache = self._content_digest_cache
        if cache is not None and cache[0] == self._version:
            return cache[1]
        h = hashlib.sha256()
        h.update(repr(self.random_seed).encode())
        for b in self.blocks:
            h.update(f"B{b.idx}:{b.parent_idx}".encode())
            for name in sorted(b.vars):
                v = b.vars[name]
                h.update(repr((
                    name, v.shape, str(v.dtype), bool(v.persistable),
                    bool(v.stop_gradient), bool(v.is_parameter),
                    v.kind,
                )).encode())
            for op in b.ops:
                h.update(op.type.encode())
                h.update(repr(sorted(op.inputs.items())).encode())
                h.update(repr(sorted(op.outputs.items())).encode())
                for k in sorted(op.attrs):
                    h.update(k.encode())
                    h.update(_canonical_attr_bytes(op.attrs[k]))
        digest = h.hexdigest()
        self._content_digest_cache = (self._version, digest)
        return digest

    # --- serialization ---

    def to_proto(self) -> pb.ProgramDesc:
        d = pb.ProgramDesc(version=self._version)
        if self.random_seed is not None:
            d.random_seed = self.random_seed
        for b in self.blocks:
            d.blocks.append(b.to_proto())
        return d

    def desc_str(self) -> bytes:
        return self.to_proto().SerializeToString()

    @staticmethod
    def from_proto(d: pb.ProgramDesc) -> "Program":
        p = Program()
        p.blocks = []
        for bd in d.blocks:
            p.blocks.append(Block(p, bd.idx, bd.parent_idx))
        for bd, b in zip(d.blocks, p.blocks):
            for vd in bd.vars:
                shape = tuple(vd.shape) if vd.shape else None
                kw = dict(
                    shape=shape,
                    dtype=vd.dtype or None,
                    persistable=vd.persistable,
                    stop_gradient=vd.stop_gradient,
                    trainable=vd.trainable,
                    kind=vd.kind,
                )
                if vd.is_parameter:
                    b.create_parameter(
                        vd.name,
                        shape,
                        vd.dtype or "float32",
                        trainable=vd.trainable,
                    )
                else:
                    b.create_var(name=vd.name, **kw)
            for od in bd.ops:
                op = Operator(
                    b,
                    od.type,
                    inputs={v.parameter: list(v.arguments) for v in od.inputs},
                    outputs={v.parameter: list(v.arguments) for v in od.outputs},
                    attrs={a.name: _attr_from_proto(a, p) for a in od.attrs},
                )
                b.ops.append(op)
        p._version = d.version
        if d.HasField("random_seed"):
            p.random_seed = d.random_seed
        return p

    @staticmethod
    def parse_from_string(s: bytes) -> "Program":
        d = pb.ProgramDesc()
        d.ParseFromString(s)
        return Program.from_proto(d)

    def clone(self, for_test: bool = False) -> "Program":
        p = Program.parse_from_string(self.desc_str())
        p._param_grad_map = dict(self._param_grad_map)
        p._amp = self._amp
        if for_test:
            for b in p.blocks:
                for op in b.ops:
                    if "is_test" in op.attrs:
                        op.attrs["is_test"] = True
                    if op.type == "dropout":
                        op.attrs["is_test"] = True
                    if op.type == "batch_norm":
                        op.attrs["is_test"] = True
        p._bump_version()
        return p

    def __repr__(self):
        return "\n".join(repr(b) for b in self.blocks)

    __str__ = __repr__


# --- default programs & guards (reference: framework.py:3574-3650) ---

_main_program_ = Program()
_startup_program_ = Program()


def default_main_program() -> Program:
    return _main_program_


def default_startup_program() -> Program:
    return _startup_program_


def switch_main_program(program: Program) -> Program:
    global _main_program_
    old, _main_program_ = _main_program_, program
    return old


def switch_startup_program(program: Program) -> Program:
    global _startup_program_
    old, _startup_program_ = _startup_program_, program
    return old


class program_guard:
    def __init__(self, main_program: Program, startup_program: Optional[Program] = None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        self.old_main = switch_main_program(self.main)
        if self.startup is not None:
            self.old_startup = switch_startup_program(self.startup)
        return self

    def __exit__(self, *exc):
        switch_main_program(self.old_main)
        if self.startup is not None:
            switch_startup_program(self.old_startup)
        return False


import contextlib


@contextlib.contextmanager
def name_scope(prefix: str):
    """Cosmetic name scoping for debugging/profiling."""
    yield


# Simple device "places" for API parity (reference: platform/place.h:79).
# Actual placement is JAX device assignment; these select default device kind.
class CPUPlace:
    def __repr__(self):
        return "CPUPlace"


class TPUPlace:
    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def __repr__(self):
        return f"TPUPlace({self.device_id})"


# Alias so reference-style `fluid.CUDAPlace(0)` code keeps working on TPU.
CUDAPlace = TPUPlace


def in_dygraph_mode() -> bool:
    from paddle_tpu.dygraph import base as dygraph_base

    return dygraph_base._in_dygraph_mode()
