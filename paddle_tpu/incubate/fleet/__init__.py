"""Fleet distributed-training façade
(reference: python/paddle/fluid/incubate/fleet/)."""

from paddle_tpu.incubate.fleet.fleet_base import (  # noqa: F401
    DistributedOptimizer,
    Fleet,
    fleet,
)
from paddle_tpu.incubate.fleet.role_maker import (  # noqa: F401
    EnvRoleMaker,
    RoleMakerBase,
    UserDefinedRoleMaker,
)
