"""Fleet: the multi-host training façade
(reference: incubate/fleet/base/fleet_base.py — fleet.init / init_worker /
distributed_optimizer / stop_worker; collective mode
incubate/fleet/collective/__init__.py).

TPU-native bootstrap (replaces the reference's gen_nccl_id RPC exchange,
operators/distributed_ops/gen_nccl_id_op.cc:62):

1. rank 0 starts the native CoordServer (csrc/coord.cc: KV + barrier +
   heartbeat over one TCP port);
2. every worker connects a CoordClient, rendezvouses (KV put/get of the
   PJRT coordinator address), and barriers;
3. ``jax.distributed.initialize`` brings up the PJRT distributed runtime —
   after which ``jax.devices()`` is the GLOBAL device list and GSPMD
   programs span all hosts (collectives ride ICI/DCN, not RPC).

After init, ``fleet.mesh(...)`` builds global meshes and
``fleet.compiled_program(main)`` wraps a Program for global
data parallelism; per-step liveness goes through heartbeat/dead_workers
(SURVEY.md section 5 failure detection).
"""

from __future__ import annotations

import atexit
import json as _json
import os as _os
import re as _re
import sys as _sys
import time as _time
from typing import List, Optional, Sequence

import numpy as np

from paddle_tpu import faults as _faults
from paddle_tpu import fleet_monitor as _fleet_monitor
from paddle_tpu import monitor as _monitor
from paddle_tpu import retry as _retry
from paddle_tpu.incubate.fleet.role_maker import (
    EnvRoleMaker,
    RoleMakerBase,
)

# Barrier waits are THE multi-host stall signal (a slow rank shows up as
# everyone else's barrier time); rendezvous counts > 1 mean the job
# re-formed its world (failure recovery re-rendezvous).
_M_BARRIER_WAIT = _monitor.histogram(
    "pt_fleet_barrier_wait_seconds",
    "time spent waiting in fleet barriers, by barrier name")
_M_RENDEZVOUS = _monitor.counter(
    "pt_fleet_rendezvous_total",
    "successful multi-worker rendezvous (>1 per process = recovery)")
_M_DEAD_EVENTS = _monitor.counter(
    "pt_fleet_dead_worker_events_total",
    "barrier_or_dead returns that reported dead peers")
_M_RESIZES = _monitor.counter(
    "pt_fleet_resizes_total",
    "elastic world resizes launched (re-exec to generation N+1), by "
    "direction: shrink = survivors of dead-worker detection, grow = a "
    "world admitting announced joiners")
_M_JOIN_SECONDS = _monitor.histogram(
    "pt_fleet_join_seconds",
    "scale-out admission latency on the JOINER: announce over the "
    "running world's KV -> leader plan adopted + acked (join_world)")

# chaos hooks: armed plans fail/delay the Nth coordination RPC, so the
# retry policy's behavior is reproducibly testable (faults.py docstring)
_F_CONNECT = _faults.site("fleet.connect")
_F_KV_GET = _faults.site("fleet.kv_get")
_F_KV_PUT = _faults.site("fleet.kv_put")
_F_HEARTBEAT = _faults.site("fleet.heartbeat")
_F_RESIZE = _faults.site("fleet.resize")
_F_JOIN = _faults.site("fleet.join")

# join announcements live in numbered KV slots (fleet/join/g<gen>/<id>);
# the probe scans this many — a resize event admitting more than 64
# hosts at once should land as two resizes
_JOIN_SLOT_CAP = 64

# heartbeats are fired from poll loops — a few quick retries beat a long
# backoff that would itself age the heartbeat past max_age_ms
_HEARTBEAT_POLICY = _retry.RetryPolicy(
    base_delay=0.05, max_delay=0.5, max_attempts=3, retry_on=(OSError,))


def resize_direction(spec: dict) -> str:
    """The ``pt_fleet_resizes_total`` direction label for a
    ``plan_resize`` spec: ``grow`` whenever the resize ADMITS joiners
    (matching the metric's documented meaning — a composed replacement
    resize that loses as many dead ranks as it admits is still an
    admission event, and its join latency already metered), ``shrink``
    otherwise."""
    return "grow" if spec.get("joiners") else "shrink"


def _barrier_label(name: str) -> str:
    """Bounded label cardinality: callers bake step/generation numbers
    into barrier names (e.g. 'step3-g1' in the recovery protocol), and a
    fresh histogram cell per training step would grow the registry and
    the Prometheus export without bound. Digit runs collapse to '*'."""
    return _re.sub(r"\d+", "*", name)


class Fleet:
    def __init__(self):
        self._role: Optional[RoleMakerBase] = None
        self._server = None
        self._client = None
        self._initialized = False
        self._done_barriers: list = []
        self._barrier_seq = 0

    # --- lifecycle (reference: fleet_base.py init/init_worker) ---

    def init(self, role_maker: Optional[RoleMakerBase] = None,
             connect_timeout_ms: Optional[int] = None):
        """Rendezvous + distributed runtime init. Single-worker jobs
        (worker_num == 1) need no endpoints and become a no-op.
        ``connect_timeout_ms`` defaults to the ``rpc_deadline_ms`` flag.

        ``PT_COORD_ONLY=1`` skips ``jax.distributed.initialize`` —
        coordination-only fleets: the coord service, KV, barriers,
        heartbeats, elastic resize and the commit barrier all come up,
        but each process keeps its own single-process jax world. For
        jobs whose compute is per-process (replicated smoke drills on
        backends that cannot form a cross-process XLA world, host-side
        parameter servers), and what gives every rank the SAME device
        identity — the condition under which the persistent compile
        cache's local entries are shareable fleet-wide."""
        if self._initialized:
            return self
        if connect_timeout_ms is None:
            from paddle_tpu import flags as _flags

            connect_timeout_ms = _flags.get_flag("rpc_deadline_ms")
        self._role = role_maker or EnvRoleMaker()
        n = self._role.worker_num()
        if n > 1:
            from paddle_tpu import native

            endpoint = self._role.coord_endpoint()
            if not endpoint:
                raise ValueError(
                    "multi-worker fleet.init needs a coordination endpoint "
                    "(PT_COORD_ENDPOINT=host:port)"
                )
            host, port = endpoint.rsplit(":", 1)
            port = int(port)
            with _monitor.span("fleet.rendezvous"), \
                    _monitor.stall_guard("fleet.rendezvous"):
                if self._role.is_first_worker():
                    self._server = native.CoordServer(port)
                # workers retry-connect until rank 0's server is up
                self._client = _connect_retry(host, port,
                                              connect_timeout_ms)

                jax_ep = (self._role.jax_coord_endpoint()
                          or f"{host}:{port + 1}")
                if self._role.is_first_worker():
                    self.put("fleet/jax_coordinator", jax_ep.encode())
                else:
                    jax_ep = _kv_get_retry(
                        self._client, "fleet/jax_coordinator",
                        connect_timeout_ms,
                    ).decode()
                self._client.barrier("fleet/rendezvous", n)
                if self._role.is_first_worker():
                    # late joiners read the running world's generation
                    # here before announcing (join_world); a world that
                    # never published it is generation 0
                    self.put("fleet/generation",
                             str(self.generation()).encode())

                if _os.environ.get("PT_COORD_ONLY") != "1":
                    import jax

                    jax.distributed.initialize(
                        jax_ep,
                        num_processes=n,
                        process_id=self._role.worker_index(),
                    )
            _M_RENDEZVOUS.inc()
            # register with the fleet observability plane: the /fleet
            # route aggregates through this client (each worker also
            # re-attaches on its first digest publish)
            _fleet_monitor.attach(self)
            atexit.register(self.stop_worker)
        # tag this process's trace exports with its rank so
        # monitor.merge_traces lands each worker's events on its own
        # track (single-worker jobs stay rank 0)
        _monitor.set_trace_rank(self._role.worker_index())
        self._initialized = True
        return self

    def stop_worker(self):
        self._done_barriers = []
        if self._client is not None:
            try:
                self._client.close()
            except OSError:
                pass
            self._client = None
        if self._server is not None:
            self._server.stop()
            self._server = None
        self._initialized = False

    # --- identity ---

    def worker_index(self) -> int:
        return self._role.worker_index() if self._role else 0

    def worker_num(self) -> int:
        return self._role.worker_num() if self._role else 1

    def is_first_worker(self) -> bool:
        return self.worker_index() == 0

    # --- collective helpers ---

    def barrier(self, name: str = "fleet/barrier"):
        if self._client is not None:
            # span and observe are both self-gating: with only the
            # profiler on this still lands in the chrome trace, with
            # only telemetry on it still feeds the histogram
            t0 = _time.perf_counter()
            with _monitor.span("fleet.barrier"), \
                    _monitor.stall_guard("fleet.barrier"):
                self._client.barrier(name, self.worker_num())
            _M_BARRIER_WAIT.observe(_time.perf_counter() - t0,
                                    labels={"barrier": _barrier_label(name)})

    def put(self, key: str, value: bytes):
        if self._client is None:
            raise RuntimeError("fleet.init with multiple workers first")
        from paddle_tpu import flags as _flags

        client = self._client

        def _once():
            _F_KV_PUT.hit()
            client.put(key, value)

        _retry.call(_once, site="fleet.kv_put", retry_on=(OSError,),
                    deadline_s=_flags.get_flag("rpc_deadline_ms") / 1000.0)

    def get(self, key: str, timeout_ms: Optional[int] = None) -> bytes:
        if self._client is None:
            raise RuntimeError("fleet.init with multiple workers first")
        if timeout_ms is None:
            from paddle_tpu import flags as _flags

            timeout_ms = _flags.get_flag("rpc_deadline_ms")
        # a blocked KV get is the classic "peer never published its key"
        # hang (e.g. waiting out a partner's multi-minute first compile)
        with _monitor.stall_guard("fleet.kv_get"):
            return _kv_get_retry(self._client, key, timeout_ms)

    # --- failure detection (SURVEY.md section 5) ---

    def heartbeat(self):
        if self._client is not None:
            client = self._client
            me = self.worker_index()

            def _once():
                _F_HEARTBEAT.hit()
                client.heartbeat(f"worker-{me}")

            _retry.call(_once, site="fleet.heartbeat",
                        policy=_HEARTBEAT_POLICY)
            if _monitor.enabled():
                # fleet observability: the registry digest rides the
                # heartbeat cadence (rate-limited inside by the
                # fleet_metrics_interval_ms flag); with telemetry off
                # this whole plane costs the one boolean check above
                _fleet_monitor.maybe_publish(self)

    def dead_workers(self, max_age_ms: int = 30_000) -> Sequence[str]:
        if self._client is None:
            return []
        return self._client.dead_peers(max_age_ms)

    def barrier_or_dead(self, name: str, max_age_ms: int = 5_000,
                        poll_ms: int = 100,
                        timeout_ms: int = 120_000) -> Sequence[str]:
        """Liveness-guarded barrier — the collective-timeout analog of
        the reference's grpc deadline on sync barriers. Arrive at
        ``name``, then wait until EITHER every worker has arrived
        (returns []) OR some worker's heartbeat ages past
        ``max_age_ms`` (returns the dead ids without blocking on them).
        Workers place this before each step's collectives so a peer
        crash surfaces as a recoverable signal instead of a hang in
        psum. The caller keeps heartbeating while it polls.

        CONTRACT: calls form a collective sequence — every worker must
        make its N-th call together (the same discipline any collective
        requires; epochs are keyed by call count). A TimeoutError is
        NOT retryable in place, and a replacement worker cannot join an
        existing world mid-sequence: both must go through a fresh
        rendezvous (new coord world), as the recovery protocol does."""
        if self._client is None:
            return []
        t_wait0 = _time.perf_counter()
        me = self.worker_index()
        # Epoch-keyed arrivals: every call gets this client's barrier
        # SEQUENCE NUMBER in the key. All workers reach their N-th
        # barrier_or_dead call together (the same SPMD contract any
        # collective requires), so the epoch matches across ranks — and
        # a reused name lands in a fresh epoch namespace, so a stale
        # arrive key from an earlier barrier can never satisfy a later
        # one. No reuse guard needed; names need not be unique.
        self._barrier_seq += 1
        tag = f"{self._barrier_seq}:{name}"
        # KV hygiene: reclaim MY arrive key from the OLDER of the last
        # two FULLY-completed barriers. Full completion of the newer one
        # required every peer to arrive there, hence to have LEFT the
        # older one — no live peer can still be polling the key being
        # deleted, however the peers' own returns happened. Dead-path
        # returns clear this history (no reclamation until two fresh
        # full completions), because a falsely-dead-but-alive straggler
        # may still be polling an older barrier whose keys it needs.
        if len(self._done_barriers) >= 2:
            old_tag = self._done_barriers.pop(0)
            try:
                self._client.delete(f"fleet/arrive/{old_tag}/{me}")
            except OSError:
                pass  # hygiene only; never fail the barrier for it
        self._client.put(f"fleet/arrive/{tag}/{me}", b"1")
        deadline = _time.monotonic() + timeout_ms / 1000.0
        # The watchdog fires well before timeout_ms (its deadline is the
        # stall_timeout_ms flag): a stall record with the span stack
        # beats staring at a silent poll loop for two minutes.
        with _monitor.stall_guard("fleet.barrier_or_dead"):
            while True:
                self._client.heartbeat(f"worker-{me}")
                missing = []
                for r in range(self.worker_num()):
                    if r == me:
                        continue
                    try:
                        self._client.get(f"fleet/arrive/{tag}/{r}",
                                         timeout_ms=0)
                    except TimeoutError:
                        missing.append(r)
                if not missing:
                    self._done_barriers.append(tag)
                    _M_BARRIER_WAIT.observe(
                        _time.perf_counter() - t_wait0,
                        labels={"barrier": _barrier_label(name)})
                    return []
                dead = list(self._client.dead_peers(max_age_ms))
                dead_missing = [d for d in dead
                                if any(d == f"worker-{r}" for r in missing)]
                if dead_missing:
                    self._done_barriers = []
                    _M_DEAD_EVENTS.inc()
                    _M_BARRIER_WAIT.observe(
                        _time.perf_counter() - t_wait0,
                        labels={"barrier": _barrier_label(name)})
                    return dead_missing
                if _time.monotonic() > deadline:
                    # the timeout IS the pathological wait this histogram
                    # exists to surface — record it before raising
                    _M_BARRIER_WAIT.observe(
                        _time.perf_counter() - t_wait0,
                        labels={"barrier": _barrier_label(name)})
                    raise TimeoutError(
                        f"barrier_or_dead {name!r}: workers {missing} "
                        f"neither arrived nor declared dead within "
                        f"{timeout_ms} ms")
                _time.sleep(poll_ms / 1000.0)

    # --- elastic resize (SURVEY.md section 5 recovery loop) ---

    def generation(self) -> int:
        """How many times this process's lineage re-rendezvoused (0 =
        the original world; ``reexec_resized`` bumps it via PT_GEN)."""
        return int(_os.environ.get("PT_GEN", "0"))

    def settle_dead(self, observed: Sequence = (),
                    max_age_ms: int = 5_000, poll_ms: int = 100,
                    timeout_ms: Optional[int] = None) -> Sequence[str]:
        """One AGREED dead set for every survivor. The liveness signal
        is not atomic: peers of the same crash cross the staleness
        threshold at different poll instants, so two survivors can
        return from ``barrier_or_dead`` with DIFFERENT partial dead sets
        — and would then derive different shrunk worlds and hang each
        other's recovery rendezvous. Each survivor keeps polling (and
        heartbeating, so survivors never mutually expire) until its
        accumulated dead set has been stable for one full staleness
        window; then the lowest-ranked survivor publishes its settled
        set over the KV (generation-keyed, so a later resize gets fresh
        keys) and every other survivor adopts the published set, acking
        the read so the leader never tears its coord server down under
        a peer still fetching. Assumes declared-dead workers stay dead
        (there is no mid-sequence rejoin; a falsely-stale-but-alive
        worker is excluded like a dead one and must re-enter through a
        fresh rendezvous)."""
        if self._client is None:
            return sorted(str(d) for d in observed)
        if timeout_ms is None:
            from paddle_tpu import flags as _flags

            timeout_ms = _flags.get_flag("rpc_deadline_ms")
        gen = self.generation()
        cur = {str(d) for d in observed}
        stable = 0.0
        with _monitor.stall_guard("fleet.settle_dead"):
            while stable < max_age_ms:
                self.heartbeat()
                _time.sleep(poll_ms / 1000.0)
                nxt = cur | set(self._client.dead_peers(max_age_ms))
                if nxt == cur:
                    stable += poll_ms
                else:
                    stable, cur = 0.0, nxt
            dead_ranks = {int(str(d).rsplit("-", 1)[-1]) for d in cur}
            survivors = [r for r in range(self.worker_num())
                         if r not in dead_ranks]
            if not survivors:
                raise ValueError(
                    f"settle_dead: every rank is stale ({sorted(cur)})")
            agreed = self._leader_adopt(
                f"fleet/resize/dead/g{gen}",
                f"fleet/resize/ack/g{gen}",
                ",".join(sorted(cur)).encode(),
                survivors[0], survivors[1:], timeout_ms)
            return sorted(x for x in agreed.decode().split(",") if x)

    def pending_joins(self, known: Sequence[int] = ()) -> List[int]:
        """Join ids announced against THIS generation: a non-blocking
        probe of the numbered join slots (``fleet/join/g<gen>/<id>``,
        ids 0..63). Incumbents poll this to notice newcomers; the
        settle/plan flow (``settle_joins`` -> ``plan_resize(joins=)``)
        turns the announcements into a grown world. Announcements never
        retract, so ``known`` ids are reported without re-probing —
        settle_joins passes its accumulated set, keeping each poll tick
        at (64 - seen) non-blocking gets instead of a fixed 64."""
        if self._client is None:
            return []
        gen = self.generation()
        out = list(known)
        for j in range(_JOIN_SLOT_CAP):
            if j in out:
                continue
            try:
                self._client.get(f"fleet/join/g{gen}/{j}", timeout_ms=0)
                out.append(j)
            except TimeoutError:
                continue  # slot not announced — the expected answer
            # any OTHER OSError propagates: a broken coord connection
            # must not read as "no joiners announced" (settle_joins
            # would agree on an EMPTY set and bump the generation while
            # the announced joiners hang)
        return sorted(out)

    def _leader_adopt(self, key: str, ack_prefix: str, payload: bytes,
                      leader: int, peers: Sequence[int],
                      timeout_ms: int) -> bytes:
        """The agreement tail ``settle_dead``/``settle_joins`` share:
        the LEADER (lowest surviving rank) publishes its settled
        payload under the generation-keyed ``key`` and collects one ack
        per surviving peer — so it never tears its coord server down
        under a peer still fetching — while every peer adopts the
        published payload and acks the read."""
        me = self.worker_index()
        if me == leader:
            self.put(key, payload)
            dl = _retry.Deadline(timeout_ms / 1000.0)
            for r in peers:
                self.get(f"{ack_prefix}/{r}",
                         timeout_ms=max(1, dl.remaining_ms()))
            return payload
        agreed = self.get(key, timeout_ms=timeout_ms)
        self.put(f"{ack_prefix}/{me}", b"1")
        return agreed

    def settle_joins(self, max_age_ms: int = 5_000, poll_ms: int = 100,
                     timeout_ms: Optional[int] = None,
                     min_count: int = 0,
                     dead: Sequence = ()) -> List[int]:
        """One AGREED joiner set for every surviving incumbent — the
        grow twin of ``settle_dead``. Join announcements are not atomic
        either: a scale-out event's hosts come up at different
        instants, so each incumbent keeps polling (and heartbeating)
        until the announced set has been stable for one full window AND
        holds at least ``min_count`` ids; then the lowest SURVIVING
        rank publishes its settled set over the KV (generation-keyed)
        and every other survivor adopts the published set, acking the
        read. ``dead`` (a ``settle_dead`` result) makes the composed
        shrink+grow resize work: the leader and the ack set are derived
        from the survivors, never from ranks that can no longer ack.
        Raises TimeoutError when ``min_count`` announcements never
        materialize inside ``timeout_ms``."""
        if self._client is None:
            return []
        if timeout_ms is None:
            from paddle_tpu import flags as _flags

            timeout_ms = _flags.get_flag("rpc_deadline_ms")
        gen = self.generation()
        dead_ranks = {int(str(d).rsplit("-", 1)[-1]) for d in dead}
        survivors = [r for r in range(self.worker_num())
                     if r not in dead_ranks]
        deadline = _time.monotonic() + timeout_ms / 1000.0
        cur: List[int] = []
        stable = 0.0
        with _monitor.stall_guard("fleet.settle_joins"):
            while stable < max_age_ms or len(cur) < min_count:
                self.heartbeat()
                _time.sleep(poll_ms / 1000.0)
                nxt = self.pending_joins(known=cur)
                if nxt == cur and len(cur) >= min_count:
                    stable += poll_ms
                else:
                    stable, cur = 0.0, nxt
                if _time.monotonic() > deadline:
                    raise TimeoutError(
                        f"settle_joins: {len(cur)} of {min_count} "
                        f"expected joiners announced within {timeout_ms} "
                        f"ms ({cur})")
            agreed = self._leader_adopt(
                f"fleet/resize/joins/g{gen}",
                f"fleet/resize/jsack/g{gen}",
                ",".join(str(j) for j in cur).encode(),
                survivors[0], survivors[1:], timeout_ms)
            return sorted(int(x) for x in agreed.decode().split(",")
                          if x)

    def plan_resize(self, dead_ids: Sequence, joins: Sequence = (),
                    rank: Optional[int] = None,
                    world: Optional[int] = None,
                    join_id: Optional[int] = None) -> dict:
        """Deterministic resized-world spec. Shrink: ``dead_ids``
        (``worker-<r>`` ids or plain ranks; pass them through
        ``settle_dead`` first so every survivor plans from the SAME
        set). Grow: ``joins`` (settled join ids from ``settle_joins``)
        — survivors keep their relative rank order and joiners take the
        ranks after them, in join-id order, so every participant
        derives the identical world from the same (dead, joins)
        agreement. A joiner passes ``join_id`` instead of ``rank`` to
        derive ITS new rank. Both compose: dead workers leave and fresh
        capacity arrives in one resize. Chaos plans can tear this step
        via the ``fleet.resize`` site (a raise here models a
        participant that fails during the resize decision).

        Returns ``{"survivors": [old ranks], "rank": my new rank,
        "world": new size, "dead": [dead old ranks]}`` plus
        ``"joiners": [[join id, new rank], ...]`` when growing.
        """
        _F_RESIZE.hit()
        world = self.worker_num() if world is None else int(world)
        dead = set()
        for d in dead_ids:
            if isinstance(d, int):
                dead.add(d)
            else:
                # "worker-3" and plain "3" both parse (settle_dead's
                # client-less fallback stringifies whatever it was fed)
                dead.add(int(str(d).rsplit("-", 1)[-1]))
        survivors = [r for r in range(world) if r not in dead]
        if not survivors:
            raise ValueError(f"resize with no survivors (dead: {sorted(dead)})")
        join_list = sorted(int(j) for j in joins)
        joiner_ranks = {j: len(survivors) + i
                        for i, j in enumerate(join_list)}
        if join_id is not None:
            if int(join_id) not in joiner_ranks:
                raise ValueError(
                    f"join_id {join_id} is not in the settled join set "
                    f"{join_list}; a joiner must announce and be settled "
                    f"before planning")
            new_rank = joiner_ranks[int(join_id)]
        else:
            rank = self.worker_index() if rank is None else int(rank)
            if rank not in survivors:
                raise ValueError(
                    f"rank {rank} is itself in the dead set "
                    f"{sorted(dead)}; a declared-dead worker must not "
                    f"plan the resize")
            new_rank = survivors.index(rank)
        spec = {"survivors": survivors, "rank": new_rank,
                "world": len(survivors) + len(join_list),
                "dead": sorted(dead)}
        if join_list:
            spec["joiners"] = [[j, joiner_ranks[j]] for j in join_list]
        return spec

    def publish_join_plan(self, spec: dict, coord_endpoint: str,
                          jax_endpoint: Optional[str] = None,
                          timeout_ms: Optional[int] = None):
        """Leader-only (rank 0): publish the grown-world plan — the
        joiners' half of the agreement, carrying their assigned ranks
        and the generation-N+1 recovery endpoints — then WAIT for every
        joiner's ack before returning. The leader owns the
        generation-N coord server and ``reexec_resized`` tears it down;
        returning before the acks would strand a joiner mid-read."""
        if timeout_ms is None:
            from paddle_tpu import flags as _flags

            timeout_ms = _flags.get_flag("rpc_deadline_ms")
        gen = self.generation()
        plan = {"survivors": spec["survivors"],
                "dead": spec.get("dead", []),
                "joiners": spec.get("joiners", []),
                "world": spec["world"], "gen": gen + 1,
                "coord": coord_endpoint, "jax": jax_endpoint}
        self.put(f"fleet/resize/plan/g{gen}",
                 _json.dumps(plan).encode())
        dl = _retry.Deadline(timeout_ms / 1000.0)
        for j, _r in spec.get("joiners", []):
            self.get(f"fleet/resize/jack/g{gen}/{j}",
                     timeout_ms=max(1, dl.remaining_ms()))

    def join_world(self, coord_endpoint: str, join_id: int,
                   connect_timeout_ms: Optional[int] = None,
                   timeout_ms: Optional[int] = None,
                   _client=None) -> dict:
        """NEWCOMER side of scale-OUT: connect to the RUNNING world's
        coord service, announce under the generation-keyed join slot,
        wait for the leader's published plan, ack it, and return the
        resize spec (rank/world/endpoints/generation) ready for
        ``reexec_resized``. The two ``fleet.join`` fault-site hits —
        before the announce and at plan adoption — let chaos plans tear
        an admission at either seam. Metered into
        ``pt_fleet_join_seconds`` (announce -> plan adopted)."""
        from paddle_tpu import flags as _flags

        if connect_timeout_ms is None:
            connect_timeout_ms = _flags.get_flag("rpc_deadline_ms")
        if timeout_ms is None:
            timeout_ms = _flags.get_flag("rpc_deadline_ms")
        if not 0 <= int(join_id) < _JOIN_SLOT_CAP:
            # an out-of-range slot would announce where pending_joins
            # never probes: a silent deterministic hang, not a join
            raise ValueError(
                f"join_id must be in [0, {_JOIN_SLOT_CAP}), got "
                f"{join_id}")
        t0 = _time.perf_counter()
        client = _client
        if client is None:
            host, port = coord_endpoint.rsplit(":", 1)
            client = _connect_retry(host, int(port), connect_timeout_ms)
        try:
            try:
                # bounded BLOCKING read: a newcomer can connect in the
                # window before rank 0's post-rendezvous publish, and a
                # wrong-generation announce lands in a slot nobody
                # probes. Worlds predating the key (which cannot settle
                # joins anyway) fall back to generation 0 at timeout.
                gen = int(_kv_get_retry(
                    client, "fleet/generation",
                    min(int(timeout_ms), 5_000)).decode())
            except (TimeoutError, OSError, ValueError):
                gen = 0
            _F_JOIN.hit()  # hit 1: the announce
            client.put(f"fleet/join/g{gen}/{int(join_id)}", b"1")
            with _monitor.stall_guard("fleet.join"):
                raw = _kv_get_retry(client, f"fleet/resize/plan/g{gen}",
                                    timeout_ms)
            plan = _json.loads(raw.decode())
            _F_JOIN.hit()  # hit 2: plan adoption
            joiner_ranks = {int(j): int(r)
                            for j, r in plan.get("joiners", [])}
            if int(join_id) not in joiner_ranks:
                raise ValueError(
                    f"join {join_id}: the leader's plan admitted only "
                    f"{sorted(joiner_ranks)}; this announcement landed "
                    f"after the join set settled — re-announce against "
                    f"the next generation")
            client.put(f"fleet/resize/jack/g{gen}/{int(join_id)}", b"1")
        finally:
            if _client is None:
                try:
                    client.close()
                except OSError:
                    pass
        dt = _time.perf_counter() - t0
        _M_JOIN_SECONDS.observe(dt)
        return {"survivors": plan["survivors"],
                "dead": plan.get("dead", []),
                "joiners": plan.get("joiners", []),
                "rank": joiner_ranks[int(join_id)],
                "world": int(plan["world"]),
                "gen": int(plan.get("gen", 1)),
                "coord_endpoint": plan.get("coord"),
                "jax_endpoint": plan.get("jax"),
                "join_latency_s": dt}

    def reexec_resized(self, spec: dict, coord_endpoint: str,
                       jax_endpoint: Optional[str] = None,
                       script: Optional[str] = None,
                       argv: Optional[Sequence[str]] = None,
                       extra_env: Optional[dict] = None):
        """Re-exec THIS process as generation N+1 of the shrunk world
        described by ``plan_resize``'s spec: rank/world/coordination
        endpoints land in the EnvRoleMaker env vars, PT_GEN increments,
        the coord connection closes, and the process image is replaced
        (``os.execve`` — no return). The restarted process's recovery
        path (e.g. Trainer auto-resume or ``checkpoint.load_latest``)
        then restores the newest valid checkpoint onto the NEW topology:
        manifest-v2 checkpoints reassemble and re-shard on any world
        shape, which is what makes this resize safe.

        The command line survives the re-exec: ``argv`` defaults to
        ``sys.argv[1:]``, so a job launched with flags restarts with the
        same flags (hyperparameters must not silently reset to defaults
        across generations). A ``python -m pkg.mod`` entrypoint re-runs
        as a plain script path — pass ``script``/``argv`` explicitly if
        your ``__main__`` relies on package-relative imports.

        Grown worlds: a JOINER re-execs through the same call with the
        spec ``join_world`` returned. Its env must be complete and
        self-consistent for ``EnvRoleMaker`` — rank/world from the
        spec, the generation from the PLAN (``spec["gen"]``, not this
        process's own generation + 1: a joiner's own is 0), and a stale
        inherited ``PT_JAX_COORD_ENDPOINT`` scrubbed when the caller
        passes none (it names the DEAD generation's PJRT coordinator;
        EnvRoleMaker's coord-host default is the correct one)."""
        env = dict(_os.environ)
        env.update({
            "PT_TRAINER_ID": str(spec["rank"]),
            "PT_TRAINERS": str(spec["world"]),
            "PT_COORD_ENDPOINT": coord_endpoint,
            "PT_GEN": str(int(spec.get("gen", self.generation() + 1))),
        })
        if jax_endpoint:
            env["PT_JAX_COORD_ENDPOINT"] = jax_endpoint
        else:
            env.pop("PT_JAX_COORD_ENDPOINT", None)
        if extra_env:
            env.update({k: str(v) for k, v in extra_env.items()})
        # direction derives from the SPEC, so survivors and joiners
        # meter identically (resize_direction is the one definition)
        _M_RESIZES.inc(labels={"direction": resize_direction(spec)})
        self.stop_worker()
        script = script or _os.path.abspath(_sys.argv[0])
        args = list(_sys.argv[1:] if argv is None else argv)
        _os.execve(_sys.executable, [_sys.executable, script] + args, env)

    # --- program compilation over the global mesh ---

    def mesh(self, shape: Optional[Sequence[int]] = None,
             axis_names: Sequence[str] = ("data",)):
        """A Mesh over ALL global devices (defaults to 1-D data mesh)."""
        import jax
        from jax.sharding import Mesh

        devs = np.asarray(jax.devices())
        if shape is not None:
            devs = devs.reshape(tuple(shape))
        return Mesh(devs, tuple(axis_names))

    def compiled_program(self, main_program, strategy=None):
        """Program -> CompiledProgram over the global device mesh; pass a
        DistributedStrategy for tp/sp/table sharding on top of dp."""
        from paddle_tpu.compiler import CompiledProgram

        if strategy is not None:
            return CompiledProgram(main_program).with_strategy(strategy)
        return CompiledProgram(main_program).with_data_parallel()

    def distributed_optimizer(self, optimizer, strategy=None):
        return DistributedOptimizer(self, optimizer, strategy)


class DistributedOptimizer:
    """Wraps an Optimizer for fleet jobs (reference: fleet_base.py
    DistributedOptimizer): minimize() is unchanged graph-side — data
    parallelism is a sharding of the SAME program, not a graph rewrite —
    and the fleet remembers the strategy for compiled_program()."""

    def __init__(self, fleet: Fleet, inner, strategy=None):
        self._fleet = fleet
        self._inner = inner
        self.strategy = strategy

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self._inner.minimize(
            loss, startup_program, parameter_list, no_grad_set
        )

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _connect_retry(host: str, port: int, timeout_ms: int):
    """Retry-connect under the unified policy (exponential backoff +
    decorrelated jitter, deadline budget) — replaces the fixed 0.1 s
    spin. Workers poll here until rank 0's server is up."""
    from paddle_tpu import native

    def _once():
        _F_CONNECT.hit()
        return native.CoordClient(host, port)

    return _retry.call(_once, site="fleet.connect", retry_on=(OSError,),
                       deadline_s=timeout_ms / 1000.0)


# between kv-get attempts the real waiting happens SERVER-side (the
# growing slice below) — the client-side sleep is kept tiny so a key
# published during a slice is served the instant it lands, not after a
# multi-second backoff nap
_KV_GAP_POLICY = _retry.RetryPolicy(
    base_delay=0.002, max_delay=0.02, retry_on=(OSError,))


def _kv_get_retry(client, key: str, timeout_ms: int) -> bytes:
    """KV get under the retry policy: server-side wait slices that grow
    exponentially from ``retry_base_delay_ms`` up to
    ``retry_max_delay_ms`` (instant wakeup when the key is published —
    the server holds the request), with only millisecond client-side
    gaps between attempts, raising TimeoutError once the overall
    ``timeout_ms`` budget is spent. ``timeout_ms`` < 0 = block forever
    (one server-side wait, no retry loop)."""
    from paddle_tpu import flags as _flags

    if timeout_ms is not None and timeout_ms <= 0:
        # -1 = block forever; 0 = one non-blocking present-check — both
        # are single passthrough calls, no retry loop (a 0 budget must
        # still ASK the server, not synthesize a timeout)
        _F_KV_GET.hit()
        return client.get(key, timeout_ms=int(timeout_ms))
    base_ms = max(1, _flags.get_flag("retry_base_delay_ms"))
    cap_ms = max(base_ms, _flags.get_flag("retry_max_delay_ms"))
    deadline = _time.monotonic() + timeout_ms / 1000.0
    state = {"slice": base_ms}

    def _once():
        _F_KV_GET.hit()
        remaining = deadline - _time.monotonic()
        if remaining <= 0:  # same float compare retry.call makes below
            raise TimeoutError(
                f"coord get {key!r}: {timeout_ms} ms budget spent")
        s = min(state["slice"], max(1, int(remaining * 1000)))
        state["slice"] = min(state["slice"] * 2, cap_ms)
        return client.get(key, timeout_ms=s)

    # the SAME absolute deadline governs _once's budget check and the
    # retry loop: when _once raises the budget-spent TimeoutError,
    # retry.call sees remaining <= 0 on the same clock and converts it
    # to a terminal 'exhausted' raise instead of one more retry cycle
    return _retry.call(
        _once, site="fleet.kv_get", retry_on=(OSError,),
        deadline_at=deadline, policy=_KV_GAP_POLICY,
    )


fleet = Fleet()
