"""Role makers: who am I in the distributed job
(reference: incubate/fleet/base/role_maker.py — MPISymetricRoleMaker /
UserDefinedRoleMaker / PaddleCloudRoleMaker).

TPU jobs have one role (worker); there is no parameter-server role because
tables shard over the mesh (SURVEY.md section 2.3). The env-driven maker
reads:

- ``PT_TRAINER_ID``     — this worker's rank (int)
- ``PT_TRAINERS``       — world size (int)
- ``PT_COORD_ENDPOINT`` — ``host:port`` of the rank-0 coordination service
- ``PT_JAX_COORD_ENDPOINT`` — optional ``host:port`` for the PJRT
  distributed runtime (defaults to the coord host with port+1)
"""

from __future__ import annotations

import os
from typing import Optional


class RoleMakerBase:
    def worker_index(self) -> int:
        raise NotImplementedError

    def worker_num(self) -> int:
        raise NotImplementedError

    def is_first_worker(self) -> bool:
        return self.worker_index() == 0

    def coord_endpoint(self) -> Optional[str]:
        return None

    def jax_coord_endpoint(self) -> Optional[str]:
        return None


class UserDefinedRoleMaker(RoleMakerBase):
    """Explicit rank/world/endpoints (reference: role_maker.py
    UserDefinedRoleMaker)."""

    def __init__(
        self,
        current_id: int,
        worker_num: int,
        coord_endpoint: Optional[str] = None,
        jax_coord_endpoint: Optional[str] = None,
    ):
        self._id = int(current_id)
        self._n = int(worker_num)
        self._coord = coord_endpoint
        self._jax_coord = jax_coord_endpoint

    def worker_index(self) -> int:
        return self._id

    def worker_num(self) -> int:
        return self._n

    def coord_endpoint(self):
        return self._coord

    def jax_coord_endpoint(self):
        return self._jax_coord


class EnvRoleMaker(UserDefinedRoleMaker):
    """Rank/world/endpoints from PT_* env vars (reference:
    PaddleCloudRoleMaker reading PADDLE_TRAINER_ID etc.)."""

    def __init__(self):
        super().__init__(
            current_id=int(os.environ.get("PT_TRAINER_ID", "0")),
            worker_num=int(os.environ.get("PT_TRAINERS", "1")),
            coord_endpoint=os.environ.get("PT_COORD_ENDPOINT"),
            jax_coord_endpoint=os.environ.get("PT_JAX_COORD_ENDPOINT"),
        )
