"""Inference predictor API (reference: paddle/fluid/inference/api/
analysis_predictor.cc:?, api/paddle_inference_api.h — AnalysisConfig +
AnalysisPredictor + create_paddle_predictor).

TPU-native design: the saved inference model (pruned Program + params,
io.save_inference_model) is loaded once into a private Scope; each
``run`` compiles the whole pruned block to one XLA executable per feed
signature (the Executor's compile cache replaces the reference's IR pass
manager + per-op execution), with optional bf16 inference in place of the
reference's TensorRT/int8 engines.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from paddle_tpu import io as _io
from paddle_tpu.executor import Executor, Scope, scope_guard
from paddle_tpu.framework import CPUPlace, TPUPlace


class Config:
    """Predictor configuration (reference: AnalysisConfig)."""

    def __init__(self, model_dir: str,
                 model_filename: Optional[str] = None,
                 params_filename: Optional[str] = None):
        self.model_dir = model_dir
        self.model_filename = model_filename
        self.params_filename = params_filename
        self._use_tpu = True
        self._use_bf16 = False

    def disable_tpu(self):
        self._use_tpu = False
        return self

    def enable_bf16(self):
        """bf16 inference (the TPU analog of the reference's fp16/TensorRT
        precision modes, contrib/float16 + inference/tensorrt)."""
        self._use_bf16 = True
        return self


class Predictor:
    """Compiled-program predictor (reference: AnalysisPredictor::Run)."""

    def __init__(self, config: Config):
        self._config = config
        self.scope = Scope()
        self._exe = Executor(
            TPUPlace(0) if config._use_tpu else CPUPlace()
        )
        with scope_guard(self.scope):
            if os.path.exists(os.path.join(config.model_dir,
                                           "__params_int8__.npz")):
                # int8 PTQ artifact (slim.calibration
                # save_int8_inference_model): quantizable-op weights
                # dequantize from the int8 snapshot, everything else
                # (BN stats, biases) loads fp32; the frozen program
                # carries the static-scale QDQ ops, so serving numerics
                # match int8 deployment through the same Predictor/C-ABI
                # surface as float artifacts.
                from paddle_tpu.slim.calibration import (
                    load_int8_inference_model,
                )

                self.program, self._feed_names, self._fetch_vars = (
                    load_int8_inference_model(
                        config.model_dir, self._exe, scope=self.scope)
                )
            else:
                self.program, self._feed_names, self._fetch_vars = (
                    _io.load_inference_model(
                        config.model_dir,
                        self._exe,
                        model_filename=config.model_filename,
                        params_filename=config.params_filename,
                    )
                )
        if config._use_bf16:
            self.program._amp = True

    # --- reference-parity surface ---

    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return [v.name for v in self._fetch_vars]

    def _as_feed(self, inputs) -> Dict[str, np.ndarray]:
        if isinstance(inputs, dict):
            feed = dict(inputs)
            missing = [n for n in self._feed_names if n not in feed]
            if missing:
                raise KeyError(f"missing inputs: {missing}")
            return feed
        if len(inputs) != len(self._feed_names):
            raise ValueError(
                f"expected {len(self._feed_names)} inputs "
                f"({self._feed_names}), got {len(inputs)}"
            )
        return dict(zip(self._feed_names, inputs))

    def run(
        self,
        inputs: Union[Sequence[np.ndarray], Dict[str, np.ndarray]],
    ) -> List[np.ndarray]:
        """Positional (aligned with get_input_names) or name-keyed feeds
        -> list of output arrays. Compiled executables are cached per
        feed signature; parameters stay device-resident in the
        predictor's private scope and round-trip through each call via
        buffer donation (XLA aliases the unchanged buffers, so no copy)."""
        feed = self._as_feed(inputs)
        with scope_guard(self.scope):
            return self._exe.run(
                self.program, feed=feed, fetch_list=self._fetch_vars
            )

    def warmup(self, inputs=None, shapes: Optional[Dict[str, tuple]] = None,
               dtypes: Optional[Dict[str, str]] = None):
        """Pre-compile (and prime the device) for a feed signature before
        serving traffic — the analog of the reference's warmup passes
        (AnalysisConfig warmup data for int8/TRT). Pass real sample
        ``inputs``, or ``shapes`` (+ optional ``dtypes``, default
        float32) to warm with zeros. Returns self."""
        if inputs is None:
            if not shapes:
                raise ValueError("warmup needs inputs or shapes")
            inputs = {
                n: np.zeros(shapes[n], np.dtype((dtypes or {}).get(
                    n, "float32")))
                for n in self._feed_names
            }
        self.run(inputs)
        return self

    def run_batch(
        self,
        inputs: Union[Sequence[np.ndarray], Dict[str, np.ndarray]],
        max_batch_size: int = 32,
    ) -> List[np.ndarray]:
        """Serve an arbitrary-size batch through FIXED-signature
        executables: the batch is split into ``max_batch_size`` chunks,
        the tail zero-padded to the chunk size, and results concatenated
        with the padding dropped. One compiled program serves every
        request size — the static-shape answer to the reference
        predictor's dynamic batching (no recompiles in steady state)."""
        feed = self._as_feed(inputs)
        n = next(iter(feed.values())).shape[0]
        if n == 0:
            raise ValueError("run_batch got an empty (0-row) batch")
        for k, v in feed.items():
            if v.shape[0] != n:
                raise ValueError(
                    f"input '{k}' batch {v.shape[0]} != {n}")
        outs: List[List[np.ndarray]] = []
        for lo in range(0, n, max_batch_size):
            chunk = {k: v[lo:lo + max_batch_size] for k, v in feed.items()}
            got = chunk[self._feed_names[0]].shape[0]
            if got < max_batch_size:
                chunk = {
                    k: np.concatenate(
                        [v, np.zeros((max_batch_size - got,) + v.shape[1:],
                                     v.dtype)])
                    for k, v in chunk.items()
                }
            res = self.run(chunk)
            res = [np.asarray(r) for r in res]
            for i, r in enumerate(res):
                if r.ndim == 0 or r.shape[0] != max_batch_size:
                    raise ValueError(
                        f"run_batch fetch #{i} has shape {r.shape}, not "
                        f"batch-major over batch {max_batch_size}; "
                        "batch-aggregated or scalar outputs cannot be "
                        "re-chunked — fetch them via run() instead")
            outs.append([r[:got] for r in res])
        return [np.concatenate([o[i] for o in outs])
                for i in range(len(self._fetch_vars))]


def create_predictor(config: Config) -> Predictor:
    """reference: create_paddle_predictor<AnalysisConfig>."""
    return Predictor(config)
