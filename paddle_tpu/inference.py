"""Inference predictor API (reference: paddle/fluid/inference/api/
analysis_predictor.cc:?, api/paddle_inference_api.h — AnalysisConfig +
AnalysisPredictor + create_paddle_predictor).

TPU-native design: the saved inference model (pruned Program + params,
io.save_inference_model) is loaded once into a private Scope; each
``run`` compiles the whole pruned block to one XLA executable per feed
signature (the Executor's compile cache replaces the reference's IR pass
manager + per-op execution), with optional bf16 inference in place of the
reference's TensorRT/int8 engines.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from paddle_tpu import io as _io
from paddle_tpu.executor import Executor, Scope, scope_guard
from paddle_tpu.framework import CPUPlace, TPUPlace


class Config:
    """Predictor configuration (reference: AnalysisConfig)."""

    def __init__(self, model_dir: str,
                 model_filename: Optional[str] = None,
                 params_filename: Optional[str] = None):
        self.model_dir = model_dir
        self.model_filename = model_filename
        self.params_filename = params_filename
        self._use_tpu = True
        self._use_bf16 = False
        self._batch_buckets: tuple = ()

    def disable_tpu(self):
        self._use_tpu = False
        return self

    def enable_bf16(self):
        """bf16 inference (the TPU analog of the reference's fp16/TensorRT
        precision modes, contrib/float16 + inference/tensorrt)."""
        self._use_bf16 = True
        return self

    def set_batch_buckets(self, sizes):
        """Serve variable-size request batches through a FIXED set of
        compiled batch shapes: ``run`` pads each batch up to the nearest
        bucket (chunking by the largest when it overflows), so the
        executor compiles at most ``len(sizes)`` executables instead of
        one per observed batch size (the reference predictor's dynamic
        batching, without per-shape TRT engine rebuilds)."""
        sizes = sorted({int(s) for s in sizes})
        if not sizes or sizes[0] < 1:
            raise ValueError(f"batch buckets must be positive: {sizes}")
        self._batch_buckets = tuple(sizes)
        return self

    def enable_compile_cache(self, cache_dir: str):
        """Route this process through the persistent compile cache
        (sets the global ``compile_cache_dir`` flag): a fresh serving
        replica loading a known model resolves its executables from
        disk — zero fresh XLA compiles at spin-up."""
        from paddle_tpu import flags as _flags

        _flags.set_flags({"compile_cache_dir": cache_dir})
        return self


class Predictor:
    """Compiled-program predictor (reference: AnalysisPredictor::Run)."""

    def __init__(self, config: Config):
        self._config = config
        self._closed = False
        self.scope = Scope()
        self._exe = Executor(
            TPUPlace(0) if config._use_tpu else CPUPlace()
        )
        with scope_guard(self.scope):
            if os.path.exists(os.path.join(config.model_dir,
                                           "__params_int8__.npz")):
                # int8 PTQ artifact (slim.calibration
                # save_int8_inference_model): quantizable-op weights
                # dequantize from the int8 snapshot, everything else
                # (BN stats, biases) loads fp32; the frozen program
                # carries the static-scale QDQ ops, so serving numerics
                # match int8 deployment through the same Predictor/C-ABI
                # surface as float artifacts.
                from paddle_tpu.slim.calibration import (
                    load_int8_inference_model,
                )

                self.program, self._feed_names, self._fetch_vars = (
                    load_int8_inference_model(
                        config.model_dir, self._exe, scope=self.scope)
                )
            else:
                self.program, self._feed_names, self._fetch_vars = (
                    _io.load_inference_model(
                        config.model_dir,
                        self._exe,
                        model_filename=config.model_filename,
                        params_filename=config.params_filename,
                    )
                )
        if config._use_bf16:
            self.program._amp = True

    # --- reference-parity surface ---

    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return [v.name for v in self._fetch_vars]

    def _as_feed(self, inputs) -> Dict[str, np.ndarray]:
        if isinstance(inputs, dict):
            feed = dict(inputs)
            missing = [n for n in self._feed_names if n not in feed]
            if missing:
                raise KeyError(f"missing inputs: {missing}")
            return feed
        if len(inputs) != len(self._feed_names):
            raise ValueError(
                f"expected {len(self._feed_names)} inputs "
                f"({self._feed_names}), got {len(inputs)}"
            )
        return dict(zip(self._feed_names, inputs))

    def run(
        self,
        inputs: Union[Sequence[np.ndarray], Dict[str, np.ndarray]],
    ) -> List[np.ndarray]:
        """Positional (aligned with get_input_names) or name-keyed feeds
        -> list of output arrays. Compiled executables are cached per
        feed signature; parameters stay device-resident in the
        predictor's private scope and round-trip through each call via
        buffer donation (XLA aliases the unchanged buffers, so no copy).
        With ``Config.set_batch_buckets`` the batch dim is padded to the
        nearest bucket first, so the executable set stays at the bucket
        count whatever batch sizes arrive."""
        feed = self._as_feed(inputs)
        if self._config._batch_buckets:
            return self._run_bucketed(feed)
        return self._run_exact(feed)

    def _run_exact(self, feed: Dict[str, np.ndarray]) -> List[np.ndarray]:
        if self._closed:
            raise RuntimeError("Predictor.run after close()")
        with scope_guard(self.scope):
            return self._exe.run(
                self.program, feed=feed, fetch_list=self._fetch_vars
            )

    def _run_bucketed(self, feed: Dict[str, np.ndarray]) -> List[np.ndarray]:
        """Pad each chunk's batch dim up to a bucket shape and trim the
        padding back off the (batch-major) outputs."""
        buckets = self._config._batch_buckets

        def pick(remaining: int):
            take = min(remaining, buckets[-1])
            return take, next(s for s in buckets if s >= take)

        return self._run_padded_chunks(feed, pick)

    def _run_padded_chunks(self, feed, pick) -> List[np.ndarray]:
        """Shared fixed-signature batching core (run_batch and the
        bucketed run): split the batch into chunks sized by
        ``pick(remaining) -> (take, padded_size)``, zero-pad each chunk
        to its padded size, run, validate every fetch is batch-major
        over that size, trim the padding, and concatenate."""
        n = int(np.shape(next(iter(feed.values())))[0])
        if n == 0:
            raise ValueError("run got an empty (0-row) batch")
        for k, v in feed.items():
            if np.shape(v)[0] != n:
                raise ValueError(
                    f"input '{k}' batch {np.shape(v)[0]} != {n}")
        outs: List[List[np.ndarray]] = []
        lo = 0
        while lo < n:
            take, b = pick(n - lo)
            chunk = {k: np.asarray(v)[lo:lo + take]
                     for k, v in feed.items()}
            if take < b:
                chunk = {
                    k: np.concatenate(
                        [v, np.zeros((b - take,) + v.shape[1:], v.dtype)])
                    for k, v in chunk.items()
                }
            res = [np.asarray(r) for r in self._run_exact(chunk)]
            for i, r in enumerate(res):
                if r.ndim == 0 or r.shape[0] != b:
                    raise ValueError(
                        f"fetch #{i} has shape {r.shape}, not "
                        f"batch-major over batch {b}; batch-aggregated "
                        f"or scalar outputs cannot be re-chunked — "
                        f"fetch them via an exact-shape run() instead")
            outs.append([r[:take] for r in res])
            lo += take
        if len(outs) == 1:
            return outs[0]
        return [np.concatenate([o[i] for o in outs])
                for i in range(len(self._fetch_vars))]

    def warmup(self, inputs=None, shapes: Optional[Dict[str, tuple]] = None,
               dtypes: Optional[Dict[str, str]] = None):
        """Pre-compile (and prime the device) for a feed signature before
        serving traffic — the analog of the reference's warmup passes
        (AnalysisConfig warmup data for int8/TRT). Pass real sample
        ``inputs``, or ``shapes`` (+ optional ``dtypes``, default
        float32) to warm with zeros. Returns self."""
        if inputs is None:
            if not shapes:
                raise ValueError("warmup needs inputs or shapes")
            inputs = {
                n: np.zeros(shapes[n], np.dtype((dtypes or {}).get(
                    n, "float32")))
                for n in self._feed_names
            }
        self.run(inputs)
        return self

    def run_batch(
        self,
        inputs: Union[Sequence[np.ndarray], Dict[str, np.ndarray]],
        max_batch_size: int = 32,
    ) -> List[np.ndarray]:
        """Serve an arbitrary-size batch through FIXED-signature
        executables: the batch is split into ``max_batch_size`` chunks,
        the tail zero-padded to the chunk size, and results concatenated
        with the padding dropped. One compiled program serves every
        request size — the static-shape answer to the reference
        predictor's dynamic batching (no recompiles in steady state)."""
        feed = self._as_feed(inputs)
        return self._run_padded_chunks(
            feed, lambda remaining: (min(remaining, max_batch_size),
                                     max_batch_size))


    def serving_engine(self, cfg, *, supervised: bool = True, **kwargs):
        """Open a continuous-batching serving engine over this
        predictor's weights (serving.py; the reference parity point is
        AnalysisPredictor as a LONG-LIVED self-healing server process).
        ``supervised=True`` (default) wraps it in an EngineSupervisor —
        decode-loop thread, wedge watchdog, warm restart through the
        persistent compile cache; pass False for a caller-driven
        ServingEngine. ``cfg`` is the transformer config; ``kwargs``
        are the engine geometry/SLO knobs (slots, src_len, ...)."""
        from paddle_tpu import serving as _serving

        return _serving.serve(cfg, self, supervised=supervised, **kwargs)

    def close(self):
        """Release the predictor's compiled entries + staged feeds
        (mirroring ``Executor.close`` scoped to this predictor's private
        Scope) and drop its device-resident parameters. Idempotent; a
        ``run`` after close raises. The reference parity point is
        AnalysisPredictor's destructor releasing its per-predictor
        scope/engine state."""
        if self._closed:
            return
        self._closed = True
        self._exe.release_scope(self.scope)
        self.scope.clear()


def create_predictor(config: Config) -> Predictor:
    """reference: create_paddle_predictor<AnalysisConfig>."""
    return Predictor(config)
