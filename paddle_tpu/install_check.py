"""Installation sanity check (reference: python/paddle/fluid/install_check.py
``run_check`` — builds a tiny model, runs one train step, reports).

``run_check()`` trains a 2-layer MLP for a few steps on the current
default device (TPU when present, else CPU) and verifies the loss is
finite and decreasing; it also reports the visible devices and whether
the native C++ runtime library is loadable.
"""

from __future__ import annotations

import numpy as np


def run_check(verbose: bool = True) -> bool:
    import jax

    import paddle_tpu as fluid
    from paddle_tpu import layers, native

    def log(*a):
        if verbose:
            print(*a)

    log(f"paddle_tpu running on backend '{jax.default_backend()}' "
        f"with devices {jax.devices()}")
    log(f"native C++ runtime available: {native.available()}")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, 16, act="relu")
        logits = layers.fc(h, 4)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(0.1).minimize(loss)

    exe = fluid.Executor()
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    probe = np.random.RandomState(1).randn(8, 4)
    from paddle_tpu.executor import scope_guard

    losses = []
    with scope_guard(scope):
        exe.run(startup)
        for _ in range(10):
            xv = rng.randn(32, 8).astype(np.float32)
            yv = np.argmax(xv @ probe, 1).astype(np.int64)[:, None]
            out = exe.run(main, feed={"x": xv, "label": yv},
                          fetch_list=[loss])
            losses.append(float(out[0]))
    ok = bool(np.isfinite(losses).all() and losses[-1] < losses[0])
    if ok:
        log("paddle_tpu is installed successfully! loss "
            f"{losses[0]:.4f} -> {losses[-1]:.4f}")
    else:
        log(f"paddle_tpu check FAILED: losses {losses}")
    return ok
