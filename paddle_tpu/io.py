"""Model I/O: save/load persistables + inference model export.

Reference: python/paddle/fluid/io.py:462,698,903,1083 (save/load_persistables,
save/load_inference_model) built on save/load ops (operators/save_op.cc).
TPU-native design: parameters are device arrays in the Scope; persistence is
host-side numpy .npz (single-file combine) or one file per var, plus the
serialized ProgramDesc for inference models. Sharded (multi-host) arrays
gather through jax before serialization; orbax-style async checkpointing
rides on the same format in parallel/checkpoint.py.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu import faults as _faults
from paddle_tpu.executor import Executor, Scope, global_scope
from paddle_tpu.framework import Program, Variable, default_main_program

# chaos hook between the export's metadata and parameter writes — the
# window whose partial state load_inference_model used to die on
_F_EXPORT = _faults.site("io.export")

_PARAMS_FILE = "__params__.npz"
_MODEL_FILE = "__model__"
_META_FILE = "__meta__.json"


def _is_persistable(var: Variable) -> bool:
    return bool(var.persistable)


def _collect(program: Program, predicate) -> List[Variable]:
    return [v for v in program.list_vars() if predicate(v)]


def save_vars(
    executor: Executor,
    dirname: str,
    main_program: Optional[Program] = None,
    vars: Optional[Sequence[Variable]] = None,
    predicate=None,
    filename: Optional[str] = None,
    scope: Optional[Scope] = None,
):
    program = main_program or default_main_program()
    scope = scope or global_scope()
    if vars is None:
        vars = _collect(program, predicate or _is_persistable)
    os.makedirs(dirname, exist_ok=True)
    arrays: Dict[str, np.ndarray] = {}
    missing = []
    for v in vars:
        val = scope.find_var(v.name)
        if val is None:
            missing.append(v.name)
            continue
        arrays[v.name] = np.asarray(val)
    if missing:
        raise RuntimeError(
            f"save_vars: {len(missing)} requested variables are not "
            f"initialized in the scope (e.g. {missing[:5]}); run the "
            f"startup program first"
        )
    if filename is None:
        filename = _PARAMS_FILE
    np.savez(os.path.join(dirname, filename), **arrays)


def load_vars(
    executor: Executor,
    dirname: str,
    main_program: Optional[Program] = None,
    vars: Optional[Sequence[Variable]] = None,
    predicate=None,
    filename: Optional[str] = None,
    scope: Optional[Scope] = None,
):
    program = main_program or default_main_program()
    scope = scope or global_scope()
    if vars is None:
        vars = _collect(program, predicate or _is_persistable)
    if filename is None:
        filename = _PARAMS_FILE
    path = os.path.join(dirname, filename)
    if not path.endswith(".npz") and not os.path.exists(path):
        path = path + ".npz"
    with np.load(path) as data:
        names = set(data.files)
        missing = [v.name for v in vars if v.name not in names]
        if missing:
            # A partially matching checkpoint would leave the rest of the
            # model at random init and silently train/eval garbage
            # (reference load_persistables raises likewise).
            raise RuntimeError(
                f"checkpoint '{path}' is missing {len(missing)} of "
                f"{len(list(vars))} requested variables "
                f"(e.g. {missing[:5]}); refusing to partially load"
            )
        for v in vars:
            scope.set(v.name, np.asarray(data[v.name]))


def save_persistables(executor, dirname, main_program=None, filename=None):
    """(reference: io.py:462)"""
    save_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    """(reference: io.py:698)"""
    load_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(
        executor, dirname, main_program,
        predicate=lambda v: getattr(v, "is_parameter", False),
        filename=filename,
    )


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(
        executor, dirname, main_program,
        predicate=lambda v: getattr(v, "is_parameter", False),
        filename=filename,
    )


def _prune_for_inference(program: Program, feeded_var_names, target_vars):
    """Keep only ops needed to compute targets from feeds."""
    pruned = program.clone(for_test=True)
    block = pruned.global_block()
    needed = {v.name if isinstance(v, Variable) else str(v) for v in target_vars}
    feeds = set(feeded_var_names)
    keep = []
    for idx in range(len(block.ops) - 1, -1, -1):
        op = block.ops[idx]
        if any(n in needed for n in op.output_arg_names):
            keep.append(idx)
            needed.update(n for n in op.input_arg_names if n not in feeds)
    keep.reverse()
    block.ops = [block.ops[i] for i in keep]
    pruned._bump_version()
    return pruned


def save_inference_model(
    dirname: str,
    feeded_var_names: Sequence[str],
    target_vars: Sequence[Variable],
    executor: Executor,
    main_program: Optional[Program] = None,
    model_filename: Optional[str] = None,
    params_filename: Optional[str] = None,
    export_for_deployment: bool = True,
):
    """(reference: io.py:903) Saves pruned ProgramDesc + params + feed/fetch
    metadata.

    Crash consistency: the export is STAGED into ``<dirname>.tmp`` and
    published by rename only once every file (model, meta, params) is on
    disk — a crash mid-export leaves either the previous complete export
    or no ``dirname`` at all, never a directory that
    ``load_inference_model`` starts loading and then dies on. The export
    OWNS ``dirname``: re-exporting replaces the whole directory (files a
    caller dropped alongside the artifacts do not survive), and a crash
    in the brief swap window parks the previous export at
    ``<dirname>.old.tmp``, from which the next export restores it."""
    program = main_program or default_main_program()
    pruned = _prune_for_inference(program, feeded_var_names, target_vars)
    base = dirname.rstrip("/\\")
    stage, old = base + ".tmp", base + ".old.tmp"
    if not os.path.isdir(dirname) and os.path.isdir(old):
        # a previous export crashed between the two publish renames;
        # bring the complete old export back before replacing it (a
        # concurrent recoverer may win the rename — that is fine)
        try:
            os.rename(old, dirname)
        except OSError:
            pass
    if os.path.isdir(stage):  # leftover of an earlier crashed export
        shutil.rmtree(stage)
    os.makedirs(stage)
    model_path = os.path.join(stage, model_filename or _MODEL_FILE)
    with open(model_path, "wb") as f:
        f.write(pruned.desc_str())
    meta = {
        "feed_names": list(feeded_var_names),
        "fetch_names": [
            v.name if isinstance(v, Variable) else str(v) for v in target_vars
        ],
    }
    with open(os.path.join(stage, _META_FILE), "w") as f:
        json.dump(meta, f)
    # the model-written/params-missing window (path enables truncate
    # plans to tear the staged __model__)
    _F_EXPORT.hit(path=model_path)
    save_persistables(executor, stage, pruned, filename=params_filename)
    # durability before publish (same discipline as the checkpoint
    # commit protocol): a rename can land on disk before the staged
    # file DATA does, which would publish a dir of empty files
    from paddle_tpu.parallel.checkpoint import _fsync_dir, _fsync_file

    for fn in os.listdir(stage):
        _fsync_file(os.path.join(stage, fn))
    # publish: swap the staged dir in (atomic when dirname is absent; a
    # pre-existing export is moved aside first, then dropped). Retried
    # once: a concurrent loader's .old.tmp recovery can recreate
    # dirname between the two renames — the new export must win, not
    # crash out and be discarded.
    for attempt in range(2):
        if os.path.isdir(dirname):
            shutil.rmtree(old, ignore_errors=True)
            os.rename(dirname, old)
        try:
            os.rename(stage, dirname)
            break
        except OSError:
            if attempt:
                raise
    _fsync_dir(os.path.dirname(base) or ".")
    shutil.rmtree(old, ignore_errors=True)
    return meta["fetch_names"]


def load_inference_model(
    dirname: str,
    executor: Executor,
    model_filename: Optional[str] = None,
    params_filename: Optional[str] = None,
):
    """(reference: io.py:1083) -> (program, feed_names, fetch_vars).

    Also recovers an export stranded at ``<dirname>.old.tmp`` by a crash
    in ``save_inference_model``'s publish-swap window — a serving-only
    host must not stay unloadable until some future export runs."""
    base = dirname.rstrip("/\\")
    if not os.path.isdir(dirname) and os.path.isdir(base + ".old.tmp"):
        # a LIVE exporter's publish swap also passes through this state
        # for a few microseconds — give it a beat before concluding the
        # parked copy is a crash leftover to recover
        import time as _t

        _t.sleep(0.05)
        if not os.path.isdir(dirname):
            try:
                os.rename(base + ".old.tmp", dirname)
            except OSError:
                pass  # a concurrent loader/exporter recovered it first
    with open(os.path.join(dirname, model_filename or _MODEL_FILE), "rb") as f:
        program = Program.parse_from_string(f.read())
    with open(os.path.join(dirname, _META_FILE)) as f:
        meta = json.load(f)
    load_persistables(executor, dirname, program, filename=params_filename)
    fetch_vars = [program.global_block().var(n) for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars
