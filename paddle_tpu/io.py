"""Model I/O: save/load persistables + inference model export.

Reference: python/paddle/fluid/io.py:462,698,903,1083 (save/load_persistables,
save/load_inference_model) built on save/load ops (operators/save_op.cc).
TPU-native design: parameters are device arrays in the Scope; persistence is
host-side numpy .npz (single-file combine) or one file per var, plus the
serialized ProgramDesc for inference models. Sharded (multi-host) arrays
gather through jax before serialization; orbax-style async checkpointing
rides on the same format in parallel/checkpoint.py.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu.executor import Executor, Scope, global_scope
from paddle_tpu.framework import Program, Variable, default_main_program

_PARAMS_FILE = "__params__.npz"
_MODEL_FILE = "__model__"
_META_FILE = "__meta__.json"


def _is_persistable(var: Variable) -> bool:
    return bool(var.persistable)


def _collect(program: Program, predicate) -> List[Variable]:
    return [v for v in program.list_vars() if predicate(v)]


def save_vars(
    executor: Executor,
    dirname: str,
    main_program: Optional[Program] = None,
    vars: Optional[Sequence[Variable]] = None,
    predicate=None,
    filename: Optional[str] = None,
    scope: Optional[Scope] = None,
):
    program = main_program or default_main_program()
    scope = scope or global_scope()
    if vars is None:
        vars = _collect(program, predicate or _is_persistable)
    os.makedirs(dirname, exist_ok=True)
    arrays: Dict[str, np.ndarray] = {}
    missing = []
    for v in vars:
        val = scope.find_var(v.name)
        if val is None:
            missing.append(v.name)
            continue
        arrays[v.name] = np.asarray(val)
    if missing:
        raise RuntimeError(
            f"save_vars: {len(missing)} requested variables are not "
            f"initialized in the scope (e.g. {missing[:5]}); run the "
            f"startup program first"
        )
    if filename is None:
        filename = _PARAMS_FILE
    np.savez(os.path.join(dirname, filename), **arrays)


def load_vars(
    executor: Executor,
    dirname: str,
    main_program: Optional[Program] = None,
    vars: Optional[Sequence[Variable]] = None,
    predicate=None,
    filename: Optional[str] = None,
    scope: Optional[Scope] = None,
):
    program = main_program or default_main_program()
    scope = scope or global_scope()
    if vars is None:
        vars = _collect(program, predicate or _is_persistable)
    if filename is None:
        filename = _PARAMS_FILE
    path = os.path.join(dirname, filename)
    if not path.endswith(".npz") and not os.path.exists(path):
        path = path + ".npz"
    with np.load(path) as data:
        names = set(data.files)
        missing = [v.name for v in vars if v.name not in names]
        if missing:
            # A partially matching checkpoint would leave the rest of the
            # model at random init and silently train/eval garbage
            # (reference load_persistables raises likewise).
            raise RuntimeError(
                f"checkpoint '{path}' is missing {len(missing)} of "
                f"{len(list(vars))} requested variables "
                f"(e.g. {missing[:5]}); refusing to partially load"
            )
        for v in vars:
            scope.set(v.name, np.asarray(data[v.name]))


def save_persistables(executor, dirname, main_program=None, filename=None):
    """(reference: io.py:462)"""
    save_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    """(reference: io.py:698)"""
    load_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(
        executor, dirname, main_program,
        predicate=lambda v: getattr(v, "is_parameter", False),
        filename=filename,
    )


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(
        executor, dirname, main_program,
        predicate=lambda v: getattr(v, "is_parameter", False),
        filename=filename,
    )


def _prune_for_inference(program: Program, feeded_var_names, target_vars):
    """Keep only ops needed to compute targets from feeds."""
    pruned = program.clone(for_test=True)
    block = pruned.global_block()
    needed = {v.name if isinstance(v, Variable) else str(v) for v in target_vars}
    feeds = set(feeded_var_names)
    keep = []
    for idx in range(len(block.ops) - 1, -1, -1):
        op = block.ops[idx]
        if any(n in needed for n in op.output_arg_names):
            keep.append(idx)
            needed.update(n for n in op.input_arg_names if n not in feeds)
    keep.reverse()
    block.ops = [block.ops[i] for i in keep]
    pruned._bump_version()
    return pruned


def save_inference_model(
    dirname: str,
    feeded_var_names: Sequence[str],
    target_vars: Sequence[Variable],
    executor: Executor,
    main_program: Optional[Program] = None,
    model_filename: Optional[str] = None,
    params_filename: Optional[str] = None,
    export_for_deployment: bool = True,
):
    """(reference: io.py:903) Saves pruned ProgramDesc + params + feed/fetch
    metadata."""
    program = main_program or default_main_program()
    pruned = _prune_for_inference(program, feeded_var_names, target_vars)
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, model_filename or _MODEL_FILE), "wb") as f:
        f.write(pruned.desc_str())
    meta = {
        "feed_names": list(feeded_var_names),
        "fetch_names": [
            v.name if isinstance(v, Variable) else str(v) for v in target_vars
        ],
    }
    with open(os.path.join(dirname, _META_FILE), "w") as f:
        json.dump(meta, f)
    save_persistables(executor, dirname, pruned, filename=params_filename)
    return meta["fetch_names"]


def load_inference_model(
    dirname: str,
    executor: Executor,
    model_filename: Optional[str] = None,
    params_filename: Optional[str] = None,
):
    """(reference: io.py:1083) -> (program, feed_names, fetch_vars)."""
    with open(os.path.join(dirname, model_filename or _MODEL_FILE), "rb") as f:
        program = Program.parse_from_string(f.read())
    with open(os.path.join(dirname, _META_FILE)) as f:
        meta = json.load(f)
    load_persistables(executor, dirname, program, filename=params_filename)
    fetch_vars = [program.global_block().var(n) for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars
