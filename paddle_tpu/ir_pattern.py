"""Shared producer/consumer pattern matching over a Block's ops.

The TPU-native analog of the reference's GraphPatternDetector
(reference: paddle/fluid/framework/ir/graph_pattern_detector.cc) at
Program altitude: instead of an IR node graph, the index is built
directly over ``block.ops`` and keyed by var name. Fusion passes that
previously hand-rolled their own producer/consumer maps (the
InferenceTranspiler conv+BN fold and the fc_fuse pass duplicated the
same walk) share this one.

The matcher is deliberately small: the only pattern shape our passes
need is a two-op chain where the first op's output feeds exactly one
consumer. Richer DAG patterns stay subsumed by XLA fusion (SURVEY.md
section 7 phase 4) — this exists for PROGRAM rewrites that must happen
before lowering (serving-artifact folding, quantization placement).
"""

from __future__ import annotations

from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple


class BlockGraph:
    """Producer/consumer index over ``block.ops``.

    ``producer[name]`` is the index of the op that (last) writes the
    var; ``consumers[name]`` lists indices of every op reading it, in
    program order. Built once — passes that mutate the block should
    re-create the graph (or finish matching before rewriting, the
    pattern both built-in passes use).
    """

    def __init__(self, block):
        self.block = block
        self.producer: Dict[str, int] = {}
        self.consumers: Dict[str, List[int]] = {}
        for idx, op in enumerate(block.ops):
            for n in op.input_arg_names:
                self.consumers.setdefault(n, []).append(idx)
            for n in op.output_arg_names:
                self.producer[n] = idx

    def producer_op(self, name: str):
        """(index, op) of the var's producer, or None (fed/parameter)."""
        idx = self.producer.get(name)
        return None if idx is None else (idx, self.block.ops[idx])

    def sole_consumer(self, name: str):
        """(index, op) when exactly one op reads the var, else None."""
        cons = self.consumers.get(name, [])
        return None if len(cons) != 1 else (cons[0], self.block.ops[cons[0]])

    def available_before(self, name: str, idx: int) -> bool:
        """True when ``name`` is defined before op ``idx`` runs: either
        it has no producer in this block (a parameter, feed, or outer
        var) or its producer precedes ``idx`` in program order. Fusions
        that splice an op at position ``idx`` must check this for every
        new input, or they can read a var before it exists."""
        p = self.producer.get(name)
        return p is None or p < idx

    def is_persistable(self, name: str) -> bool:
        v = self.block._find_var_recursive(name)
        return bool(v is not None and getattr(v, "persistable", False))


def match_chain(
    graph: BlockGraph,
    first_types: Sequence[str],
    out_slot: str,
    second_type: str,
    in_slot: str,
    second_pred: Optional[Callable] = None,
) -> Iterator[Tuple[int, int]]:
    """Yield ``(i, j)`` index pairs where ``ops[i]`` is one of
    ``first_types``, its ``out_slot`` output is read ONLY by ``ops[j]``
    (a ``second_type`` whose ``in_slot`` input is that var), and the
    chain runs forward (``i < j``). ``second_pred(op)`` optionally
    filters the consumer (e.g. ``is_test`` batch norms).

    The sole-consumer requirement is what makes collapsing the pair
    safe: any other reader of the intermediate would observe a var that
    the fused op no longer produces.
    """
    first_types = set(first_types)
    for i, op in enumerate(graph.block.ops):
        if op.type not in first_types or out_slot not in op.outputs:
            continue
        out = op.outputs[out_slot][0]
        hit = graph.sole_consumer(out)
        if hit is None:
            continue
        j, nxt = hit
        if j <= i or nxt.type != second_type:
            continue
        if nxt.inputs.get(in_slot, [None])[0] != out:
            continue
        if second_pred is not None and not second_pred(nxt):
            continue
        yield i, j
