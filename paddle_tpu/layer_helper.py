"""LayerHelper: shared machinery for layer functions
(reference: python/paddle/fluid/layer_helper.py:42)."""

from __future__ import annotations

from typing import Optional

from paddle_tpu import unique_name
from paddle_tpu.framework import (
    Variable,
    default_main_program,
    default_startup_program,
)
from paddle_tpu.initializer import (
    ConstantInitializer,
    Initializer,
    XavierInitializer,
)
from paddle_tpu.param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name else unique_name.generate(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    def append_op(self, *args, **kwargs):
        return self.block.append_op(*args, **kwargs)

    def create_variable_for_type_inference(self, dtype="float32", stop_gradient=False):
        return self.block.create_var(
            name=unique_name.generate(self.name + ".tmp"),
            dtype=dtype,
            stop_gradient=stop_gradient,
        )

    def create_parameter(
        self,
        attr: Optional[ParamAttr],
        shape,
        dtype="float32",
        is_bias: bool = False,
        default_initializer: Optional[Initializer] = None,
    ):
        attr = ParamAttr._to_attr(attr)
        if attr is False or (attr is not None and attr.name is False):
            return None
        suffix = "b" if is_bias else "w"
        name = attr.name or unique_name.generate(f"{self.name}.{suffix}")
        init = attr.initializer or default_initializer
        if init is None:
            init = ConstantInitializer(0.0) if is_bias else XavierInitializer()

        shape = [int(d) for d in shape]
        # Parameter lives in both programs: startup initializes, main uses.
        startup_block = self.startup_program.global_block()
        sp = startup_block.create_parameter(
            name,
            shape,
            dtype,
            initializer=init,
            regularizer=attr.regularizer,
            trainable=attr.trainable,
            optimize_attr={"learning_rate": attr.learning_rate},
        )
        init(sp, startup_block)
        mp = self.main_program.global_block().create_parameter(
            name,
            shape,
            dtype,
            regularizer=attr.regularizer,
            trainable=attr.trainable,
            optimize_attr={"learning_rate": attr.learning_rate},
        )
        return mp

    def append_bias_op(self, input_var: Variable, dim_start=1, dim_end=None):
        bias_attr = self.kwargs.get("bias_attr")
        if bias_attr is False:
            return input_var
        size = input_var.shape[dim_start:dim_end] if input_var.shape else None
        b = self.create_parameter(
            ParamAttr._to_attr(bias_attr),
            shape=list(size) if size else [1],
            dtype=input_var.dtype,
            is_bias=True,
        )
        if b is None:
            return input_var
        out = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            "elementwise_add",
            inputs={"X": input_var, "Y": b},
            outputs={"Out": out},
            attrs={"axis": dim_start},
        )
        return out

    def append_activation(self, input_var: Variable):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = dict(act)
        act_type = act.pop("type")
        out = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(act_type, inputs={"X": input_var}, outputs={"Out": out}, attrs=act)
        return out


def append_simple_op(op_type, inputs, attrs=None, out_slots=("Out",),
                     dtypes=None, name=None, stop_gradient=False):
    """Append one op whose outputs are freshly created temp vars; returns
    the output var(s). The shared graph-building shorthand behind the
    detection/more layer surfaces (one copy so dtype-fallback and
    None-input handling cannot drift)."""
    helper = LayerHelper(op_type, name=name)
    first = next(v for v in inputs.values() if v is not None)
    base = first[0] if isinstance(first, (list, tuple)) else first
    outs = {}
    for i, s in enumerate(out_slots):
        dt = (dtypes[i] if dtypes else None) or base.dtype
        outs[s] = helper.create_variable_for_type_inference(
            dtype=dt, stop_gradient=stop_gradient)
    helper.append_op(op_type,
                     inputs={k: v for k, v in inputs.items()
                             if v is not None},
                     outputs=outs, attrs=attrs or {})
    vals = [outs[s] for s in out_slots]
    return vals[0] if len(vals) == 1 else tuple(vals)
