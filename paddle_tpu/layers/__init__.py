"""Layers API (reference: python/paddle/fluid/layers/)."""

from paddle_tpu.layers.io import *  # noqa: F401,F403
from paddle_tpu.layers.nn import *  # noqa: F401,F403
from paddle_tpu.layers.tensor import *  # noqa: F401,F403
from paddle_tpu.layers.rnn import *  # noqa: F401,F403
from paddle_tpu.layers.more import *  # noqa: F401,F403
from paddle_tpu.layers import detection  # noqa: F401
from paddle_tpu.layers.detection import *  # noqa: F401,F403
from paddle_tpu.layers.control_flow import (  # noqa: F401
    DynamicRNN,
    IfElse,
    Print,
    StaticRNN,
    Switch,
    While,
    array_fill,
    array_write_step,
    cond,
    while_loop,
)
from paddle_tpu.layers import learning_rate_scheduler  # noqa: F401
from paddle_tpu.layers.learning_rate_scheduler import (  # noqa: F401
    cosine_decay,
    exponential_decay,
    inverse_time_decay,
    natural_exp_decay,
    noam_decay,
    piecewise_decay,
    polynomial_decay,
)
