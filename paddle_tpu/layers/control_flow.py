"""Control-flow layers: While / while_loop / cond / StaticRNN / Switch.

Graph-building front end for the structural ops in
ops/control_flow_ops.py. Mirrors the reference's control-flow layer API
(reference: python/paddle/fluid/layers/control_flow.py — While:697,
StaticRNN:396, Switch:1058, and the ConditionalBlock machinery:996), but the
sub-blocks lower to XLA While/Conditional/Scan instead of being interpreted
per-iteration by the C++ executor.

Design notes (TPU-first):
- ``StaticRNN`` builds a ``scan`` op — the differentiable recurrence. Use it
  for training-time RNNs.
- ``While`` builds a ``while`` op — data-dependent trip count, no gradient
  (XLA While is not differentiable). Use it for decoding/inference loops.
- Values crossing the block boundary become op inputs (``X``/``Init``/
  ``Captured``), discovered by analyzing the sub-block's read/write sets, so
  state analysis and autodiff see them without any manual annotation.
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional, Sequence, Tuple

from paddle_tpu import unique_name
from paddle_tpu.framework import (
    Block,
    Variable,
    default_main_program,
)
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.layers.tensor import range_

__all__ = [
    "While",
    "while_loop",
    "cond",
    "StaticRNN",
    "DynamicRNN",
    "IfElse",
    "Switch",
    "increment",
    "array_fill",
    "array_write_step",
    "Print",
]


def _broadcast_row_mask(mask: Variable, v: Variable) -> Variable:
    """Reshape a [B, 1] per-row mask to broadcast against rank(v): [B]
    for rank-1 values, [B, 1, ...] for higher ranks (a bare [B, 1] mask
    against a [B] value would outer-broadcast to [B, B])."""
    from paddle_tpu import layers

    rank = len(v.shape or ())
    if rank == 1:
        return layers.reshape(mask, [-1])
    if rank > 2:
        return layers.reshape(mask, [-1, 1] + [1] * (rank - 2))
    return mask


def _ordered_unique(names):
    seen = set()
    out = []
    for n in names:
        if n and n not in seen:
            seen.add(n)
            out.append(n)
    return out


def _read_write_sets(sub: Block) -> Tuple[List[str], List[str]]:
    """(reads-before-local-write, writes) name lists for a sub-block."""
    written: set = set()
    reads: List[str] = []
    writes: List[str] = []
    for op in sub.ops:
        for n in op.input_arg_names:
            if n and n not in written:
                reads.append(n)
        for n in op.output_arg_names:
            if n and n not in written:
                written.add(n)
                writes.append(n)
    return _ordered_unique(reads), writes


def _captured_names(
    sub: Block, parent: Block, exclude: Sequence[str]
) -> List[str]:
    """Names the sub-block reads from enclosing scopes (closure inputs)."""
    reads, _ = _read_write_sets(sub)
    ex = set(exclude)
    out = []
    for n in reads:
        if n in ex or n in sub.vars:
            continue
        if parent._find_var_recursive(n) is not None:
            out.append(n)
    return out


class While:
    """``with While(cond).block():`` — run the body while ``cond`` is true.

    The body must refresh ``cond`` (e.g. via ``layers.less_than(..,
    cond=cond)`` or ``layers.assign(new_cond, output=cond)``); loop-carried
    variables are exactly the enclosing-scope variables the body writes to.
    Reference: layers/control_flow.py:697 (While), lowered via
    operators/controlflow/while_op.cc:43 -> here ``lax.while_loop``.
    """

    def __init__(self, cond: Variable, is_test: bool = False, name=None,
                 max_trip_count: Optional[int] = None):
        """``max_trip_count``: upper bound on iterations. When given, the
        loop lowers DIFFERENTIABLY (``bounded_while``: a scan over
        exactly N steps with dead iterations masked through selects), so
        programs that backprop through a data-dependent loop — which the
        reference trains via WhileGradOp — work here too, at the cost of
        always executing N body evaluations. Without it, the loop lowers
        to XLA While (dynamic trip count, NO gradient — backprop through
        it raises; use scan/StaticRNN for recurrences)."""
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond
        self.max_trip_count = max_trip_count
        self._steps_var: Optional[Variable] = None

    @contextlib.contextmanager
    def block(self):
        program = default_main_program()
        parent = program.current_block()
        sub = program._create_block()
        try:
            yield
        finally:
            program._rollback()

        reads, writes = _read_write_sets(sub)
        cond_name = self.cond_var.name
        if cond_name not in writes:
            raise ValueError(
                "While body never updates the condition variable "
                f"'{cond_name}' — the loop would not terminate. Refresh it "
                "with layers.less_than(..., cond=cond) or layers.assign."
            )
        # Loop-carried: enclosing-scope names the body writes (minus cond).
        carry_names = [
            n
            for n in writes
            if n != cond_name
            and n not in sub.vars
            and parent._find_var_recursive(n) is not None
        ]
        captured = _captured_names(
            sub, parent, exclude=[cond_name] + carry_names
        )
        steps = parent.create_var(
            name=unique_name.generate("while_steps"),
            dtype="int32",
            shape=(),
            stop_gradient=True,
        )
        self._steps_var = steps
        attrs = {
            "sub_block": sub,
            "carry_names": carry_names,
            "cond_name": cond_name,
            "captured_names": captured,
        }
        op_type = "while"
        if self.max_trip_count is not None:
            op_type = "bounded_while"
            attrs["max_trip_count"] = int(self.max_trip_count)
        parent.append_op(
            op_type,
            inputs={
                "Condition": [cond_name],
                "X": carry_names,
                "Captured": captured,
            },
            outputs={
                "Out": carry_names,
                "CondOut": [cond_name],
                "Steps": [steps],
            },
            attrs=attrs,
        )


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """Functional while: ``loop_vars = body_fn(*loop_vars) while cond_fn``.

    Shapes/dtypes of loop vars must be loop-invariant (XLA While).
    Returns the loop variables (updated in place by name).
    """
    from paddle_tpu import layers

    if isinstance(loop_vars, Variable):
        loop_vars = [loop_vars]
    loop_vars = list(loop_vars)
    cond0 = cond_fn(*loop_vars)
    w = While(cond0, is_test=is_test, name=name)
    with w.block():
        new_vars = body_fn(*loop_vars)
        if new_vars is None:
            new_vars = []
        if isinstance(new_vars, Variable):
            new_vars = [new_vars]
        if len(new_vars) != len(loop_vars):
            raise ValueError(
                f"body_fn returned {len(new_vars)} values for "
                f"{len(loop_vars)} loop vars"
            )
        for old, new in zip(loop_vars, new_vars):
            if new is not old:
                layers.assign(new, output=old)
        layers.assign(cond_fn(*loop_vars), output=cond0)
    return loop_vars


def cond(pred: Variable, true_fn, false_fn, name=None):
    """Two-way branch: ``true_fn()`` if pred else ``false_fn()``.

    Both branches build sub-blocks traced into ``lax.cond``; their return
    structures must match (same arity, shapes, dtypes). Differentiable with
    respect to values the branches read from the enclosing scope.
    Reference: the ConditionalBlock pair in layers/control_flow.py:996 /
    operators/controlflow/conditional_block_op.cc:75.
    """
    program = default_main_program()
    parent = program.current_block()

    def build(fn):
        sub = program._create_block()
        try:
            outs = fn()
        finally:
            program._rollback()
        if outs is None:
            outs = []
        if isinstance(outs, Variable):
            outs = [outs]
        return sub, list(outs)

    tb, t_outs = build(true_fn)
    fb, f_outs = build(false_fn)
    if len(t_outs) != len(f_outs):
        raise ValueError(
            f"cond branches returned different arities: {len(t_outs)} vs "
            f"{len(f_outs)}"
        )
    cap = _ordered_unique(
        _captured_names(tb, parent, exclude=[])
        + _captured_names(fb, parent, exclude=[])
    )
    out_vars = [
        parent.create_var(
            name=unique_name.generate("cond_out"),
            dtype=t.dtype,
            shape=t.shape,
        )
        for t in t_outs
    ]
    parent.append_op(
        "cond",
        inputs={"Cond": [pred.name], "Captured": cap},
        outputs={"Out": [v.name for v in out_vars]},
        attrs={
            "true_block": tb,
            "false_block": fb,
            "true_out_names": [v.name for v in t_outs],
            "false_out_names": [v.name for v in f_outs],
            "captured_names": cap,
        },
    )
    if not out_vars:
        return None
    return out_vars[0] if len(out_vars) == 1 else out_vars


class StaticRNN:
    """Fixed-length recurrence over a sequence, built on the ``scan`` op.

    Usage (reference: layers/control_flow.py:396):

        rnn = StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)          # x: [B, T, D] batch-major
            h_prev = rnn.memory(init=h0)     # carried state
            h = layers.fc(...)               # any graph ops
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out = rnn()                          # [B, T, H]

    Differentiable: lowers to one ``scan`` op whose grad is the XLA scan
    transpose — the reference's RecurrentGradOp tape
    (operators/recurrent_op.cc:250) done by the compiler.
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._program = default_main_program()
        self._sub: Optional[Block] = None
        self._parent: Optional[Block] = None
        self._inputs: List[Tuple[Variable, Variable]] = []  # (parent, step)
        self._mems: List[Dict] = []  # {init, pre, new_name}
        self._outputs: List[Variable] = []
        self._seq_len: Optional[int] = None
        self._out_vars: List[Variable] = []
        self._final_vars: List[Variable] = []

    @contextlib.contextmanager
    def step(self):
        self._parent = self._program.current_block()
        self._sub = self._program._create_block()
        try:
            yield
        finally:
            self._program._rollback()
        self._complete()

    def step_input(self, x: Variable) -> Variable:
        """Register ``x`` ([B, T, ...]) as a scanned input; returns the
        per-step slice ([B, ...])."""
        if x.shape is None or len(x.shape) < 2 or x.shape[1] < 0:
            raise ValueError(
                "StaticRNN.step_input needs a static sequence length in "
                f"x.shape[1]; got {x.shape}"
            )
        if self._seq_len is None:
            self._seq_len = int(x.shape[1])
        elif self._seq_len != int(x.shape[1]):
            raise ValueError(
                f"inconsistent sequence lengths: {self._seq_len} vs "
                f"{x.shape[1]}"
            )
        step = self._sub.create_var(
            name=unique_name.generate("rnn_step_in"),
            dtype=x.dtype,
            shape=(x.shape[0],) + tuple(x.shape[2:]),
        )
        self._inputs.append((x, step))
        return step

    def memory(
        self,
        init: Optional[Variable] = None,
        shape=None,
        batch_ref: Optional[Variable] = None,
        init_value: float = 0.0,
        init_batch_dim_idx: int = 0,
        ref_batch_dim_idx: int = 1,
        dtype="float32",
    ) -> Variable:
        from paddle_tpu import layers

        if init is None:
            if shape is None:
                raise ValueError("memory() needs init= or shape=")
            # The boundary value is a parent-block computation (it feeds the
            # scan op's Init slot), but memory() is called inside step() —
            # build it in the parent block explicitly.
            cur = self._program.current_block_idx
            self._program.current_block_idx = self._parent.idx
            try:
                if batch_ref is not None:
                    # batch dim read from batch_ref.shape[ref_batch_dim_idx]
                    # and written at init_batch_dim_idx (reference
                    # StaticRNN.memory fill_constant_batch_size_like)
                    helper = LayerHelper("rnn_mem_init")
                    init = helper.create_variable_for_type_inference(
                        dtype=dtype)
                    helper.append_op(
                        "fill_constant_batch_size_like",
                        inputs={"Input": batch_ref},
                        outputs={"Out": init},
                        attrs={"shape": [-1] + list(shape),
                               "value": init_value, "dtype": dtype,
                               "input_dim_idx": ref_batch_dim_idx,
                               "output_dim_idx": init_batch_dim_idx})
                else:
                    init = layers.fill_constant(
                        shape=list(shape), dtype=dtype, value=init_value
                    )
            finally:
                self._program.current_block_idx = cur
        pre = self._sub.create_var(
            name=unique_name.generate("rnn_mem"),
            dtype=init.dtype,
            shape=init.shape,
        )
        self._mems.append({"init": init, "pre": pre, "new_name": None})
        return pre

    def update_memory(self, mem: Variable, var: Variable):
        for m in self._mems:
            if m["pre"].name == mem.name:
                m["new_name"] = var.name
                return
        raise ValueError(f"'{mem.name}' is not a memory of this StaticRNN")

    def step_output(self, o: Variable):
        self._outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete(self):
        from paddle_tpu import layers

        sub, parent = self._sub, self._parent
        for m in self._mems:
            if m["new_name"] is None:
                raise ValueError(
                    f"memory '{m['pre'].name}' was never update_memory()'d"
                )
        if self._seq_len is None:
            raise ValueError("StaticRNN needs at least one step_input")

        # Time-major views of the scanned inputs: [B, T, ...] -> [T, B, ...].
        xt_names = []
        for x, _step in self._inputs:
            perm = [1, 0] + list(range(2, len(x.shape)))
            xt = layers.transpose(x, perm)
            xt_names.append(xt.name)

        x_names = [s.name for _x, s in self._inputs]
        s_in = [m["pre"].name for m in self._mems]
        s_out = [m["new_name"] for m in self._mems]
        init_names = [m["init"].name for m in self._mems]
        y_names = [o.name for o in self._outputs]
        captured = _captured_names(
            sub, parent, exclude=x_names + s_in
        )

        y_tm = [
            parent.create_var(
                name=unique_name.generate("rnn_out_tm"),
                dtype=o.dtype,
                shape=(self._seq_len,) + tuple(o.shape or ()),
            )
            for o in self._outputs
        ]
        finals = [
            parent.create_var(
                name=unique_name.generate("rnn_final"),
                dtype=m["init"].dtype,
                shape=m["init"].shape,
            )
            for m in self._mems
        ]
        parent.append_op(
            "scan",
            inputs={"X": xt_names, "Init": init_names, "Captured": captured},
            outputs={
                "Y": [v.name for v in y_tm],
                "FinalState": [v.name for v in finals],
            },
            attrs={
                "sub_block": sub,
                "x_names": x_names,
                "state_in_names": s_in,
                "state_out_names": s_out,
                "y_names": y_names,
                "captured_names": captured,
            },
        )
        # Back to batch-major [B, T, ...].
        self._out_vars = []
        for v, o in zip(y_tm, self._outputs):
            perm = [1, 0] + list(range(2, 1 + len(o.shape or ())))
            self._out_vars.append(layers.transpose(v, perm))
        self._final_vars = finals

    def __call__(self):
        if len(self._out_vars) == 1:
            return self._out_vars[0]
        return list(self._out_vars)

    @property
    def outputs(self):
        return list(self._out_vars)

    @property
    def final_states(self):
        return list(self._final_vars)


class Switch:
    """``with switch.case(cond): ... with switch.default(): ...``

    Reference: layers/control_flow.py:1058. Built on nested ``cond`` ops:
    each case body must assign to the same output variables (via
    ``layers.assign(..., output=...)`` / ``fill_constant(out=...)``), and
    those assignments are rewritten into a branch chain.
    """

    def __init__(self, name=None):
        self._cases: List[Tuple[Optional[Variable], object]] = []
        self._entered = False

    @contextlib.contextmanager
    def case(self, condition: Variable):
        program = default_main_program()
        sub = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        self._cases.append((condition, sub))

    @contextlib.contextmanager
    def default(self):
        program = default_main_program()
        sub = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        self._cases.append((None, sub))

    def __enter__(self):
        self._entered = True
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            return False
        program = default_main_program()
        parent = program.current_block()
        # Output names: union of names every case writes into parent scope.
        out_names: List[str] = []
        for _c, sub in self._cases:
            _reads, writes = _read_write_sets(sub)
            for n in writes:
                if n not in sub.vars and parent._find_var_recursive(n):
                    if n not in out_names:
                        out_names.append(n)
        if not out_names:
            return False
        conds = [c for c, _ in self._cases if c is not None]
        subs = [s for _, s in self._cases]
        has_default = any(c is None for c, _ in self._cases)
        if not has_default:
            raise ValueError("Switch requires a default() case")

        # Chain: cond(c0, case0, cond(c1, case1, ... default))
        def make_branch(i):
            def branch():
                from paddle_tpu import layers

                if i >= len(self._cases):
                    raise AssertionError
                c, sub = self._cases[i]
                # Re-play the recorded block inside a fresh sub-block by
                # moving its ops (blocks are only built once; reuse ops).
                cur = program.current_block()
                cur.ops.extend(sub.ops)
                cur.vars.update(sub.vars)
                return [layers.assign(parent.var(n)) for n in out_names]

            return branch

        def chain(i):
            c, _sub = self._cases[i]
            if c is None or i == len(self._cases) - 1:
                return make_branch(i)()
            return cond(c, make_branch(i), lambda: chain(i + 1))

        results = chain(0)
        if isinstance(results, Variable):
            results = [results]
        from paddle_tpu import layers

        for n, r in zip(out_names, results):
            layers.assign(r, output=parent.var(n))
        return False


def increment(x, value=1.0, in_place=True):
    from paddle_tpu import layers

    return layers.increment(x, value=value, in_place=in_place)


def array_fill(maxlen: int, template: Variable, value: float = 0.0):
    """Dense stand-in for the reference's LoDTensorArray: a preallocated
    ``[maxlen, ...]`` buffer written by ``array_write_step``. XLA needs
    static shapes, so the array is a fixed tensor, not a growable list
    (reference: operators/controlflow/tensor_array_read_write_op.cc)."""
    from paddle_tpu import layers

    shape = [maxlen] + list(template.shape or ())
    return layers.fill_constant(shape=shape, dtype=template.dtype, value=value)


def array_write_step(array: Variable, index: Variable, value: Variable):
    """Write ``value`` at position ``index`` (dynamic scalar) of ``array``."""
    helper = LayerHelper("array_write")
    out = helper.create_variable_for_type_inference(dtype=array.dtype)
    helper.append_op(
        "dynamic_update",
        inputs={"X": array, "Index": index, "Value": value},
        outputs={"Out": out},
    )
    out.shape = array.shape
    return out


class DynamicRNN:
    """Batch RNN over padded sequences (reference:
    layers/control_flow.py:1661 ``DynamicRNN``).

    The reference unfolds LoD sequences through a While loop with rank
    tables shrinking the batch as sequences end. The TPU-native design
    keeps the batch DENSE and static: inputs are padded [B, T, ...]
    tensors with an optional per-sample ``length`` [B] (the SURVEY.md
    section 5 padding design); the recurrence lowers to the same
    differentiable ``scan`` op as StaticRNN, and masking replaces the
    shrinking batch — memories freeze (carry their last valid value) and
    outputs are zeroed once ``t >= length``.

    Usage::

        drnn = DynamicRNN()
        with drnn.block():
            w = drnn.step_input(emb, length=seq_len)   # emb [B, T, D]
            prev = drnn.memory(shape=[200])
            h = layers.fc([w, prev], 200, act="relu")
            drnn.update_memory(prev, h)
            drnn.output(h)
        out = drnn()                      # [B, T, 200], zero past length
        last = layers.sequence_pool(out, "last", length=seq_len)
    """

    def __init__(self, name=None):
        self._rnn = StaticRNN(name)
        self._length: Optional[Variable] = None
        self._keep: Optional[Variable] = None   # [B, 1] bool in-block
        self._batch_ref: Optional[Variable] = None
        self._in_block = False

    @contextlib.contextmanager
    def block(self):
        with self._rnn.step():
            self._in_block = True
            try:
                yield
            finally:
                self._in_block = False

    def _ensure_keep(self):
        """Lazy [B, 1] bool keep mask = (t < length), built once per
        block."""
        from paddle_tpu import layers

        if self._keep is not None or self._length is None:
            return
        prog = self._rnn._program
        t = self._rnn._seq_len
        cur = prog.current_block_idx
        prog.current_block_idx = self._rnn._parent.idx
        try:
            steps = layers.reshape(range_(0, t, 1, "int64"), [1, t, 1])
        finally:
            prog.current_block_idx = cur
        t_step = self._rnn.step_input(steps)          # [1, 1] int64
        # normalize length to [B, 1] whatever its declared/fed rank
        length = layers.reshape(self._length, [-1, 1])
        self._keep = layers.less_than(
            t_step, layers.cast(length, "int64"))      # [B, 1] bool

    def _keep_as(self, v: Variable):
        return _broadcast_row_mask(self._keep, v)

    def _require_block(self, what):
        if not self._in_block:
            raise ValueError(
                f"DynamicRNN.{what}() must be called inside "
                "`with drnn.block():` (reference DynamicRNN._assert_in_rnn_"
                "block_ semantics)")

    def step_input(self, x: Variable, level=0, length: Optional[Variable] = None):
        self._require_block("step_input")
        step = self._rnn.step_input(x)
        if self._batch_ref is None:
            self._batch_ref = x
        if length is not None:
            if self._length is not None and length.name != self._length.name:
                raise ValueError(
                    "DynamicRNN: conflicting `length` on a second "
                    f"step_input ('{self._length.name}' vs '{length.name}')"
                    " — all scanned inputs share one length tensor")
            self._length = length
        return step

    def static_input(self, x: Variable) -> Variable:
        """Non-scanned input, visible at every step (reference
        drnn.static_input; dense: captured as-is)."""
        return x

    def memory(self, init: Optional[Variable] = None, shape=None,
               value: float = 0.0, need_reorder: bool = False,
               dtype="float32"):
        self._require_block("memory")
        if init is not None:
            return self._rnn.memory(init=init)
        if shape is None:
            raise ValueError("DynamicRNN.memory needs init= or shape=")
        if self._batch_ref is None:
            raise ValueError(
                "DynamicRNN.memory(shape=...) must follow step_input so "
                "the batch size is known")
        return self._rnn.memory(shape=list(shape),
                                batch_ref=self._batch_ref,
                                init_batch_dim_idx=0, ref_batch_dim_idx=0,
                                init_value=value, dtype=dtype)

    def update_memory(self, mem: Variable, new: Variable):
        from paddle_tpu import layers

        self._require_block("update_memory")
        self._ensure_keep()
        if self._keep is not None:
            # freeze finished rows: carry keeps its last valid value
            new = layers.where(self._keep_as(new), new, mem)
        self._rnn.update_memory(mem, new)

    def output(self, *outputs):
        from paddle_tpu import layers

        self._require_block("output")
        self._ensure_keep()
        for o in outputs:
            if self._keep is not None:
                o = layers.where(self._keep_as(o), o,
                                 layers.fill_constant_like(o, 0))
            self._rnn.step_output(o)

    def __call__(self):
        return self._rnn()


class IfElse:
    """Per-sample two-way branch (reference:
    layers/control_flow.py:1525 ``IfElse``).

    The reference gathers the true/false row subsets into separate
    sub-blocks and scatters results back (dynamic row counts). The
    TPU-native design computes BOTH branches over the full dense batch
    and merges rows with a select — static shapes, XLA-fusable, same
    results for the row-wise computations the construct exists for (the
    branches cost compute for all rows; on the MXU that is cheaper than
    dynamic-shape gathers).

    Usage::

        ie = IfElse(cond)                  # cond [B, 1] bool
        with ie.true_block():
            ie.output(fc_true(ie.input(x)))
        with ie.false_block():
            ie.output(fc_false(ie.input(x)))
        out = ie()
    """

    def __init__(self, cond: Variable, name=None):
        self._cond = cond
        self._true_outs: List[Variable] = []
        self._false_outs: List[Variable] = []
        self._phase: Optional[str] = None

    @contextlib.contextmanager
    def true_block(self):
        self._phase = "true"
        try:
            yield
        finally:
            self._phase = None

    @contextlib.contextmanager
    def false_block(self):
        self._phase = "false"
        try:
            yield
        finally:
            self._phase = None

    def input(self, x: Variable) -> Variable:
        if self._phase is None:
            raise ValueError("IfElse.input() outside true_block/false_block")
        return x

    def output(self, *outs):
        if self._phase == "true":
            self._true_outs.extend(outs)
        elif self._phase == "false":
            self._false_outs.extend(outs)
        else:
            raise ValueError(
                "IfElse.output() outside true_block/false_block")

    def __call__(self):
        from paddle_tpu import layers

        if len(self._true_outs) != len(self._false_outs):
            raise ValueError(
                f"IfElse branches produced {len(self._true_outs)} vs "
                f"{len(self._false_outs)} outputs; they must align")
        merged = []
        for t, f in zip(self._true_outs, self._false_outs):
            merged.append(
                layers.where(_broadcast_row_mask(self._cond, t), t, f))
        return merged[0] if len(merged) == 1 else merged


_PRINT_UID = [0]


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Print a tensor's value whenever it is computed (reference:
    layers/control_flow.py:135 + operators/print_op.cc). The host print is
    staged with ``jax.debug.callback`` so it fires every executed step.
    ``print_phase`` 'backward'/'both' also prints the incoming gradient
    (emitted as a second print op by the grad maker). ``print_tensor_lod``
    is accepted for API parity; the dense/padded design has no LoD."""
    helper = LayerHelper("print")
    _PRINT_UID[0] += 1
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        "print",
        inputs={"In": input},
        outputs={"Out": out},
        attrs={
            "first_n": first_n,
            "summarize": summarize,
            "message": message or "",
            "print_tensor_name": print_tensor_name,
            "print_tensor_type": print_tensor_type,
            "print_tensor_shape": print_tensor_shape,
            "print_phase": print_phase.upper(),
            "is_forward": True,
            "var_name": input.name,
            "print_uid": _PRINT_UID[0],
        },
    )
    return out
