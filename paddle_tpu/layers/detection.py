"""Detection layer API (reference: python/paddle/fluid/layers/detection.py).

Signatures mirror the reference with one systematic change: ground-truth
inputs that were LoD tensors ([Ng, 4] with per-image offsets) are dense
padded tensors ([N, G, 4] with zero-area rows as padding, labels
alongside) — the SURVEY.md section 5 design. Outputs that were LoD lists
are fixed-capacity tensors plus counts/weights.
"""

from __future__ import annotations


from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.layers import nn as _nn

__all__ = [
    "iou_similarity", "box_coder", "prior_box", "density_prior_box",
    "anchor_generator", "bipartite_match", "target_assign", "ssd_loss",
    "detection_output", "multi_box_head", "yolov3_loss", "detection_map",
    "rpn_target_assign", "generate_proposals", "generate_proposal_labels",
    "distribute_fpn_proposals", "collect_fpn_proposals",
    "box_decoder_and_assign", "box_clip", "generate_mask_labels",
]


from paddle_tpu.layer_helper import append_simple_op as _op  # noqa: E402


def iou_similarity(x, y, name=None):
    """Pairwise IoU (reference: detection.py:328)."""
    return _op("iou_similarity", {"X": x, "Y": y}, name=name,
               stop_gradient=True)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None, axis=0):
    """Encode/decode boxes against priors (reference: detection.py:365)."""
    return _op("box_coder",
               {"PriorBox": prior_box, "PriorBoxVar": prior_box_var,
                "TargetBox": target_box},
               {"code_type": code_type, "box_normalized": box_normalized,
                "axis": axis},
               out_slots=("OutputBox",), name=name)


def box_clip(input, im_info, name=None):
    """Clip boxes to image bounds (reference: detection.py:2267)."""
    return _op("box_clip", {"Input": input, "ImInfo": im_info}, name=name,
               out_slots=("Output",))


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    """SSD prior boxes per feature-map cell (reference: detection.py:1247).
    Outputs Boxes/Variances [H, W, P, 4]."""
    attrs = {
        "min_sizes": list(min_sizes),
        "max_sizes": list(max_sizes or []),
        "aspect_ratios": list(aspect_ratios),
        "variances": list(variance),
        "flip": flip, "clip": clip,
        "step_w": steps[0], "step_h": steps[1], "offset": offset,
        "min_max_aspect_ratios_order": min_max_aspect_ratios_order,
    }
    return _op("prior_box", {"Input": input, "Image": image}, attrs,
               out_slots=("Boxes", "Variances"), name=name,
               stop_gradient=True)


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    """Density prior boxes (reference: detection.py:1369)."""
    attrs = {
        "densities": list(densities or []),
        "fixed_sizes": list(fixed_sizes or []),
        "fixed_ratios": list(fixed_ratios or []),
        "variances": list(variance), "clip": clip,
        "step_w": steps[0], "step_h": steps[1], "offset": offset,
        "flatten_to_2d": flatten_to_2d,
    }
    return _op("density_prior_box", {"Input": input, "Image": image}, attrs,
               out_slots=("Boxes", "Variances"), name=name,
               stop_gradient=True)


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    """RPN anchors per feature-map cell (reference: detection.py:1753)."""
    attrs = {
        "anchor_sizes": list(anchor_sizes or [64.0, 128.0, 256.0, 512.0]),
        "aspect_ratios": list(aspect_ratios or [0.5, 1.0, 2.0]),
        "variances": list(variance),
        "stride": list(stride or [16.0, 16.0]),
        "offset": offset,
    }
    return _op("anchor_generator", {"Input": input}, attrs,
               out_slots=("Anchors", "Variances"), name=name,
               stop_gradient=True)


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """Greedy bipartite matching (reference: detection.py:830).
    ``dist_matrix`` [G, P] or batched [N, G, P]."""
    return _op("bipartite_match", {"DistMat": dist_matrix},
               {"match_type": match_type or "bipartite",
                "dist_threshold":
                    0.5 if dist_threshold is None else dist_threshold},
               out_slots=("ColToRowMatchIndices", "ColToRowMatchDist"),
               dtypes=("int32", None), name=name, stop_gradient=True)


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    """Assign targets by match indices (reference: detection.py:916).
    ``input`` [N, G, K] dense per-image entities."""
    return _op("target_assign",
               {"X": input, "MatchIndices": matched_indices,
                "NegIndices": negative_indices},
               {"mismatch_value":
                    0.0 if mismatch_value is None else mismatch_value},
               out_slots=("Out", "OutWeight"), name=name,
               stop_gradient=True)


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None,
             name=None):
    """SSD multibox loss -> [N, 1] (reference: detection.py:1013; the
    bipartite-match/mining/target-assign pipeline runs as one fused dense
    op, see ops/detection_ops.py ssd_loss). ``gt_box`` [N, G, 4] padded
    dense, ``gt_label`` [N, G]."""
    if mining_type != "max_negative":
        raise ValueError("Only mining_type == 'max_negative' is supported")
    return _op("ssd_loss",
               {"Location": location, "Confidence": confidence,
                "GtBox": gt_box, "GtLabel": gt_label,
                "PriorBox": prior_box, "PriorBoxVar": prior_box_var},
               {"background_label": background_label,
                "overlap_threshold": overlap_threshold,
                "neg_pos_ratio": neg_pos_ratio, "neg_overlap": neg_overlap,
                "loc_loss_weight": loc_loss_weight,
                "conf_loss_weight": conf_loss_weight,
                "match_type": match_type, "normalize": normalize},
               out_slots=("Loss",), name=name)


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     name=None):
    """Decode + multiclass NMS (reference: detection.py:213). ``loc``
    [N, P, 4], ``scores`` [N, P, C] (post-softmax). Output
    [N, keep_top_k, 6] rows (label, score, x1, y1, x2, y2), label -1
    padding — the dense analog of the reference's LoD output."""
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    scores_t = _nn.transpose(scores, [0, 2, 1])     # [N, C, P]
    return _nn.multiclass_nms(
        decoded, scores_t, score_threshold=score_threshold,
        nms_top_k=nms_top_k, keep_top_k=keep_top_k,
        nms_threshold=nms_threshold, background_label=background_label,
        name=name)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head over multiple feature maps (reference:
    detection.py:1497): per-map loc/conf convs + prior boxes,
    concatenated. Returns (mbox_locs [N, P, 4], mbox_confs [N, P, C],
    boxes [P, 4], variances [P, 4])."""
    if isinstance(inputs, (list, tuple)) is False:
        inputs = [inputs]
    n_maps = len(inputs)
    if min_sizes is None:
        # reference ratio schedule (detection.py:1657)
        min_sizes, max_sizes = [], []
        step = int((max_ratio - min_ratio) / (n_maps - 2.0)) if n_maps > 2 \
            else 100
        min_sizes.append(base_size * 0.1)
        max_sizes.append(base_size * 0.2)
        for r in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * r / 100.0)
            max_sizes.append(base_size * (r + step) / 100.0)
        min_sizes = min_sizes[:n_maps]
        max_sizes = max_sizes[:n_maps]
    locs, confs, boxes_l, vars_l = [], [], [], []
    for i, x in enumerate(inputs):
        mins = min_sizes[i]
        maxs = max_sizes[i] if max_sizes else None
        mins = mins if isinstance(mins, (list, tuple)) else [mins]
        maxs = (maxs if isinstance(maxs, (list, tuple)) else [maxs]) \
            if maxs is not None else None
        ars = aspect_ratios[i]
        ars = ars if isinstance(ars, (list, tuple)) else [ars]
        step_pair = (steps[i] if steps else
                     ((step_w[i] if step_w else 0.0),
                      (step_h[i] if step_h else 0.0)))
        if not isinstance(step_pair, (list, tuple)):
            step_pair = (step_pair, step_pair)
        box, var = prior_box(
            x, image, mins, maxs, ars, variance, flip, clip,
            step_pair, offset,
            min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        # priors per cell: mirror the prior_box op's aspect-ratio dedup
        # (1.0 implicit; flip adds the reciprocal of each non-1 ratio)
        uniq = [1.0]
        for a in ars:
            if not any(abs(a - u) < 1e-6 for u in uniq):
                uniq.append(a)
                if flip:
                    uniq.append(1.0 / a)
        n_priors = len(mins) * len(uniq) + (len(maxs) if maxs else 0)
        loc = _nn.conv2d(x, n_priors * 4, kernel_size, stride=stride,
                         padding=pad)
        conf = _nn.conv2d(x, n_priors * num_classes, kernel_size,
                          stride=stride, padding=pad)
        # [N, P_i*4, H, W] -> [N, H, W, P_i*4] -> [N, -1, 4]
        loc = _nn.transpose(loc, [0, 2, 3, 1])
        conf = _nn.transpose(conf, [0, 2, 3, 1])
        locs.append(_nn.reshape(loc, [0, -1, 4]))
        confs.append(_nn.reshape(conf, [0, -1, num_classes]))
        boxes_l.append(_nn.reshape(box, [-1, 4]))
        vars_l.append(_nn.reshape(var, [-1, 4]))
    mbox_locs = _nn.concat(locs, axis=1)
    mbox_confs = _nn.concat(confs, axis=1)
    boxes = _nn.concat(boxes_l, axis=0)
    variances = _nn.concat(vars_l, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    """YOLOv3 loss -> [N] (reference: detection.py:536)."""
    loss, _, _ = _op(
        "yolov3_loss",
        {"X": x, "GTBox": gt_box, "GTLabel": gt_label, "GTScore": gt_score},
        {"anchors": list(anchors), "anchor_mask": list(anchor_mask),
         "class_num": class_num, "ignore_thresh": ignore_thresh,
         "downsample_ratio": downsample_ratio,
         "use_label_smooth": use_label_smooth},
        out_slots=("Loss", "ObjectnessMask", "GTMatchMask"),
        dtypes=(None, None, "int32"), name=name)
    return loss


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.5, evaluate_difficult=True,
                  has_state=None, input_states=None, out_states=None,
                  ap_version="integral", name=None, **_compat):
    """Batch mAP scalar (reference: detection.py:738). With
    ``has_state``/``input_states``/``out_states`` wired (the
    metrics.DetectionMAP accumulation path), ``input_states`` is the
    ``(pos_count [C], true_pos [C, B], false_pos [C, B])`` triple of
    fixed-size binned accumulator vars (see ops/detection_ops.py
    detection_map docstring for the static-shape redesign of the
    reference's LoD states), the same vars are updated in place as
    ``out_states``, and the return is the ``(batch mAP, accumulated
    mAP)`` pair — one op computes both, so the metric does not run the
    greedy matching twice."""
    attrs = {"class_num": class_num,
             "background_label": background_label,
             "overlap_threshold": overlap_threshold,
             "evaluate_difficult": evaluate_difficult,
             "ap_type": ap_version}
    if has_state is None:
        return _op("detection_map",
                   {"DetectRes": detect_res, "Label": label}, attrs,
                   out_slots=("MAP",), dtypes=("float32",), name=name,
                   stop_gradient=True)
    from paddle_tpu.framework import default_main_program
    from paddle_tpu.layer_helper import LayerHelper

    pos_count, true_pos, false_pos = input_states
    o_pos, o_tp, o_fp = out_states
    helper = LayerHelper("detection_map", name=name)
    accum_map = helper.create_variable_for_type_inference(
        dtype="float32", stop_gradient=True)
    batch_map = helper.create_variable_for_type_inference(
        dtype="float32", stop_gradient=True)
    attrs["score_bins"] = int(true_pos.shape[-1])
    default_main_program().current_block().append_op(
        "detection_map",
        inputs={"DetectRes": detect_res, "Label": label,
                "HasState": has_state, "PosCount": pos_count,
                "TruePos": true_pos, "FalsePos": false_pos},
        outputs={"MAP": batch_map, "AccumMAP": accum_map,
                 "AccumPosCount": o_pos, "AccumTruePos": o_tp,
                 "AccumFalsePos": o_fp},
        attrs=attrs)
    return batch_map, accum_map


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True, name=None):
    """RPN anchor labelling (reference: detection.py:61). Dense outputs:
    (score_label [N, M], score_weight [N, M], bbox_target [N, M, 4],
    bbox_weight [N, M, 4]) — losses contract with the weights instead of
    gathering LoD index lists."""
    return _op("rpn_target_assign",
               {"Anchor": anchor_box, "GtBoxes": gt_boxes,
                "ImInfo": im_info, "IsCrowd": is_crowd},
               {"rpn_batch_size_per_im": rpn_batch_size_per_im,
                "rpn_straddle_thresh": rpn_straddle_thresh,
                "rpn_fg_fraction": rpn_fg_fraction,
                "rpn_positive_overlap": rpn_positive_overlap,
                "rpn_negative_overlap": rpn_negative_overlap,
                "use_random": use_random},
               out_slots=("ScoreLabel", "ScoreWeight", "BboxTarget",
                          "BboxWeight"),
               name=name, stop_gradient=True)


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None):
    """RPN proposals (reference: detection.py:2162). Returns
    (rpn_rois [N, post_nms_top_n, 4], rpn_roi_probs [N, post_nms_top_n, 1],
    rois_num [N])."""
    return _op("generate_proposals",
               {"Scores": scores, "BboxDeltas": bbox_deltas,
                "ImInfo": im_info, "Anchors": anchors,
                "Variances": variances},
               {"pre_nms_topN": pre_nms_top_n,
                "post_nms_topN": post_nms_top_n,
                "nms_thresh": nms_thresh, "min_size": min_size, "eta": eta},
               out_slots=("RpnRois", "RpnRoiProbs", "RpnRoisNum"),
               dtypes=(None, None, "int32"), name=name, stop_gradient=True)


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.25,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False,
                             name=None):
    """Second-stage RoI sampling (reference: detection.py:1907). Returns
    (rois [N, B, 4], labels_int32 [N, B], bbox_targets
    [N, B, 4*class_nums], bbox_inside_weights, bbox_outside_weights)."""
    return _op("generate_proposal_labels",
               {"RpnRois": rpn_rois, "GtClasses": gt_classes,
                "GtBoxes": gt_boxes, "ImInfo": im_info,
                "IsCrowd": is_crowd},
               {"batch_size_per_im": batch_size_per_im,
                "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
                "bg_thresh_hi": bg_thresh_hi, "bg_thresh_lo": bg_thresh_lo,
                "class_nums": class_nums or 81, "use_random": use_random},
               out_slots=("Rois", "LabelsInt32", "BboxTargets",
                          "BboxInsideWeights", "BboxOutsideWeights"),
               dtypes=(None, "int32", None, None, None),
               name=name, stop_gradient=True)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, name=None):
    """Route RoIs to FPN levels (reference: detection.py:2433). Returns
    (multi_rois: list of [N, R, 4] per level, restore_ind [N, R])."""
    helper = LayerHelper("distribute_fpn_proposals", name=name)
    n_levels = max_level - min_level + 1
    outs = [helper.create_variable_for_type_inference(
        dtype=fpn_rois.dtype, stop_gradient=True) for _ in range(n_levels)]
    nums = [helper.create_variable_for_type_inference(
        dtype="int32", stop_gradient=True) for _ in range(n_levels)]
    restore = helper.create_variable_for_type_inference(
        dtype="int32", stop_gradient=True)
    helper.append_op(
        "distribute_fpn_proposals", inputs={"FpnRois": fpn_rois},
        outputs={"MultiFpnRois": outs, "MultiLevelRoIsNum": nums,
                 "RestoreInd": restore},
        attrs={"min_level": min_level, "max_level": max_level,
               "refer_level": refer_level, "refer_scale": refer_scale})
    return outs, restore


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, name=None):
    """Merge per-level RoIs by score (reference: detection.py:2569)."""
    rois, _num = _op("collect_fpn_proposals",
                     {"MultiLevelRois": list(multi_rois),
                      "MultiLevelScores": list(multi_scores)},
                     {"post_nms_topN": post_nms_top_n},
                     out_slots=("FpnRois", "RoisNum"),
                     dtypes=(None, "int32"), name=name, stop_gradient=True)
    return rois


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip, name=None):
    """Per-class decode + best-class assign (reference:
    detection.py:2507)."""
    return _op("box_decoder_and_assign",
               {"PriorBox": prior_box, "PriorBoxVar": prior_box_var,
                "TargetBox": target_box, "BoxScore": box_score},
               {"box_clip": box_clip},
               out_slots=("DecodeBox", "OutputAssignBox"), name=name,
               stop_gradient=True)


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution,
                         poly_lens=None, name=None):
    """Mask R-CNN mask targets (reference: detection.py
    generate_mask_labels). Dense-padded polygons: ``gt_segms``
    [N, G, Q, V, 2] with ``poly_lens`` [N, G, Q] vertex counts replace
    the reference's 3-level LoD. Returns (mask_rois, roi_has_mask_int32,
    mask_int32) plus a per-image fg count var."""
    ins = {"ImInfo": im_info, "GtClasses": gt_classes,
           "IsCrowd": is_crowd, "GtSegms": gt_segms, "Rois": rois,
           "LabelsInt32": labels_int32, "PolyLens": poly_lens}
    mask_rois, has_mask, mask_i32, mask_num = _op(
        "generate_mask_labels", ins,
        {"num_classes": num_classes, "resolution": resolution},
        out_slots=("MaskRois", "RoiHasMaskInt32", "MaskInt32", "MaskNum"),
        dtypes=("float32", "int32", "int32", "int32"), name=name,
        stop_gradient=True)
    return mask_rois, has_mask, mask_i32, mask_num
