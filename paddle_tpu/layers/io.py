"""Data layers (reference: python/paddle/fluid/layers/io.py).

``data`` declares a feed variable; there are no feed/fetch *ops* — the
executor binds feeds directly into the lowered XLA computation
(core/lowering.py), and device prefetch is the double-buffered host pipeline
in reader/ (the analog of the reference's buffered_reader.cc).
"""

from __future__ import annotations

from typing import Sequence

from paddle_tpu.framework import convert_np_dtype_to_dtype_, default_main_program

__all__ = ["data"]


def data(
    name: str,
    shape: Sequence[int],
    append_batch_size: bool = True,
    dtype: str = "float32",
    lod_level: int = 0,
    type=None,
    stop_gradient: bool = True,
):
    """Declare an input variable (reference: layers/io.py data).

    ``lod_level`` is accepted for source compatibility; variable-length data
    is represented as padded dense + mask/length (SURVEY.md section 5), so it
    has no effect here.
    """
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = default_main_program().global_block()
    return block.create_var(
        name=name,
        shape=shape,
        dtype=convert_np_dtype_to_dtype_(dtype),
        persistable=False,
        stop_gradient=stop_gradient,
    )
