"""Learning-rate schedules as graph ops
(reference: python/paddle/fluid/layers/learning_rate_scheduler.py).

Each schedule creates a persistable global-step counter in the main program,
increments it once per step, and computes the decayed LR with ordinary ops —
so the whole schedule lives inside the compiled step function.
"""

from __future__ import annotations

import math

from paddle_tpu import unique_name
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.layers import nn, tensor

__all__ = [
    "noam_decay", "exponential_decay", "natural_exp_decay",
    "inverse_time_decay", "polynomial_decay", "piecewise_decay",
    "cosine_decay", "linear_lr_warmup",
]


def _global_step():
    """Create + auto-increment a float32 global step counter."""
    name = unique_name.generate("learning_rate_sched_step")
    step = tensor.create_global_var(
        shape=[1], value=0.0, dtype="float32", persistable=True, name=name
    )
    nn.increment(step, value=1.0, in_place=True)
    return step


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    """lr = lr0 * d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)
    (reference: learning_rate_scheduler.py noam_decay)."""
    step = _global_step()
    a = nn.pow(step, factor=-0.5)
    b = nn.scale(step, scale=warmup_steps ** -1.5)
    lr = nn.scale(
        nn.elementwise_min(a, b),
        scale=float(learning_rate) * d_model ** -0.5,
    )
    return lr


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step()
    exponent = nn.scale(step, scale=1.0 / decay_steps)
    if staircase:
        exponent = nn.elementwise_floordiv(
            step, tensor.fill_constant([1], "float32", float(decay_steps))
        )
    factor = nn.elementwise_pow(
        tensor.fill_constant([1], "float32", decay_rate), exponent
    )
    return nn.scale(factor, scale=float(learning_rate))


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step()
    exponent = nn.scale(step, scale=1.0 / decay_steps)
    if staircase:
        exponent = nn.elementwise_floordiv(
            step, tensor.fill_constant([1], "float32", float(decay_steps))
        )
    return nn.scale(nn.exp(nn.scale(exponent, scale=-decay_rate)),
                    scale=float(learning_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step()
    ratio = nn.scale(step, scale=1.0 / decay_steps)
    if staircase:
        ratio = nn.elementwise_floordiv(
            step, tensor.fill_constant([1], "float32", float(decay_steps))
        )
    denom = nn.scale(ratio, scale=decay_rate, bias=1.0)
    return nn.elementwise_div(
        tensor.fill_constant([1], "float32", float(learning_rate)), denom
    )


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    step = _global_step()
    capped = nn.elementwise_min(
        step, tensor.fill_constant([1], "float32", float(decay_steps))
    )
    ratio = nn.scale(capped, scale=1.0 / decay_steps)
    one_minus = nn.scale(ratio, scale=-1.0, bias=1.0)
    decayed = nn.pow(one_minus, factor=power)
    return nn.scale(decayed, scale=float(learning_rate - end_learning_rate),
                    bias=float(end_learning_rate))


def piecewise_decay(boundaries, values):
    """Piecewise-constant LR via nested where ops."""
    assert len(boundaries) + 1 == len(values)
    step = _global_step()
    lr = tensor.fill_constant([1], "float32", float(values[-1]))
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        cond = nn.less_than(
            step, tensor.fill_constant([1], "float32", float(b))
        )
        lr = nn.where(cond, tensor.fill_constant([1], "float32", float(v)), lr)
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _global_step()
    helper = LayerHelper("cosine_decay")
    epoch_f = nn.scale(step, scale=1.0 / step_each_epoch)
    theta = nn.scale(epoch_f, scale=math.pi / epochs)
    cos_out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op("cos", inputs={"X": theta}, outputs={"Out": cos_out})
    return nn.scale(cos_out, scale=0.5 * learning_rate, bias=0.5 * learning_rate)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    step = _global_step()
    ratio = nn.scale(step, scale=1.0 / warmup_steps)
    warm = nn.scale(ratio, scale=float(end_lr - start_lr), bias=float(start_lr))
    cond = nn.less_than(
        step, tensor.fill_constant([1], "float32", float(warmup_steps))
    )
    if not hasattr(learning_rate, "name"):
        learning_rate = tensor.fill_constant([1], "float32", float(learning_rate))
    return nn.where(cond, warm, learning_rate)
