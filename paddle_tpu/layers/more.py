"""Layer-API parity tail (reference: python/paddle/fluid/layers/
{nn,tensor,control_flow}.py names not yet exported elsewhere).

Thin graph-building wrappers over already-registered kernels — the op
library has covered these for rounds; this module closes the LAYER
surface so reference user code ports name-for-name. Dense/padded
redesigns (sequence ops over [B, T, ...] + Length, fixed-capacity
arrays) are documented per function.
"""

from __future__ import annotations

import builtins

from paddle_tpu.framework import Variable
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.param_attr import ParamAttr

__all__ = [
    # activations / elementwise
    "brelu", "soft_relu", "stanh", "selu", "sign", "logical_xor",
    "reduce_all", "reduce_any", "rank", "sum", "reverse", "argsort",
    "diag", "cos_sim", "multiplex", "isfinite", "has_inf", "has_nan",
    "greater_equal", "less_equal", "not_equal", "is_empty",
    # losses
    "bpr_loss", "dice_loss", "kldiv_loss", "log_loss", "margin_rank_loss",
    "npair_loss", "rank_loss", "hinge_loss",
    "teacher_student_sigmoid_loss", "sampled_softmax_with_cross_entropy",
    # shape / vision
    "adaptive_pool2d", "adaptive_pool3d", "pad2d", "pad_constant_like",
    "crop", "pixel_shuffle", "shuffle_channel", "space_to_depth",
    "temporal_shift", "grid_sampler", "affine_channel", "data_norm",
    "row_conv", "fsp_matrix", "image_resize", "resize_bilinear",
    "resize_nearest", "image_resize_short", "pool3d", "conv3d_transpose",
    "random_crop", "psroi_pool", "roi_perspective_transform",
    "polygon_box_transform", "similarity_focus", "continuous_value_model",
    "sampling_id",
    # sequence (dense/padded)
    "sequence_concat", "sequence_enumerate", "sequence_expand_as",
    "sequence_first_step", "sequence_last_step", "sequence_pad",
    "sequence_unpad", "sequence_reshape", "sequence_scatter",
    "sequence_slice",
    # tensor / control flow / misc
    "fill_constant_batch_size_like", "uniform_random_batch_size_like",
    "gaussian_random_batch_size_like", "range",
    "create_array", "array_write", "array_read", "array_length",
    "autoincreased_step_counter", "lod_reset",
    # rnn units
    "dynamic_lstmp", "lstm_unit", "gru_unit", "lstm",
    "tensor_array_to_tensor",
    # decode
    "beam_search", "beam_search_decode",
]


from paddle_tpu.layer_helper import append_simple_op as _op  # noqa: E402


# --------------------------------------------------------------------------
# activations / elementwise / comparison
# --------------------------------------------------------------------------


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    """clip(x, t_min, t_max) (reference: brelu op)."""
    return _op("brelu", {"X": x}, {"t_min": t_min, "t_max": t_max},
               name=name)


def soft_relu(x, threshold=40.0, name=None):
    return _op("soft_relu", {"X": x}, {"threshold": threshold}, name=name)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _op("stanh", {"X": x},
               {"scale_a": scale_a, "scale_b": scale_b}, name=name)


def selu(x, scale=None, alpha=None, name=None):
    attrs = {}
    if scale is not None:
        attrs["scale"] = scale
    if alpha is not None:
        attrs["alpha"] = alpha
    return _op("selu", {"X": x}, attrs, name=name)


def sign(x, name=None):
    return _op("sign", {"X": x}, name=name)


def logical_xor(x, y, out=None, name=None):
    return _op("logical_xor", {"X": x, "Y": y}, name=name,
               dtypes=("bool",), stop_gradient=True)


def _dims(dim):
    if dim is None:
        return None
    return [dim] if isinstance(dim, int) else list(dim)


def reduce_all(input, dim=None, keep_dim=False, name=None):
    return _op("reduce_all", {"X": input},
               {"dim": _dims(dim), "keep_dim": keep_dim},
               dtypes=("bool",), name=name, stop_gradient=True)


def reduce_any(input, dim=None, keep_dim=False, name=None):
    return _op("reduce_any", {"X": input},
               {"dim": _dims(dim), "keep_dim": keep_dim},
               dtypes=("bool",), name=name, stop_gradient=True)


def rank(input):
    """Static rank as a constant tensor (reference: layers/nn.py rank)."""
    from paddle_tpu.layers.tensor import fill_constant

    return fill_constant(shape=[1], dtype="int32",
                         value=len(input.shape or ()))


def sum(x, name=None):
    """Elementwise sum of a list of tensors (reference: sum op)."""
    from paddle_tpu.layers.nn import sums

    return sums(x if isinstance(x, (list, tuple)) else [x])


def reverse(x, axis, name=None):
    return _op("reverse", {"X": x},
               {"axis": [axis] if isinstance(axis, int) else list(axis)},
               name=name)


def argsort(input, axis=-1, descending=False, name=None):
    out, ids = _op("argsort", {"X": input},
                   {"axis": axis, "descending": descending},
                   out_slots=("Out", "Indices"), dtypes=(None, "int64"),
                   name=name)
    return out, ids


def diag(diagonal, name=None):
    return _op("diag", {"Diagonal": diagonal}, name=name)


def cos_sim(X, Y, name=None):
    return _op("cos_sim", {"X": X, "Y": Y}, name=name)


def multiplex(inputs, index, name=None):
    helper = LayerHelper("multiplex", name=name)
    out = helper.create_variable_for_type_inference(dtype=inputs[0].dtype)
    helper.append_op("multiplex",
                     inputs={"X": list(inputs), "Ids": [index]},
                     outputs={"Out": [out]}, attrs={})
    return out


def isfinite(x, name=None):
    return _op("isfinite", {"X": x}, dtypes=("bool",), name=name,
               stop_gradient=True)


def has_inf(x, name=None):
    """True when any element is +-inf (reference: isinf op)."""
    return _op("has_inf", {"X": x}, dtypes=("bool",), name=name,
               stop_gradient=True)


def has_nan(x, name=None):
    """True when any element is NaN (reference: isnan op)."""
    return _op("has_nan", {"X": x}, dtypes=("bool",), name=name,
               stop_gradient=True)


def greater_equal(x, y, cond=None, name=None):
    return _op("greater_equal", {"X": x, "Y": y}, dtypes=("bool",),
               name=name, stop_gradient=True)


def less_equal(x, y, cond=None, name=None):
    return _op("less_equal", {"X": x, "Y": y}, dtypes=("bool",),
               name=name, stop_gradient=True)


def not_equal(x, y, cond=None, name=None):
    return _op("not_equal", {"X": x, "Y": y}, dtypes=("bool",),
               name=name, stop_gradient=True)


def is_empty(x, cond=None, name=None):
    return _op("is_empty", {"X": x}, dtypes=("bool",), name=name,
               stop_gradient=True)


# --------------------------------------------------------------------------
# losses
# --------------------------------------------------------------------------


def bpr_loss(input, label, name=None):
    return _op("bpr_loss", {"X": input, "Label": label},
               out_slots=("Y",), name=name)


def dice_loss(input, label, epsilon=1e-5):
    """Composed exactly as the reference layer (layers/nn.py dice_loss):
    1 - 2*|intersection| / (|input| + |label|)."""
    from paddle_tpu.layers import nn as _nn

    if label.shape and int(label.shape[-1]) == 1:
        label = _nn.squeeze(label, [-1])
    label = _nn.one_hot(label, depth=input.shape[-1])
    reduce_dims = list(builtins.range(1, len(input.shape or ())))
    inse = _nn.reduce_sum(_nn.elementwise_mul(input, label),
                          dim=reduce_dims)
    dice_denominator = _nn.elementwise_add(
        _nn.reduce_sum(input, dim=reduce_dims),
        _nn.reduce_sum(label, dim=reduce_dims))
    dice_score = _nn.elementwise_sub(
        _nn.fill_constant_like(inse, 1.0),
        _nn.elementwise_div(
            _nn.scale(inse, scale=2.0),
            _nn.scale(dice_denominator, bias=epsilon)))
    return _nn.reduce_mean(dice_score)


def kldiv_loss(x, target, reduction="mean", name=None):
    return _op("kldiv_loss", {"X": x, "Target": target},
               {"reduction": reduction}, out_slots=("Loss",), name=name)


def log_loss(input, label, epsilon=1e-4, name=None):
    return _op("log_loss", {"Predicted": input, "Labels": label},
               {"epsilon": epsilon}, out_slots=("Loss",), name=name)


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    out, _act = _op("margin_rank_loss",
                    {"Label": label, "X1": left, "X2": right},
                    {"margin": margin}, out_slots=("Out", "Activated"),
                    name=name)
    return out


def rank_loss(label, left, right, name=None):
    return _op("rank_loss", {"Label": label, "Left": left, "Right": right},
               name=name)


def hinge_loss(input, label, name=None):
    return _op("hinge_loss", {"Logits": input, "Labels": label},
               out_slots=("Loss",), name=name)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """Composed as the reference layer (layers/nn.py npair_loss):
    cross entropy over anchor@positive^T similarities + L2 on both."""
    from paddle_tpu.layers import nn as _nn

    labels = _nn.cast(_nn.reshape(labels, [-1, 1]), "float32")
    same = _nn.cast(
        _nn.equal(labels, _nn.transpose(labels, [1, 0])), "float32")
    batch = int(anchor.shape[0])
    row_sums = _nn.expand(
        _nn.reshape(_nn.reduce_sum(same, dim=1), [-1, 1]), [1, batch])
    norm = _nn.elementwise_div(same, row_sums)
    sim = _nn.matmul(anchor, positive, transpose_y=True)
    ce = _nn.reduce_mean(_nn.reduce_sum(
        _nn.elementwise_mul(
            _nn.scale(_nn.log_softmax(sim), scale=-1.0), norm), dim=1))
    l2 = _nn.scale(
        _nn.elementwise_add(
            _nn.reduce_mean(_nn.reduce_sum(
                _nn.elementwise_mul(anchor, anchor), dim=1)),
            _nn.reduce_mean(_nn.reduce_sum(
                _nn.elementwise_mul(positive, positive), dim=1))),
        scale=l2_reg * 0.25)
    return _nn.elementwise_add(ce, l2)


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    return _op("teacher_student_sigmoid_loss",
               {"X": input, "Label": label},
               {"soft_max_up_bound": soft_max_up_bound,
                "soft_max_lower_bound": soft_max_lower_bound},
               out_slots=("Y",))


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """Composed as the reference layer: sample_logits then
    softmax_with_cross_entropy on the sampled slice."""
    from paddle_tpu.layers import nn as _nn

    sampled_logits, sampled_label = _nn.sample_logits(
        logits, label, num_samples,
        remove_accidental_hits=remove_accidental_hits)
    return _nn.softmax_with_cross_entropy(sampled_logits, sampled_label)


# --------------------------------------------------------------------------
# shape / vision
# --------------------------------------------------------------------------


def adaptive_pool2d(input, pool_size, pool_type="max",
                    require_index=False, name=None):
    if require_index:
        raise ValueError("require_index is not supported (dense design "
                         "returns values only)")
    return _op("adaptive_pool2d", {"X": input},
               {"ksize": list(pool_size), "pooling_type": pool_type},
               name=name)


def adaptive_pool3d(input, pool_size, pool_type="max",
                    require_index=False, name=None):
    if require_index:
        raise ValueError("require_index is not supported")
    return _op("adaptive_pool3d", {"X": input},
               {"ksize": list(pool_size), "pooling_type": pool_type},
               name=name)


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    if data_format != "NCHW":
        raise ValueError("pad2d: only NCHW is supported")
    return _op("pad2d", {"X": input},
               {"paddings": list(paddings), "mode": mode,
                "pad_value": pad_value}, name=name)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    return _op("pad_constant_like", {"X": x, "Y": y},
               {"pad_value": pad_value}, name=name)


def crop(x, shape=None, offsets=None, name=None):
    if shape is None or isinstance(shape, Variable):
        raise ValueError("crop: pass a static `shape` list (dense design)")
    return _op("crop", {"X": x},
               {"shape": list(shape),
                "offsets": list(offsets or [0] * len(shape))}, name=name)


def pixel_shuffle(x, upscale_factor, name=None):
    return _op("pixel_shuffle", {"X": x},
               {"upscale_factor": upscale_factor}, name=name)


def shuffle_channel(x, group, name=None):
    return _op("shuffle_channel", {"X": x}, {"group": group}, name=name)


def space_to_depth(x, blocksize, name=None):
    return _op("space_to_depth", {"X": x}, {"blocksize": blocksize},
               name=name)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    return _op("temporal_shift", {"X": x},
               {"seg_num": seg_num, "shift_ratio": shift_ratio}, name=name)


def grid_sampler(x, grid, name=None):
    return _op("grid_sampler", {"X": x, "Grid": grid}, name=name)


def affine_channel(x, scale=None, bias=None, data_layout="NCHW",
                   name=None, act=None):
    out = _op("affine_channel", {"X": x, "Scale": scale, "Bias": bias},
              {"data_layout": data_layout}, name=name)
    if act:
        helper = LayerHelper("affine_channel", act=act)
        out = helper.append_activation(out)
    return out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=False):
    """Accumulated-statistics normalization (reference: layers/nn.py
    data_norm + data_norm_op.cc). The three accumulators are persistable
    parameters updated by the training program externally (as the
    reference's gradient-less stats params)."""
    helper = LayerHelper("data_norm", name=name, act=act)
    c = int(input.shape[-1] if data_layout == "NHWC" else input.shape[1])
    from paddle_tpu.initializer import ConstantInitializer

    bsize = helper.create_parameter(
        ParamAttr._to_attr(param_attr), [c], input.dtype,
        default_initializer=ConstantInitializer(1e4))
    bsum = helper.create_parameter(
        ParamAttr(name=(name or helper.name) + ".batch_sum",
                  initializer=ConstantInitializer(0.0)), [c], input.dtype)
    bsq = helper.create_parameter(
        ParamAttr(name=(name or helper.name) + ".batch_square_sum",
                  initializer=ConstantInitializer(1e4)), [c], input.dtype)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    means = helper.create_variable_for_type_inference(dtype=input.dtype)
    scales = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        "data_norm",
        inputs={"X": [input], "BatchSize": [bsize], "BatchSum": [bsum],
                "BatchSquareSum": [bsq]},
        outputs={"Y": [out], "Means": [means], "Scales": [scales]},
        attrs={"epsilon": epsilon})
    return helper.append_activation(out)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (reference: layers/nn.py row_conv)."""
    helper = LayerHelper("row_conv", act=act)
    d = int(input.shape[-1])
    w = helper.create_parameter(
        ParamAttr._to_attr(param_attr), [future_context_size + 1, d],
        input.dtype)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("row_conv", inputs={"X": [input], "Filter": [w]},
                     outputs={"Out": [out]}, attrs={})
    return helper.append_activation(out)


def fsp_matrix(x, y):
    return _op("fsp", {"X": x, "Y": y})


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None,
                 align_corners=True, align_mode=1):
    op = {"BILINEAR": "bilinear_interp",
          "NEAREST": "nearest_interp"}.get(resample.upper())
    if op is None:
        raise ValueError(f"image_resize: unsupported resample {resample}")
    attrs = {"align_corners": align_corners, "align_mode": align_mode}
    if scale:
        attrs["scale"] = float(scale)
    if out_shape is not None:
        attrs["out_h"], attrs["out_w"] = int(out_shape[0]), int(out_shape[1])
    return _op(op, {"X": input}, attrs, name=name)


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1):
    return image_resize(input, out_shape, scale, name, "BILINEAR",
                        actual_shape, align_corners, align_mode)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True):
    return image_resize(input, out_shape, scale, name, "NEAREST",
                        actual_shape, align_corners, align_mode=1)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize so the SHORT side equals out_short_len (reference:
    layers/nn.py image_resize_short); static shapes."""
    h, w = int(input.shape[2]), int(input.shape[3])
    short, long_ = (h, w) if h < w else (w, h)
    new_long = int(long_ * out_short_len / short)
    out_shape = ([out_short_len, new_long] if h < w
                 else [new_long, out_short_len])
    return image_resize(input, out_shape=out_shape, resample=resample)


def pool3d(input, pool_size=2, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True):
    def _trip(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    ks = _trip(pool_size)
    if global_pooling:
        ks = [int(d) for d in input.shape[2:5]]
    return _op("pool3d", {"X": input},
               {"ksize": ks, "strides": _trip(pool_stride),
                "paddings": _trip(pool_padding), "pooling_type": pool_type,
                "exclusive": exclusive}, name=name)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    helper = LayerHelper("conv3d_transpose", name=name,
                         bias_attr=bias_attr, act=act)
    c_in = int(input.shape[1])

    def _trip0(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    if filter_size is None:
        if output_size is None:
            raise ValueError(
                "conv3d_transpose needs filter_size or output_size")
        # derive the filter from the requested output extent (reference:
        # conv_transpose layer): k = out - (in-1)*s + 2p - ... solved per
        # dim for dilation 1
        outs3 = _trip0(output_size)
        st3, pd3, dl3 = _trip0(stride), _trip0(padding), _trip0(dilation)
        fs = []
        for i in range(3):
            k = (outs3[i] - (int(input.shape[2 + i]) - 1) * st3[i]
                 + 2 * pd3[i] - 1) // dl3[i] + 1
            fs.append(int(k))
    else:
        fs = _trip0(filter_size)
        if output_size is not None:
            raise ValueError(
                "conv3d_transpose: pass filter_size OR output_size, "
                "not both (static-shape design derives one from the "
                "other)")
    g = groups or 1
    w = helper.create_parameter(
        ParamAttr._to_attr(param_attr), [c_in, num_filters // g] + fs,
        input.dtype)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)

    def _trip(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    helper.append_op(
        "conv3d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": _trip(stride), "paddings": _trip(padding),
               "dilations": _trip(dilation), "groups": g})
    from paddle_tpu.layers.nn import _conv_bias

    out = _conv_bias(helper, out)
    return helper.append_activation(out)


def random_crop(x, shape, seed=None):
    return _op("random_crop", {"X": x}, {"shape": list(shape)})


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, name=None, rois_num=None):
    ins = {"X": input, "ROIs": rois}
    if rois_num is not None:
        ins["RoisNum"] = rois_num
    return _op("psroi_pool", ins,
               {"output_channels": output_channels,
                "spatial_scale": spatial_scale,
                "pooled_height": pooled_height,
                "pooled_width": pooled_width}, name=name)


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              name=None):
    return _op("roi_perspective_transform", {"X": input, "ROIs": rois},
               {"transformed_height": transformed_height,
                "transformed_width": transformed_width,
                "spatial_scale": spatial_scale}, name=name)


def polygon_box_transform(input, name=None):
    return _op("polygon_box_transform", {"Input": input},
               out_slots=("Output",), name=name)


def similarity_focus(input, axis, indexes, name=None):
    return _op("similarity_focus", {"X": input},
               {"axis": axis, "indexes": list(indexes)}, name=name)


def continuous_value_model(input, cvm, use_cvm=True):
    return _op("cvm", {"X": input, "CVM": cvm}, {"use_cvm": use_cvm},
               out_slots=("Y",))


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    return _op("sampling_id", {"X": x}, {"min": min, "max": max,
                                         "seed": seed},
               dtypes=("int64",), stop_gradient=True)


# --------------------------------------------------------------------------
# sequence tail (dense/padded: [B, T, ...] + Length)
# --------------------------------------------------------------------------


def sequence_concat(input, name=None):
    """Concatenate along TIME (reference: sequence_concat_op.cc); dense
    design concatenates the padded time axes."""
    helper = LayerHelper("sequence_concat", name=name)
    out = helper.create_variable_for_type_inference(dtype=input[0].dtype)
    helper.append_op("sequence_concat", inputs={"X": list(input)},
                     outputs={"Out": [out]}, attrs={})
    return out


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    return _op("sequence_enumerate", {"X": input},
                   {"win_size": win_size, "pad_value": pad_value})


def sequence_expand_as(x, y, name=None):
    return _op("sequence_expand_as", {"X": x, "Y": y})


def sequence_first_step(input, length=None):
    ins = {"X": input}
    if length is not None:
        ins["Length"] = length
    return _op("sequence_pool", ins, {"pooltype": "FIRST"})


def sequence_last_step(input, length=None):
    ins = {"X": input}
    if length is not None:
        ins["Length"] = length
    return _op("sequence_pool", ins, {"pooltype": "LAST"})


def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    ins = {"X": x, "PadValue": pad_value}
    if length is not None:
        ins["Length"] = length
    out, out_len = _op("sequence_pad", ins,
                       {"padded_length": maxlen or -1},
                       out_slots=("Out", "OutLength"),
                       dtypes=(None, "int64"))
    return out, out_len


def sequence_unpad(x, length, name=None):
    return _op("sequence_unpad", {"X": x, "Length": length})


def sequence_reshape(input, new_dim):
    return _op("sequence_reshape", {"X": input}, {"new_dim": new_dim})


def sequence_scatter(input, index, updates, name=None):
    return _op("sequence_scatter",
                   {"X": input, "Ids": index, "Updates": updates})


def sequence_slice(input, offset, length, name=None):
    return _op("sequence_slice",
                   {"X": input, "Offset": offset, "Length": length})


# --------------------------------------------------------------------------
# tensor / control flow / misc
# --------------------------------------------------------------------------


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    return _op("fill_constant_batch_size_like", {"Input": input},
               {"shape": list(shape), "dtype": dtype, "value": value,
                "input_dim_idx": input_dim_idx,
                "output_dim_idx": output_dim_idx},
               dtypes=(dtype,), stop_gradient=True)


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    return _op("uniform_random_batch_size_like", {"Input": input},
               {"shape": list(shape), "dtype": dtype, "min": min,
                "max": max, "input_dim_idx": input_dim_idx,
                "output_dim_idx": output_dim_idx},
               dtypes=(dtype,), stop_gradient=True)


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    return _op("gaussian_random_batch_size_like", {"Input": input},
               {"shape": list(shape), "dtype": dtype, "mean": mean,
                "std": std, "input_dim_idx": input_dim_idx,
                "output_dim_idx": output_dim_idx},
               dtypes=(dtype,), stop_gradient=True)


def range(start, end, step, dtype):
    from paddle_tpu.layers.tensor import range_

    return range_(start, end, step, dtype)


def create_array(dtype, maxlen, template=None, value=0.0):
    """Fixed-capacity dense array (reference LoDTensorArray analog;
    see control_flow.array_fill — XLA needs static shapes, so the
    capacity is declared up front)."""
    from paddle_tpu.layers.control_flow import array_fill

    if template is None:
        raise ValueError(
            "create_array needs a `template` variable: the dense design "
            "preallocates [maxlen, *template.shape]")
    return array_fill(maxlen, template, value)


def array_write(x, i, array):
    """Write x at position i (reference: array_write). Returns the
    UPDATED array (functional, not in-place: XLA values are immutable)."""
    from paddle_tpu.layers.control_flow import array_write_step

    return array_write_step(array, i, x)


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(dtype=array.dtype)
    helper.append_op("dynamic_slice",
                     inputs={"X": [array], "Index": [i]},
                     outputs={"Out": [out]}, attrs={})
    return out


def array_length(array):
    """Capacity of a dense array (static; reference returned the dynamic
    length — the dense design tracks live length separately when
    needed)."""
    from paddle_tpu.layers.tensor import fill_constant

    return fill_constant(shape=[1], dtype="int64",
                         value=int((array.shape or (0,))[0]))


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Persistable step counter incremented each run (reference:
    layers/nn.py autoincreased_step_counter)."""
    from paddle_tpu.framework import default_startup_program

    helper = LayerHelper("step_counter")
    name = counter_name or "@STEP_COUNTER@"
    block = helper.main_program.global_block()
    counter = block.create_var(name=name, shape=(1,), dtype="int64",
                               persistable=True)
    sb = default_startup_program().global_block()
    sv = sb.create_var(name=name, shape=(1,), dtype="int64",
                       persistable=True)
    sb.append_op("fill_constant",
                 inputs={}, outputs={"Out": [sv]},
                 attrs={"shape": [1], "dtype": "int64",
                        "value": float(begin - step)})
    out = helper.create_variable_for_type_inference(dtype="int64",
                                                    stop_gradient=True)
    helper.append_op("increment", inputs={"X": [counter]},
                     outputs={"Out": [out]}, attrs={"step": float(step)})
    helper.append_op("assign", inputs={"X": [out]},
                     outputs={"Out": [counter]}, attrs={})
    return out


def lod_reset(x, y=None, target_lod=None):
    """Identity in the dense/padded design: sequence structure is carried
    by explicit Length tensors, not LoD metadata (SURVEY.md §5), so
    re-binning offsets has no dense meaning. Returns x unchanged."""
    return x


# --------------------------------------------------------------------------
# rnn units
# --------------------------------------------------------------------------


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=False, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None):
    """Projected LSTM over padded [B, T, 4*hidden/4] input (reference:
    layers/nn.py dynamic_lstmp over lstmp_op)."""
    if use_peepholes:
        raise ValueError("dynamic_lstmp: peepholes unsupported "
                         "(matches dynamic_lstm's dense design)")
    helper = LayerHelper("dynamic_lstmp", name=name, bias_attr=bias_attr)
    hidden = size // 4
    w = helper.create_parameter(ParamAttr._to_attr(param_attr),
                                [proj_size, 4 * hidden], dtype)
    wp = helper.create_parameter(
        ParamAttr(name=(name or helper.name) + ".w_proj"),
        [hidden, proj_size], dtype)
    b = helper.create_parameter(
        ParamAttr._to_attr(bias_attr), [4 * hidden], dtype, is_bias=True)
    proj = helper.create_variable_for_type_inference(dtype=dtype)
    cell = helper.create_variable_for_type_inference(dtype=dtype)
    lstmp_ins = {"Input": [input], "Weight": [w], "ProjWeight": [wp]}
    if b is not None:
        lstmp_ins["Bias"] = [b]
    helper.append_op(
        "lstmp",
        inputs=lstmp_ins,
        outputs={"Projection": [proj], "Cell": [cell]},
        attrs={"is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation,
               "proj_activation": proj_activation})
    return proj, cell


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """One LSTM step (reference: layers/nn.py lstm_unit): fc over
    [x, h_prev] then the lstm_unit op."""
    from paddle_tpu.layers import nn as _nn

    helper = LayerHelper("lstm_unit", name=name)
    hidden = int(hidden_t_prev.shape[-1])
    concat = _nn.concat([x_t, hidden_t_prev], axis=-1)
    gates = _nn.fc(concat, 4 * hidden, param_attr=param_attr,
                   bias_attr=bias_attr)
    h = helper.create_variable_for_type_inference(dtype=x_t.dtype)
    c = helper.create_variable_for_type_inference(dtype=x_t.dtype)
    helper.append_op(
        "lstm_unit", inputs={"X": [gates], "C_prev": [cell_t_prev]},
        outputs={"H": [h], "C": [c]},
        attrs={"forget_bias": float(forget_bias)})
    return h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    """One GRU step (reference: layers/nn.py gru_unit over gru_unit_op)."""
    helper = LayerHelper("gru_unit", bias_attr=bias_attr)
    h_dim = size // 3
    w = helper.create_parameter(ParamAttr._to_attr(param_attr),
                                [h_dim, 3 * h_dim], input.dtype)
    b = helper.create_parameter(ParamAttr._to_attr(bias_attr),
                                [3 * h_dim], input.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    gate = helper.create_variable_for_type_inference(dtype=input.dtype)
    reset = helper.create_variable_for_type_inference(dtype=input.dtype)
    gru_ins = {"Input": [input], "HiddenPrev": [hidden], "Weight": [w]}
    if b is not None:
        gru_ins["Bias"] = [b]
    helper.append_op(
        "gru_unit",
        inputs=gru_ins,
        outputs={"Hidden": [out], "Gate": [gate],
                 "ResetHiddenPrev": [reset]},
        attrs={"activation": activation,
               "gate_activation": gate_activation,
               "origin_mode": origin_mode})
    return out, reset, gate


# --------------------------------------------------------------------------
# decode
# --------------------------------------------------------------------------


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                log_probs=None, finished=None, step_idx=None,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=False):
    """One beam expansion over the dense decode state (reference:
    layers/beam_search — LoD-based; here the state is the fixed-shape
    {Ids [B, K, T], Scores [B, K], Finished [B, K]} triple the
    beam_search_step op maintains; see models/transformer.py translate for
    the end-to-end loop). ``scores``/``log_probs`` is [B, K, V] log
    p(next); ``ids`` is accepted for API parity and unused (the op
    derives candidate ids from the vocab axis)."""
    from paddle_tpu.layers.tensor import fill_constant

    lp = log_probs if log_probs is not None else scores
    if finished is None:
        k = int(pre_scores.shape[-1])
        finished = fill_constant(shape=[int(pre_scores.shape[0]), k],
                                 dtype="bool", value=0.0)
    if step_idx is None:
        step_idx = fill_constant(shape=[], dtype="int64", value=0)
    ins = {"Ids": pre_ids, "Scores": pre_scores, "LogProbs": lp,
           "Finished": finished, "StepIdx": step_idx}
    out_ids, out_scores, out_fin, parent = _op(
        "beam_search_step", ins, {"end_id": int(end_id)},
        out_slots=("Ids", "Scores", "Finished", "Parent"),
        dtypes=("int64", "float32", "bool", "int64"), stop_gradient=True)
    if return_parent_idx:
        return out_ids, out_scores, out_fin, parent
    return out_ids, out_scores, out_fin


def beam_search_decode(ids, scores, beam_size=None, end_id=None, name=None):
    """Pick the best finished hypothesis per batch row (reference:
    beam_search_decode_op): Ids [B, K, T] + Scores [B, K] ->
    (best ids [B, T], best scores [B])."""
    from paddle_tpu.layers import nn as _nn

    best = _nn.argmax(scores, axis=-1)                     # [B]
    best_ids = _op("beam_gather", {"X": ids, "Index": best},
                       dtypes=("int64",))
    best_scores = _op("beam_gather", {"X": scores, "Index": best})
    return best_ids, best_scores


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """Multi-layer (optionally bidirectional) LSTM over padded [B, T, D]
    input (reference: layers/nn.py lstm — the cudnn_lstm op; here each
    layer/direction is one fused lstm scan, ops/rnn_ops.py). init_h and
    init_c are accepted for API parity; the dense scans start from zeros
    like dynamic_lstm (feed nonzero states via a custom first step if
    needed). Returns (out [B, T, H*dirs], last_h, last_c)."""
    from paddle_tpu.layers import nn as _nn

    helper = LayerHelper("lstm", name=name)
    x = input
    dirs = 2 if is_bidirec else 1
    last_hs, last_cs = [], []
    for layer in builtins.range(num_layers):
        outs = []
        for d in builtins.range(dirs):
            gates = _nn.fc(x, 4 * hidden_size, num_flatten_dims=2,
                           param_attr=ParamAttr(
                               name=f"{helper.name}_l{layer}d{d}.w_in"),
                           bias_attr=False)
            w = helper.create_parameter(
                ParamAttr(name=f"{helper.name}_l{layer}d{d}.w_h",
                          initializer=default_initializer),
                [hidden_size, 4 * hidden_size], input.dtype)
            b = helper.create_parameter(
                ParamAttr(name=f"{helper.name}_l{layer}d{d}.b"),
                [4 * hidden_size], input.dtype, is_bias=True)
            h_seq = helper.create_variable_for_type_inference(
                dtype=input.dtype)
            last_h = helper.create_variable_for_type_inference(
                dtype=input.dtype)
            last_c = helper.create_variable_for_type_inference(
                dtype=input.dtype)
            helper.append_op(
                "lstm",
                inputs={"Input": [gates.name], "Weight": [w.name],
                        "Bias": [b.name]},
                outputs={"Hidden": [h_seq.name], "LastH": [last_h.name],
                         "LastC": [last_c.name]},
                attrs={"is_reverse": d == 1})
            outs.append(h_seq)
            last_hs.append(last_h)
            last_cs.append(last_c)
        x = outs[0] if dirs == 1 else _nn.concat(outs, axis=-1)
        if dropout_prob and not is_test:
            x = _nn.dropout(x, dropout_prob)
    # final states stacked [num_layers*dirs, B, H] (reference cudnn_lstm
    # LastH/LastC layout)
    last_h = _nn.stack([_nn.unsqueeze(v, [0]) for v in last_hs], axis=0)
    last_h = _nn.reshape(last_h, [len(last_hs), -1, hidden_size])
    last_c = _nn.stack([_nn.unsqueeze(v, [0]) for v in last_cs], axis=0)
    last_c = _nn.reshape(last_c, [len(last_cs), -1, hidden_size])
    return x, last_h, last_c


def tensor_array_to_tensor(input, axis=1, name=None):
    """Dense-array design: the fixed-capacity array IS already a stacked
    [maxlen, ...] tensor (see create_array), so this returns it moved to
    ``axis`` plus the per-slot sizes (reference:
    tensor_array_to_tensor_op.cc concatenates LoDTensorArray slots)."""
    from paddle_tpu.layers import nn as _nn
    from paddle_tpu.layers.tensor import fill_constant

    n = int((input.shape or (0,))[0])
    out = input
    if axis != 0:
        perm = list(builtins.range(len(input.shape or ())))
        perm.insert(axis, perm.pop(0))
        out = _nn.transpose(input, perm)
    sizes = fill_constant(shape=[n], dtype="int32", value=1)
    return out, sizes
