"""NN layer functions (reference: python/paddle/fluid/layers/nn.py — 170
layer fns). Each builds vars + appends ops via LayerHelper."""

from __future__ import annotations

from typing import Optional, Sequence, Union

from paddle_tpu import unique_name
from paddle_tpu.framework import Variable
from paddle_tpu.initializer import ConstantInitializer
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.param_attr import ParamAttr

__all__ = [
    "fc", "embedding", "conv2d", "conv2d_transpose", "pool2d", "batch_norm",
    "layer_norm", "dropout", "relu", "sigmoid", "tanh", "sqrt", "exp", "log",
    "abs", "square", "gelu", "leaky_relu", "softplus", "softsign", "elu",
    "relu6", "swish", "hard_swish", "hard_sigmoid", "softmax", "log_softmax",
    "cross_entropy", "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits", "square_error_cost", "huber_loss",
    "smooth_l1", "mean", "mul", "matmul", "elementwise_op", "elementwise_add",
    "elementwise_sub", "elementwise_mul", "elementwise_div", "elementwise_pow",
    "elementwise_max", "elementwise_min", "reduce_sum", "reduce_mean",
    "reduce_max", "reduce_min", "reduce_prod", "scale", "cast", "clip",
    "clip_by_norm", "accuracy", "topk", "one_hot", "lookup_table", "gather",
    "scatter", "label_smooth", "l2_normalize", "dropout", "split", "pad",
    "pow", "stack", "unstack", "squeeze", "unsqueeze", "expand", "expand_as",
    "argmax", "argmin", "equal", "less_than", "greater_than", "logical_and",
    "logical_or", "logical_not", "where", "cumsum", "increment", "reshape",
    "transpose", "concat", "fill_constant_like", "log_softmax",
    "sequence_pool", "sequence_softmax", "sequence_mask", "sequence_reverse",
    "sequence_expand", "im2sequence", "batch_norm", "group_norm", "prelu",
    "flatten", "sums", "elementwise_mod", "elementwise_floordiv", "maxout",
    "mean_iou",
    "linear_chain_crf", "crf_decoding", "warpctc", "edit_distance",
    "bilinear_tensor_product", "nce", "switch_moe",
    "roi_align", "roi_pool", "lrn", "spp", "affine_grid", "multiclass_nms",
    "yolo_box", "sequence_conv", "add_position_encoding", "conv3d",
    "spectral_norm", "hsigmoid", "sample_logits",
    "chunk_eval", "ctc_greedy_decoder",
    "py_func", "hash", "tree_conv",
]


def _single_op(op_type, x, attrs=None, dtype=None, slot_in="X", slot_out="Out",
               name=None, stop_gradient=False):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(
        dtype=dtype or x.dtype, stop_gradient=stop_gradient
    )
    helper.append_op(
        op_type, inputs={slot_in: x}, outputs={slot_out: out}, attrs=attrs or {}
    )
    return out


# --- dense / conv layers ---


def fc(
    input: Union[Variable, Sequence[Variable]],
    size: int,
    num_flatten_dims: int = 1,
    param_attr=None,
    bias_attr=None,
    act: Optional[str] = None,
    is_test: bool = False,
    name: Optional[str] = None,
):
    """Fully-connected layer (reference: layers/nn.py fc)."""
    helper = LayerHelper("fc", name=name, bias_attr=bias_attr, act=act)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    param_attrs = param_attr if isinstance(param_attr, (list, tuple)) else [
        param_attr
    ] * len(inputs)
    mul_results = []
    for x, pa in zip(inputs, param_attrs):
        import math

        in_features = math.prod(x.shape[num_flatten_dims:])
        w = helper.create_parameter(
            ParamAttr._to_attr(pa), shape=[in_features, size], dtype=x.dtype
        )
        tmp = helper.create_variable_for_type_inference(dtype=x.dtype)
        helper.append_op(
            "mul",
            inputs={"X": x, "Y": w},
            outputs={"Out": tmp},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype=inputs[0].dtype)
        helper.append_op("sum", inputs={"X": mul_results}, outputs={"Out": pre_bias})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(
    input: Variable,
    size: Sequence[int],
    is_sparse: bool = False,
    is_distributed: bool = False,
    padding_idx: Optional[int] = None,
    param_attr=None,
    dtype: str = "float32",
    name: Optional[str] = None,
):
    """Embedding lookup (reference: layers/nn.py embedding). ``is_sparse`` /
    ``is_distributed`` are accepted for API parity; on TPU the gradient is a
    dense XLA scatter-add and sharding is a pjit spec (SURVEY.md section 2.3)."""
    helper = LayerHelper("embedding", name=name)
    w = helper.create_parameter(
        ParamAttr._to_attr(param_attr), shape=list(size), dtype=dtype
    )
    out = helper.create_variable_for_type_inference(dtype=dtype)
    # Padded [b, t] ids convention: never squeeze, even when t == 1 (the
    # op's squeeze heuristic exists for the reference's [N, 1] column ids).
    attrs = {"squeeze_last": False}
    if padding_idx is not None:
        attrs["padding_idx"] = int(padding_idx)
    if is_distributed:
        # Marks the table for row-sharded lookup (psum over the strategy's
        # table axis) when run under CompiledProgram.with_strategy.
        attrs["is_distributed"] = True
    if is_sparse:
        # Row-sparse {rows, values} gradient pair instead of a dense
        # [V, D] scatter-add (the reference's SelectedRows); consumed by
        # the *_sparse optimizer ops. See ops/sparse_ops.py.
        attrs["is_sparse"] = True
    helper.append_op(
        "lookup_table",
        inputs={"W": w, "Ids": input},
        outputs={"Out": out},
        attrs=attrs,
    )
    return out


lookup_table = embedding


def conv2d(
    input: Variable,
    num_filters: int,
    filter_size: Union[int, Sequence[int]],
    stride: Union[int, Sequence[int]] = 1,
    padding: Union[int, Sequence[int]] = 0,
    dilation: Union[int, Sequence[int]] = 1,
    groups: int = 1,
    param_attr=None,
    bias_attr=None,
    use_cudnn: bool = True,
    act: Optional[str] = None,
    name: Optional[str] = None,
):
    """2D convolution, NCHW (reference: layers/nn.py conv2d)."""
    helper = LayerHelper("conv2d", name=name, bias_attr=bias_attr, act=act)
    c_in = input.shape[1]
    fs = list(filter_size) if isinstance(filter_size, (list, tuple)) else [filter_size] * 2
    groups = groups or 1
    w_shape = [num_filters, c_in // groups] + fs

    import math

    fan_in = (c_in // groups) * math.prod(fs)
    from paddle_tpu.initializer import NormalInitializer

    default_init = NormalInitializer(0.0, math.sqrt(2.0 / fan_in))
    w = helper.create_parameter(
        ParamAttr._to_attr(param_attr),
        shape=w_shape,
        dtype=input.dtype,
        default_initializer=default_init,
    )
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        "conv2d" if groups == 1 or c_in != groups else "depthwise_conv2d",
        inputs={"Input": input, "Filter": w},
        outputs={"Output": out},
        attrs={
            "strides": [stride] * 2 if isinstance(stride, int) else list(stride),
            "paddings": [padding] * 2 if isinstance(padding, int) else list(padding),
            "dilations": [dilation] * 2 if isinstance(dilation, int) else list(dilation),
            "groups": groups,
        },
    )
    pre_act = _conv_bias(helper, out)
    return helper.append_activation(pre_act)


def _conv_bias(helper, out):
    bias_attr = helper.kwargs.get("bias_attr")
    if bias_attr is False:
        return out
    num_filters = out.shape[1] if out.shape else 1
    b = helper.create_parameter(
        ParamAttr._to_attr(bias_attr), shape=[num_filters], dtype=out.dtype,
        is_bias=True,
    )
    if b is None:
        return out
    res = helper.create_variable_for_type_inference(dtype=out.dtype)
    helper.append_op(
        "elementwise_add",
        inputs={"X": out, "Y": b},
        outputs={"Out": res},
        attrs={"axis": 1},
    )
    return res


def conv2d_transpose(
    input, num_filters, output_size=None, filter_size=None, padding=0,
    stride=1, dilation=1, groups=1, param_attr=None, bias_attr=None,
    use_cudnn=True, act=None, name=None,
):
    helper = LayerHelper("conv2d_transpose", name=name, bias_attr=bias_attr, act=act)
    c_in = input.shape[1]
    fs = list(filter_size) if isinstance(filter_size, (list, tuple)) else [filter_size] * 2
    w = helper.create_parameter(
        ParamAttr._to_attr(param_attr),
        shape=[c_in, num_filters // (groups or 1)] + fs,
        dtype=input.dtype,
    )
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        "conv2d_transpose",
        inputs={"Input": input, "Filter": w},
        outputs={"Output": out},
        attrs={
            "strides": [stride] * 2 if isinstance(stride, int) else list(stride),
            "paddings": [padding] * 2 if isinstance(padding, int) else list(padding),
            "dilations": [dilation] * 2 if isinstance(dilation, int) else list(dilation),
        },
    )
    pre_act = _conv_bias(helper, out)
    return helper.append_activation(pre_act)


def pool2d(
    input,
    pool_size=2,
    pool_type="max",
    pool_stride=1,
    pool_padding=0,
    global_pooling=False,
    use_cudnn=True,
    ceil_mode=False,
    exclusive=True,
    name=None,
):
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        "pool2d",
        inputs={"X": input},
        outputs={"Out": out},
        attrs={
            "pooling_type": pool_type,
            "ksize": [pool_size] * 2 if isinstance(pool_size, int) else list(pool_size),
            "strides": [pool_stride] * 2 if isinstance(pool_stride, int) else list(pool_stride),
            "paddings": [pool_padding] * 2 if isinstance(pool_padding, int) else list(pool_padding),
            "global_pooling": global_pooling,
            "exclusive": exclusive,
        },
    )
    return out


def batch_norm(
    input,
    act=None,
    is_test=False,
    momentum=0.9,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    data_layout="NCHW",
    in_place=False,
    name=None,
    moving_mean_name=None,
    moving_variance_name=None,
    do_model_average_for_mean_and_var=False,
    use_global_stats=False,
):
    """Batch normalization (reference: layers/nn.py batch_norm)."""
    helper = LayerHelper("batch_norm", name=name, act=act)
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    dtype = input.dtype

    scale = helper.create_parameter(
        ParamAttr._to_attr(param_attr), shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(1.0),
    )
    bias = helper.create_parameter(
        ParamAttr._to_attr(bias_attr), shape=[c], dtype=dtype, is_bias=True,
    )
    mean = helper.create_parameter(
        ParamAttr(name=moving_mean_name, initializer=ConstantInitializer(0.0),
                  trainable=False),
        shape=[c], dtype=dtype,
    )
    var = helper.create_parameter(
        ParamAttr(name=moving_variance_name, initializer=ConstantInitializer(1.0),
                  trainable=False),
        shape=[c], dtype=dtype,
    )
    out = helper.create_variable_for_type_inference(dtype=dtype)
    saved_mean = helper.create_variable_for_type_inference(dtype=dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(dtype=dtype, stop_gradient=True)
    helper.append_op(
        "batch_norm",
        inputs={"X": input, "Scale": scale, "Bias": bias, "Mean": mean, "Variance": var},
        outputs={
            "Y": out,
            "MeanOut": mean,
            "VarianceOut": var,
            "SavedMean": saved_mean,
            "SavedVariance": saved_var,
        },
        attrs={
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test or use_global_stats,
            "data_layout": data_layout,
        },
    )
    return helper.append_activation(out)


def layer_norm(
    input,
    scale=True,
    shift=True,
    begin_norm_axis=1,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    helper = LayerHelper("layer_norm", name=name, act=act)
    import math

    feat = math.prod(input.shape[begin_norm_axis:])
    inputs = {"X": input}
    if scale:
        s = helper.create_parameter(
            ParamAttr._to_attr(param_attr), shape=[feat], dtype=input.dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        inputs["Scale"] = s
    if shift:
        b = helper.create_parameter(
            ParamAttr._to_attr(bias_attr), shape=[feat], dtype=input.dtype,
            is_bias=True,
        )
        inputs["Bias"] = b
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    m = helper.create_variable_for_type_inference(dtype=input.dtype, stop_gradient=True)
    v = helper.create_variable_for_type_inference(dtype=input.dtype, stop_gradient=True)
    helper.append_op(
        "layer_norm",
        inputs=inputs,
        outputs={"Y": out, "Mean": m, "Variance": v},
        attrs={"begin_norm_axis": begin_norm_axis, "epsilon": epsilon},
    )
    return helper.append_activation(out)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    """Group normalization (reference: layers/nn.py group_norm,
    operators/group_norm_op.cc)."""
    if data_layout != "NCHW":
        raise ValueError("group_norm supports NCHW layout")
    helper = LayerHelper("group_norm", name=name, act=act)
    c = input.shape[1]
    scale = helper.create_parameter(
        ParamAttr._to_attr(param_attr), shape=[c], dtype=input.dtype,
        default_initializer=ConstantInitializer(1.0),
    )
    bias = helper.create_parameter(
        ParamAttr._to_attr(bias_attr), shape=[c], dtype=input.dtype,
        is_bias=True,
    )
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    mean = helper.create_variable_for_type_inference(
        dtype=input.dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(
        dtype=input.dtype, stop_gradient=True)
    helper.append_op(
        "group_norm",
        inputs={"X": input, "Scale": scale, "Bias": bias},
        outputs={"Y": out, "Mean": mean, "Variance": var},
        attrs={"groups": groups, "epsilon": epsilon},
    )
    return helper.append_activation(out)


def dropout(
    x,
    dropout_prob,
    is_test=False,
    seed=None,
    name=None,
    dropout_implementation="downgrade_in_infer",
):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    mask = helper.create_variable_for_type_inference(dtype="uint8", stop_gradient=True)
    helper.append_op(
        "dropout",
        inputs={"X": x},
        outputs={"Out": out, "Mask": mask},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "seed": seed if seed is not None else 0,
            "dropout_implementation": dropout_implementation,
        },
    )
    return out


# --- activations ---


def _make_act(name):
    def _act(x, **kwargs):
        attrs = {k: v for k, v in kwargs.items() if k != "name"}
        return _single_op(name, x, attrs=attrs, name=kwargs.get("name"))

    _act.__name__ = name
    return _act


relu = _make_act("relu")
sigmoid = _make_act("sigmoid")
tanh = _make_act("tanh")
sqrt = _make_act("sqrt")
exp = _make_act("exp")
log = _make_act("log")
abs = _make_act("abs")
square = _make_act("square")
softplus = _make_act("softplus")
softsign = _make_act("softsign")
relu6 = _make_act("relu6")
swish = _make_act("swish")
hard_swish = _make_act("hard_swish")
hard_sigmoid = _make_act("hard_sigmoid")
elu = _make_act("elu")


def gelu(x, approximate=False, name=None):
    return _single_op("gelu", x, attrs={"approximate": approximate}, name=name)


def leaky_relu(x, alpha=0.02, name=None):
    return _single_op("leaky_relu", x, attrs={"alpha": alpha}, name=name)


def pow(x, factor=1.0, name=None):
    return _single_op("pow", x, attrs={"factor": factor}, name=name)


def prelu(x, mode="all", param_attr=None, name=None):
    helper = LayerHelper("prelu", name=name)
    if mode == "all":
        shape = [1]
    elif mode == "channel":
        shape = [x.shape[1]]
    else:
        shape = [int(__import__("math").prod(x.shape[1:]))]
    alpha = helper.create_parameter(
        ParamAttr._to_attr(param_attr), shape=shape, dtype=x.dtype,
        default_initializer=ConstantInitializer(0.25),
    )
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        "prelu",
        inputs={"X": x, "Alpha": alpha},
        outputs={"Out": out},
        attrs={"mode": mode},
    )
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        "maxout", inputs={"X": x}, outputs={"Out": out}, attrs={"groups": groups}
    )
    return out


def softmax(input, use_cudnn=False, name=None, axis=-1):
    return _single_op("softmax", input, attrs={"axis": axis}, name=name)


def log_softmax(input, axis=-1, name=None):
    return _single_op("log_softmax", input, attrs={"axis": axis}, name=name)


# --- losses ---


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        "cross_entropy",
        inputs={"X": input, "Label": label},
        outputs={"Y": out},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def softmax_with_cross_entropy(
    logits, label, soft_label=False, ignore_index=-100,
    numeric_stable_mode=True, return_softmax=False,
):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_variable_for_type_inference(dtype=logits.dtype)
    loss = helper.create_variable_for_type_inference(dtype=logits.dtype)
    helper.append_op(
        "softmax_with_cross_entropy",
        inputs={"Logits": logits, "Label": label},
        outputs={"Softmax": softmax_out, "Loss": loss},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    if return_softmax:
        return loss, softmax_out
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        "sigmoid_cross_entropy_with_logits",
        inputs={"X": x, "Label": label},
        outputs={"Out": out},
        attrs={"ignore_index": ignore_index},
    )
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        "square_error_cost",
        inputs={"X": input, "Label": label},
        outputs={"Out": out},
    )
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    res = helper.create_variable_for_type_inference(dtype=input.dtype, stop_gradient=True)
    helper.append_op(
        "huber_loss",
        inputs={"X": input, "Y": label},
        outputs={"Out": out, "Residual": res},
        attrs={"delta": delta},
    )
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    diff = helper.create_variable_for_type_inference(dtype=x.dtype, stop_gradient=True)
    inputs = {"X": x, "Y": y}
    if inside_weight is not None:
        inputs["InsideWeight"] = inside_weight
    if outside_weight is not None:
        inputs["OutsideWeight"] = outside_weight
    helper.append_op(
        "smooth_l1_loss",
        inputs=inputs,
        outputs={"Out": out, "Diff": diff},
        attrs={"sigma": sigma or 1.0},
    )
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32", name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    inputs = {"X": label}
    if prior_dist is not None:
        inputs["PriorDist"] = prior_dist
    helper.append_op(
        "label_smooth", inputs=inputs, outputs={"Out": out},
        attrs={"epsilon": float(epsilon)},
    )
    return out


# --- math wrappers ---


def mean(x, name=None):
    return _single_op("mean", x, name=name)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        "mul",
        inputs={"X": x, "Y": y},
        outputs={"Out": out},
        attrs={"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        "matmul",
        inputs={"X": x, "Y": y},
        outputs={"Out": out},
        attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y,
               "alpha": float(alpha)},
    )
    return out


def elementwise_op(op_type, x, y, axis=-1, act=None, name=None):
    helper = LayerHelper(op_type, name=name, act=act)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        op_type, inputs={"X": x, "Y": y}, outputs={"Out": out}, attrs={"axis": axis}
    )
    return helper.append_activation(out)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_div", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_pow", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_max", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_min", x, y, axis, act, name)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_mod", x, y, axis, act, name)


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_floordiv", x, y, axis, act, name)


def _reduce(op_type, input, dim, keep_dim, name):
    attrs = {"keep_dim": keep_dim}
    if dim is None:
        attrs["reduce_all"] = True
    else:
        attrs["dim"] = [dim] if isinstance(dim, int) else list(dim)
    return _single_op(op_type, input, attrs=attrs, name=name)


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim, name)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", name=name, act=act)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        "scale",
        inputs={"X": x},
        outputs={"Out": out},
        attrs={"scale": float(scale), "bias": float(bias),
               "bias_after_scale": bias_after_scale},
    )
    return helper.append_activation(out)


def cast(x, dtype):
    from paddle_tpu.framework import convert_np_dtype_to_dtype_

    dtype = convert_np_dtype_to_dtype_(dtype)
    return _single_op("cast", x, attrs={"out_dtype": dtype}, dtype=dtype)


def clip(x, min, max, name=None):
    return _single_op("clip", x, attrs={"min": float(min), "max": float(max)}, name=name)


def clip_by_norm(x, max_norm, name=None):
    return _single_op("clip_by_norm", x, attrs={"max_norm": float(max_norm)}, name=name)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    sq = square(x)
    ssum = reduce_sum(sq, dim=axis, keep_dim=True)
    norm = sqrt(elementwise_max(ssum, fill_constant_like(ssum, epsilon)))
    return elementwise_div(x, norm)


def fill_constant_like(x, value):
    helper = LayerHelper("fill_any_like")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        "fill_any_like", inputs={"X": x}, outputs={"Out": out},
        attrs={"value": float(value)},
    )
    return out


# --- metrics / indexing ---


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy")
    topk_out, topk_indices = topk(input, k)
    acc = helper.create_variable_for_type_inference(dtype="float32", stop_gradient=True)
    correct = correct or helper.create_variable_for_type_inference(
        dtype="int32", stop_gradient=True)
    total = total or helper.create_variable_for_type_inference(
        dtype="int32", stop_gradient=True)
    helper.append_op(
        "accuracy",
        inputs={"Out": topk_out, "Indices": topk_indices, "Label": label},
        outputs={"Accuracy": acc, "Correct": correct, "Total": total},
    )
    return acc


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    vals = helper.create_variable_for_type_inference(dtype=input.dtype)
    idx = helper.create_variable_for_type_inference(dtype="int64", stop_gradient=True)
    helper.append_op(
        "top_k", inputs={"X": input}, outputs={"Out": vals, "Indices": idx},
        attrs={"k": k},
    )
    return vals, idx


def one_hot(input, depth, dtype="float32"):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference(dtype=dtype, stop_gradient=True)
    helper.append_op(
        "one_hot", inputs={"X": input}, outputs={"Out": out},
        attrs={"depth": depth, "dtype": dtype},
    )
    return out


def argmax(x, axis=0, name=None):
    return _single_op("arg_max", x, attrs={"axis": axis}, dtype="int64",
                      stop_gradient=True, name=name)


def argmin(x, axis=0, name=None):
    return _single_op("arg_min", x, attrs={"axis": axis}, dtype="int64",
                      stop_gradient=True, name=name)


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou")
    out = helper.create_variable_for_type_inference(dtype="float32", stop_gradient=True)
    helper.append_op(
        "mean_iou",
        inputs={"Predictions": input, "Labels": label},
        outputs={"OutMeanIou": out},
        attrs={"num_classes": num_classes},
    )
    return out


def _compare(op_type, x, y, cond=None):
    helper = LayerHelper(op_type)
    out = cond or helper.create_variable_for_type_inference(
        dtype="bool", stop_gradient=True)
    helper.append_op(op_type, inputs={"X": x, "Y": y}, outputs={"Out": out})
    return out


def equal(x, y, cond=None):
    return _compare("equal", x, y, cond)


def less_than(x, y, cond=None, force_cpu=None):
    return _compare("less_than", x, y, cond)


def greater_than(x, y, cond=None):
    return _compare("greater_than", x, y, cond)


def logical_and(x, y, out=None, name=None):
    return _compare("logical_and", x, y, out)


def logical_or(x, y, out=None, name=None):
    return _compare("logical_or", x, y, out)


def logical_not(x, out=None, name=None):
    helper = LayerHelper("logical_not")
    out = out or helper.create_variable_for_type_inference(
        dtype="bool", stop_gradient=True)
    helper.append_op("logical_not", inputs={"X": x}, outputs={"Out": out})
    return out


def where(condition, x, y, name=None):
    helper = LayerHelper("where", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        "where", inputs={"Condition": condition, "X": x, "Y": y},
        outputs={"Out": out},
    )
    return out


def cumsum(x, axis=-1, exclusive=False, reverse=False, name=None):
    return _single_op(
        "cumsum", x,
        attrs={"axis": axis, "exclusive": exclusive, "reverse": reverse},
        name=name,
    )


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        "increment", inputs={"X": x}, outputs={"Out": out}, attrs={"step": float(value)}
    )
    return out


# --- shape manipulation ---


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", name=name, act=act)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        "reshape2", inputs={"X": x}, outputs={"Out": out},
        attrs={"shape": list(shape)},
    )
    return helper.append_activation(out)


def transpose(x, perm, name=None):
    return _single_op("transpose2", x, attrs={"axis": list(perm)}, name=name)


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    if isinstance(num_or_sections, int):
        n = num_or_sections
        attrs = {"num": n, "axis": dim}
    else:
        n = len(num_or_sections)
        attrs = {"sections": list(num_or_sections), "axis": dim}
    outs = [helper.create_variable_for_type_inference(dtype=input.dtype)
            for _ in range(n)]
    helper.append_op("split", inputs={"X": input}, outputs={"Out": outs}, attrs=attrs)
    return outs


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(dtype=input[0].dtype)
    helper.append_op("concat", inputs={"X": list(input)}, outputs={"Out": out},
                     attrs={"axis": axis})
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    out = out or helper.create_variable_for_type_inference(dtype=input[0].dtype)
    helper.append_op("sum", inputs={"X": list(input)}, outputs={"Out": out})
    return out


def stack(x, axis=0):
    helper = LayerHelper("stack")
    out = helper.create_variable_for_type_inference(dtype=x[0].dtype)
    helper.append_op("stack", inputs={"X": list(x)}, outputs={"Out": out},
                     attrs={"axis": axis})
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    num = num or x.shape[axis]
    outs = [helper.create_variable_for_type_inference(dtype=x.dtype)
            for _ in range(num)]
    helper.append_op("unstack", inputs={"X": x}, outputs={"Y": outs},
                     attrs={"axis": axis})
    return outs


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze2", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("squeeze2", inputs={"X": input}, outputs={"Out": out},
                     attrs={"axes": list(axes)})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze2", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("unsqueeze2", inputs={"X": input}, outputs={"Out": out},
                     attrs={"axes": list(axes)})
    return out


def expand(x, expand_times, name=None):
    return _single_op("expand", x, attrs={"expand_times": list(expand_times)}, name=name)


def expand_as(x, target_tensor, name=None):
    helper = LayerHelper("expand_as", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("expand_as", inputs={"X": x, "Y": target_tensor},
                     outputs={"Out": out})
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten2", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("flatten2", inputs={"X": x}, outputs={"Out": out},
                     attrs={"axis": axis})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    return _single_op("pad", x, attrs={"paddings": list(paddings),
                                       "pad_value": float(pad_value)}, name=name)


def gather(input, index, overwrite=True):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("gather", inputs={"X": input, "Index": index},
                     outputs={"Out": out})
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        "scatter", inputs={"X": input, "Ids": index, "Updates": updates},
        outputs={"Out": out}, attrs={"overwrite": overwrite},
    )
    return out


# --- sequence (padded/masked; see ops/sequence_ops.py) ---


def sequence_pool(input, pool_type, length=None):
    helper = LayerHelper("sequence_pool")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    inputs = {"X": input}
    if length is None and getattr(input, "mask_name", None):
        length = input.block.var(input.mask_name)
    if length is not None:
        inputs["Length"] = length
    helper.append_op("sequence_pool", inputs=inputs, outputs={"Out": out},
                     attrs={"pooltype": pool_type.upper()})
    return out


def sequence_softmax(input, length=None, use_cudnn=False, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    inputs = {"X": input}
    if length is not None:
        inputs["Length"] = length
    helper.append_op("sequence_softmax", inputs=inputs, outputs={"Out": out})
    return out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype=dtype, stop_gradient=True)
    helper.append_op(
        "sequence_mask", inputs={"X": x}, outputs={"Y": out},
        attrs={"maxlen": maxlen if maxlen is not None else -1, "out_dtype": dtype},
    )
    return out


def sequence_reverse(x, length=None, name=None):
    helper = LayerHelper("sequence_reverse", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    inputs = {"X": x}
    if length is not None:
        inputs["Length"] = length
    helper.append_op("sequence_reverse", inputs=inputs, outputs={"Y": out})
    return out


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("sequence_expand", inputs={"X": x, "Y": y},
                     outputs={"Out": out})
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    helper = LayerHelper("im2sequence", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    fs = [filter_size] * 2 if isinstance(filter_size, int) else list(filter_size)
    st = [stride] * 2 if isinstance(stride, int) else list(stride)
    helper.append_op(
        "im2sequence", inputs={"X": input}, outputs={"Out": out},
        attrs={"kernels": fs, "strides": st},
    )
    return out


def linear_chain_crf(input, label, param_attr=None, length=None):
    """CRF loss layer (reference: layers/nn.py linear_chain_crf).
    ``input`` [b, t, c] emissions, ``label`` [b, t]; creates the [c+2, c]
    transition parameter. Returns the per-sequence NEGATIVE
    log-likelihood [b, 1] (reference kernel semantics: minimize
    ``mean(...)`` directly)."""
    helper = LayerHelper("linear_chain_crf")
    c = input.shape[-1]
    trans = helper.create_parameter(
        ParamAttr._to_attr(param_attr), shape=[c + 2, c], dtype=input.dtype,
    )
    ll = helper.create_variable_for_type_inference(dtype=input.dtype)
    inputs = {"Emission": input, "Transition": trans, "Label": label}
    if length is not None:
        inputs["Length"] = length
    helper.append_op(
        "linear_chain_crf", inputs=inputs, outputs={"LogLikelihood": ll}
    )
    return ll


def crf_decoding(input, param_attr=None, label=None, length=None):
    """Viterbi decode with a (shared, by ParamAttr name) transition
    parameter (reference: layers/nn.py crf_decoding). With ``label``, the
    output switches to the reference's per-position correctness mask
    (1 where the Viterbi path agrees with the label) instead of tag ids."""
    helper = LayerHelper("crf_decoding")
    c = input.shape[-1]
    trans = helper.create_parameter(
        ParamAttr._to_attr(param_attr), shape=[c + 2, c], dtype=input.dtype,
    )
    out = helper.create_variable_for_type_inference(
        dtype="int64", stop_gradient=True)
    inputs = {"Emission": input, "Transition": trans}
    if length is not None:
        inputs["Length"] = length
    helper.append_op(
        "crf_decoding", inputs=inputs, outputs={"ViterbiPath": out}
    )
    if label is None:
        return out
    return cast(equal(out, label), "int64")


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    """CTC loss (reference: layers/nn.py warpctc). ``input`` [b, t, c]
    unnormalized logits (batch-major; the reference's time-major LoD
    convention becomes padded + length vectors)."""
    helper = LayerHelper("warpctc")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    inputs = {"Logits": input, "Label": label}
    if input_length is not None:
        inputs["LogitsLength"] = input_length
    if label_length is not None:
        inputs["LabelLength"] = label_length
    helper.append_op(
        "warpctc", inputs=inputs, outputs={"Loss": out},
        attrs={"blank": blank, "norm_by_times": norm_by_times},
    )
    return out


def edit_distance(input, label, normalized=True, input_length=None,
                  label_length=None):
    """Levenshtein distance per row (reference: layers/nn.py
    edit_distance). Returns (distance [b, 1], seq_num [1])."""
    helper = LayerHelper("edit_distance")
    out = helper.create_variable_for_type_inference(
        dtype="float32", stop_gradient=True)
    num = helper.create_variable_for_type_inference(
        dtype="int64", stop_gradient=True)
    inputs = {"Hyps": input, "Refs": label}
    if input_length is not None:
        inputs["HypsLength"] = input_length
    if label_length is not None:
        inputs["RefsLength"] = label_length
    helper.append_op(
        "edit_distance", inputs=inputs,
        outputs={"Out": out, "SequenceNum": num},
        attrs={"normalized": normalized},
    )
    return out, num


def bilinear_tensor_product(x, y, size, act=None, param_attr=None,
                            bias_attr=None, name=None):
    """out_k = x^T W_k y (reference: layers/nn.py bilinear_tensor_product)."""
    helper = LayerHelper("bilinear_tensor_product", name=name, act=act)
    w = helper.create_parameter(
        ParamAttr._to_attr(param_attr),
        shape=[size, x.shape[-1], y.shape[-1]], dtype=x.dtype,
    )
    b = helper.create_parameter(
        ParamAttr._to_attr(bias_attr), shape=[size], dtype=x.dtype,
        is_bias=True,
    )
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    inputs = {"X": x, "Y": y, "Weight": w}
    if b is not None:
        inputs["Bias"] = b
    helper.append_op(
        "bilinear_tensor_product", inputs=inputs, outputs={"Out": out}
    )
    return helper.append_activation(out)


def nce(input, label, num_total_classes, num_neg_samples=10,
        param_attr=None, bias_attr=None, name=None):
    """Noise-contrastive estimation (reference: layers/nn.py nce).
    Returns per-example cost [b, 1]; the weight table is [C, D]."""
    helper = LayerHelper("nce", name=name)
    d = input.shape[-1]
    w = helper.create_parameter(
        ParamAttr._to_attr(param_attr),
        shape=[num_total_classes, d], dtype=input.dtype,
    )
    b = helper.create_parameter(
        ParamAttr._to_attr(bias_attr), shape=[num_total_classes],
        dtype=input.dtype, is_bias=True,
    )
    cost = helper.create_variable_for_type_inference(dtype=input.dtype)
    inputs = {"Input": input, "Label": label, "Weight": w}
    if b is not None:
        inputs["Bias"] = b
    helper.append_op(
        "nce", inputs=inputs, outputs={"Cost": cost},
        attrs={"num_neg_samples": num_neg_samples},
    )
    return cost


def switch_moe(input, num_experts, d_ff=None, capacity_factor=2.0,
               act="relu", param_attr=None, name=None):
    """Switch-style top-1 Mixture-of-Experts FFN (net-new vs the
    reference; SURVEY.md section 2.3 "EP, MoE"). Returns
    ``(out, aux_loss)``: out has the input's shape; add a multiple of
    ``aux_loss`` (Switch uses ~0.01) to the training loss for load
    balancing.

    Under ``CompiledProgram.with_strategy`` with a strategy declaring
    ``expert_axis`` (mesh axis of size ``num_experts``), experts shard
    one-per-rank and tokens travel over ICI all_to_all; otherwise the
    identical fixed-capacity math runs on one device. Parameter naming
    matches ``parallel.strategy.moe_rules``: ``{name}_experts.{w1,...}``
    stacked [E, ...] weights, ``{name}_gate.w`` router.
    """
    from paddle_tpu.initializer import NormalInitializer

    helper = LayerHelper("switch_moe", name=name)
    d = input.shape[-1]
    d_ff = d_ff or 4 * d

    def param(suffix, shape, is_bias=False):
        base = ParamAttr._to_attr(param_attr) or ParamAttr()
        # Keep the user's attr fields; only the name is forced (the
        # _experts./_gate. naming is the moe_rules sharding contract).
        attr = ParamAttr(
            name=unique_name.generate(f"{helper.name}{suffix}"),
            initializer=base.initializer,
            learning_rate=base.learning_rate,
            regularizer=base.regularizer,
            trainable=base.trainable,
        )
        init = (ConstantInitializer(0.0) if is_bias
                else NormalInitializer(0.0, 0.02))
        return helper.create_parameter(
            attr, shape=shape, dtype=input.dtype, is_bias=is_bias,
            default_initializer=init,
        )

    gate_w = param("_gate.w", [d, num_experts])
    w1 = param("_experts.w1", [num_experts, d, d_ff])
    b1 = param("_experts.b1", [num_experts, d_ff], is_bias=True)
    w2 = param("_experts.w2", [num_experts, d_ff, d])
    b2 = param("_experts.b2", [num_experts, d], is_bias=True)

    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    aux = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op(
        "switch_moe",
        inputs={"X": input, "GateW": gate_w, "W1": w1, "B1": b1,
                "W2": w2, "B2": b2},
        outputs={"Out": out, "AuxLoss": aux},
        attrs={"capacity_factor": float(capacity_factor), "act": act},
    )
    return out, aux


def _simple_op_layer(op_type, inputs, attrs=None, out_slot="Out",
                     dtype=None, name=None, n_outs=1, out_slots=None):
    helper = LayerHelper(op_type, name=name)
    first = next(iter(inputs.values()))
    base = first[0] if isinstance(first, (list, tuple)) else first
    slots = out_slots or [out_slot]
    outs = {
        s: helper.create_variable_for_type_inference(
            dtype=dtype or base.dtype)
        for s in slots
    }
    helper.append_op(op_type, inputs=inputs, outputs=outs, attrs=attrs or {})
    vals = [outs[s] for s in slots]
    return vals[0] if len(vals) == 1 else tuple(vals)


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None):
    """Bilinear RoI align (reference: layers/nn.py roi_align)."""
    return _simple_op_layer(
        "roi_align", {"X": input, "ROIs": rois},
        {"pooled_height": pooled_height, "pooled_width": pooled_width,
         "spatial_scale": spatial_scale, "sampling_ratio": sampling_ratio},
        name=name)


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, name=None):
    """Quantized max RoI pooling (reference: layers/nn.py roi_pool)."""
    return _simple_op_layer(
        "roi_pool", {"X": input, "ROIs": rois},
        {"pooled_height": pooled_height, "pooled_width": pooled_width,
         "spatial_scale": spatial_scale}, name=name)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    """Local response normalization (reference: layers/nn.py lrn)."""
    return _simple_op_layer(
        "lrn", {"X": input}, {"n": n, "k": k, "alpha": alpha, "beta": beta},
        name=name)


def spp(input, pyramid_height=3, pool_type="max", name=None):
    """Spatial pyramid pooling (reference: layers/nn.py spp... via spp_op)."""
    return _simple_op_layer(
        "spp", {"X": input},
        {"pyramid_height": pyramid_height, "pooling_type": pool_type},
        name=name)


def affine_grid(theta, out_shape, name=None):
    """2-D affine sampling grid (reference: layers/nn.py affine_grid)."""
    if isinstance(out_shape, (list, tuple)):
        return _simple_op_layer(
            "affine_grid", {"Theta": theta},
            {"output_shape": [int(s) for s in out_shape]},
            out_slot="Output", name=name)
    return _simple_op_layer(
        "affine_grid", {"Theta": theta, "OutputShape": out_shape},
        out_slot="Output", name=name)


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=64,
                   keep_top_k=16, nms_threshold=0.3, background_label=0,
                   name=None):
    """Static-shape multiclass NMS: [n, keep_top_k, 6] rows of
    (label, score, box), label -1 padding (reference:
    layers/detection.py multiclass_nms, LoD output redesigned away).
    ``background_label``: class skipped entirely (reference default 0;
    pass -1 to keep every class, e.g. single-class detectors)."""
    return _simple_op_layer(
        "multiclass_nms", {"BBoxes": bboxes, "Scores": scores},
        {"score_threshold": score_threshold, "nms_top_k": nms_top_k,
         "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
         "background_label": background_label},
        name=name)


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, name=None):
    """YOLOv3 head decode (reference: layers/detection.py yolo_box)."""
    return _simple_op_layer(
        "yolo_box", {"X": x, "ImgSize": img_size},
        {"anchors": list(anchors), "class_num": class_num,
         "conf_thresh": conf_thresh, "downsample_ratio": downsample_ratio},
        out_slots=["Boxes", "Scores"], name=name)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Spectrally-normalized view of ``weight`` (reference: layers/nn.py
    spectral_norm). Creates persistable U/V power-iteration vectors and
    declares the op's UOut/VOut outputs so the iteration state advances
    across steps (the batch_norm MeanOut/VarianceOut pattern)."""
    from paddle_tpu.initializer import NormalInitializer

    helper = LayerHelper("spectral_norm", name=name)
    shape = weight.shape
    h = int(shape[dim])
    w_elems = 1
    for s_ in shape:
        w_elems *= int(s_)
    w_dim = w_elems // h
    u = helper.create_parameter(
        ParamAttr(name=unique_name.generate(f"{helper.name}.u"),
                  trainable=False),
        shape=[h], dtype=weight.dtype,
        default_initializer=NormalInitializer(0.0, 1.0))
    v = helper.create_parameter(
        ParamAttr(name=unique_name.generate(f"{helper.name}.v"),
                  trainable=False),
        shape=[w_dim], dtype=weight.dtype,
        default_initializer=NormalInitializer(0.0, 1.0))
    out = helper.create_variable_for_type_inference(dtype=weight.dtype)
    helper.append_op(
        "spectral_norm",
        inputs={"Weight": weight, "U": u, "V": v},
        outputs={"Out": out, "UOut": u.name, "VOut": v.name},
        attrs={"dim": dim, "power_iters": power_iters, "eps": eps},
    )
    return out


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, param_attr=None, bias_attr=None, act=None,
                  name=None):
    """Context-window sequence convolution (reference: layers/nn.py
    sequence_conv) on padded [b, t, d] batches."""
    helper = LayerHelper("sequence_conv", name=name, bias_attr=bias_attr,
                         act=act)
    d = input.shape[-1]
    w = helper.create_parameter(
        ParamAttr._to_attr(param_attr),
        shape=[filter_size * d, num_filters], dtype=input.dtype)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        "sequence_conv", inputs={"X": input, "Filter": w},
        outputs={"Out": out},
        attrs={"contextLength": filter_size,
               "contextStart": -(filter_size // 2)})
    out = helper.append_bias_op(out, dim_start=2)
    return helper.append_activation(out)


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    """Sinusoidal position mix-in (reference: layers/nn.py
    add_position_encoding)."""
    return _simple_op_layer(
        "add_position_encoding", {"X": input},
        {"alpha": float(alpha), "beta": float(beta)}, name=name)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None):
    """3-D convolution, NCDHW (reference: layers/nn.py conv3d)."""
    helper = LayerHelper("conv3d", name=name, bias_attr=bias_attr, act=act)
    c_in = input.shape[1]

    def triple(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    fs = triple(filter_size)
    w = helper.create_parameter(
        ParamAttr._to_attr(param_attr),
        shape=[num_filters, c_in // groups] + fs, dtype=input.dtype)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        "conv3d", inputs={"Input": input, "Filter": w},
        outputs={"Output": out},
        attrs={"strides": triple(stride), "paddings": triple(padding),
               "dilations": triple(dilation), "groups": groups})
    out = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(out)


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    """Hierarchical sigmoid over a complete binary tree (reference:
    layers/nn.py hsigmoid / hsigmoid_op.cc). Cost [b, 1]."""
    helper = LayerHelper("hsigmoid", name=name)
    d = input.shape[-1]
    w = helper.create_parameter(
        ParamAttr._to_attr(param_attr),
        shape=[num_classes - 1, d], dtype=input.dtype)
    b = helper.create_parameter(
        ParamAttr._to_attr(bias_attr), shape=[num_classes - 1],
        dtype=input.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    pre = helper.create_variable_for_type_inference(dtype=input.dtype)
    inputs = {"X": input, "W": w, "Label": label}
    if b is not None:
        inputs["Bias"] = b
    helper.append_op(
        "hierarchical_sigmoid", inputs=inputs,
        outputs={"Out": out, "PreOut": pre},
        attrs={"num_classes": int(num_classes)})
    return out


def sample_logits(logits, label, num_samples, remove_accidental_hits=True,
                  name=None):
    """Sampled-softmax logits slice (reference: layers/nn.py
    sample_logits). Returns (sampled_logits, sampled_label); feed them to
    softmax_with_cross_entropy."""
    helper = LayerHelper("sample_logits", name=name)
    outs = {
        s: helper.create_variable_for_type_inference(
            dtype="int64" if s in ("Samples", "SampledLabel") else
            logits.dtype,
            stop_gradient=s != "SampledLogits")
        for s in ("Samples", "Probabilities", "SampledLogits",
                  "SampledLabel")
    }
    helper.append_op(
        "sample_logits", inputs={"Logits": logits, "Labels": label},
        outputs=outs,
        attrs={"num_samples": int(num_samples),
               "remove_accidental_hits": bool(remove_accidental_hits)})
    return outs["SampledLogits"], outs["SampledLabel"]


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """Chunk-level P/R/F1 for tagging (reference: layers/nn.py
    chunk_eval). Returns (precision, recall, f1, n_infer, n_label,
    n_correct)."""
    helper = LayerHelper("chunk_eval")
    outs = {}
    for slot, dt in [("Precision", "float32"), ("Recall", "float32"),
                     ("F1-Score", "float32"), ("NumInferChunks", "int64"),
                     ("NumLabelChunks", "int64"),
                     ("NumCorrectChunks", "int64")]:
        outs[slot] = helper.create_variable_for_type_inference(
            dtype=dt, stop_gradient=True)
    inputs = {"Inference": input, "Label": label}
    if seq_length is not None:
        inputs["SeqLength"] = seq_length
    helper.append_op(
        "chunk_eval", inputs=inputs, outputs=outs,
        attrs={"num_chunk_types": num_chunk_types,
               "chunk_scheme": chunk_scheme,
               "excluded_chunk_types": list(excluded_chunk_types or [])})
    return (outs["Precision"], outs["Recall"], outs["F1-Score"],
            outs["NumInferChunks"], outs["NumLabelChunks"],
            outs["NumCorrectChunks"])


def ctc_greedy_decoder(input, blank, input_length=None, name=None):
    """Greedy CTC decode: per-step argmax then ctc_align merge/blank
    removal (reference: layers/nn.py ctc_greedy_decoder). ``input``
    [B, T, C] probabilities; returns (decoded [B, T] left-compacted with
    -1/0 padding, out_length [B, 1])."""
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    top1 = argmax(input, axis=-1)
    decoded = helper.create_variable_for_type_inference(
        dtype="int64", stop_gradient=True)
    out_len = helper.create_variable_for_type_inference(
        dtype="int32", stop_gradient=True)
    inputs = {"Input": top1}
    if input_length is not None:
        inputs["InputLength"] = input_length
    helper.append_op(
        "ctc_align", inputs=inputs,
        outputs={"Output": decoded, "OutputLength": out_len},
        attrs={"blank": blank, "merge_repeated": True})
    return decoded, out_len


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Register a user Python callable as an operator (reference:
    layers/nn.py:11059 py_func + operators/py_func_op.cc:105). ``func``
    runs on the HOST inside the compiled step via ``jax.pure_callback``;
    ``out`` variables must be pre-created with shapes/dtypes (XLA needs a
    static callback signature — same contract as the reference's "users
    should create out beforehand"). ``backward_func`` receives forward
    inputs, forward outputs, then output gradients (None where absent),
    and returns input gradients (None = no grad)."""
    from paddle_tpu.ops.misc_ops import register_py_func

    helper = LayerHelper("py_func")
    if x is None:
        x = []
    elif isinstance(x, Variable):
        x = [x]
    if out is None:
        out_list = []
    elif isinstance(out, Variable):
        out_list = [out]
    else:
        out_list = list(out)
    for o in out_list:
        if o.shape is None:
            raise ValueError(
                "py_func output shapes must be provided by users manually")
        if any(int(d) < 0 for d in o.shape):
            raise ValueError(
                f"py_func output '{o.name}' has dynamic shape "
                f"{tuple(o.shape)}; the host callback needs a static XLA "
                f"signature — declare concrete dims (including batch)")
    fwd_id = register_py_func(func)
    bwd_id = register_py_func(backward_func) if backward_func else -1
    skip = skip_vars_in_backward_input
    if isinstance(skip, Variable):
        skip = [skip]
    skip_names = [v.name if isinstance(v, Variable) else v for v in skip or []]
    in_out = {v.name for v in list(x) + out_list}
    for n in skip_names:
        if n not in in_out:
            raise ValueError(f"Variable {n} is not found in forward inputs "
                             f"and outputs")
    helper.append_op(
        "py_func",
        inputs={"X": list(x)},
        outputs={"Out": out_list},
        attrs={
            "forward_callable_id": fwd_id,
            "backward_callable_id": bwd_id,
            "out_shapes": [[int(d) for d in o.shape] for o in out_list],
            "out_dtypes": [str(o.dtype) for o in out_list],
            "backward_skip_vars": skip_names,
        },
    )
    return out


def hash(input, hash_size, num_hash=1, name=None):
    """Multi-seed feature hashing into ``[0, hash_size)`` buckets
    (reference: layers/nn.py:10456 + operators/hash_op.cc). ``input``
    [N, d] integer ids; output [N, num_hash, 1].

    Bucket-value compatibility: under ``jax_enable_x64`` the op is
    bit-exact XXH64 and buckets match the reference (so vocabularies,
    pretrained embedding tables, and serving systems built against
    reference hash buckets port numerically). With x64 DISABLED (the
    JAX default) a different mixer is used and bucket values differ
    from the reference — enable x64 before building or porting any
    artifact keyed by hash buckets."""
    helper = LayerHelper("hash", name=name)
    out = helper.create_variable_for_type_inference(
        dtype=input.dtype, stop_gradient=True)
    helper.append_op(
        "hash", inputs={"X": input}, outputs={"Out": out},
        attrs={"num_hash": num_hash, "mod_by": hash_size})
    return out


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    """Tree-based convolution over node features (reference:
    layers/nn.py:11351 tree_conv + operators/tree_conv_op.cc).
    ``nodes_vector`` [N, n, f], ``edge_set`` [N, e, 2] directional
    parent->child 1-indexed edges; output [N, n, output_size,
    num_filters]."""
    helper = LayerHelper("tree_conv", name=name, bias_attr=bias_attr,
                         act=act)
    dtype = nodes_vector.dtype
    feature_size = int(nodes_vector.shape[2])
    w = helper.create_parameter(
        attr=param_attr, shape=[feature_size, 3, output_size, num_filters],
        dtype=dtype, is_bias=False)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        "tree_conv",
        inputs={"NodesVector": nodes_vector, "EdgeSet": edge_set,
                "Filter": w},
        outputs={"Out": out},
        attrs={"max_depth": max_depth})
    if bias_attr is not False:
        out = helper.append_bias_op(out, dim_start=2)
    return helper.append_activation(out)
