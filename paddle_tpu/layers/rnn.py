"""Recurrent layers: dynamic_lstm / dynamic_gru / lstm_lm helpers.

Reference API: python/paddle/fluid/layers/nn.py (dynamic_lstm:443,
dynamic_gru:743). Like the reference, the input-to-hidden projection is NOT
part of these layers — callers project with ``fc`` first (one big MXU matmul
over all timesteps), and the layer scans only the recurrent part. Input is a
padded dense batch ``[B, T, 4H|3H]`` (+ optional lengths) instead of a LoD
tensor.
"""

from __future__ import annotations

from paddle_tpu.framework import Variable
from paddle_tpu.layer_helper import LayerHelper

__all__ = ["dynamic_lstm", "dynamic_gru"]


def dynamic_lstm(
    input: Variable,
    size: int,
    length: Variable = None,
    h_0: Variable = None,
    c_0: Variable = None,
    param_attr=None,
    bias_attr=None,
    use_peepholes: bool = False,
    is_reverse: bool = False,
    gate_activation: str = "sigmoid",
    cell_activation: str = "tanh",
    candidate_activation: str = "tanh",
    dtype: str = "float32",
    name=None,
):
    """LSTM over ``input`` [B, T, 4*H] (pre-projected gates); returns
    (hidden [B, T, H], cell [B, T, H])."""
    if use_peepholes:
        raise NotImplementedError(
            "peephole connections are not supported (rarely used; the "
            "reference defaults them on but every benchmark model disables "
            "them)"
        )
    helper = LayerHelper("lstm", name=name, param_attr=param_attr,
                         bias_attr=bias_attr)
    h = size // 4
    weight = helper.create_parameter(param_attr, shape=[h, size], dtype=dtype)
    bias = helper.create_parameter(
        bias_attr, shape=[size], dtype=dtype, is_bias=True
    )
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": input, "Weight": weight}
    if bias is not None:
        inputs["Bias"] = bias
    if h_0 is not None:
        inputs["H0"] = h_0
    if c_0 is not None:
        inputs["C0"] = c_0
    if length is not None:
        inputs["Length"] = length
    helper.append_op(
        "lstm",
        inputs=inputs,
        outputs={
            "Hidden": hidden,
            "Cell": cell,
            "LastH": last_h,
            "LastC": last_c,
        },
        attrs={
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "cell_activation": cell_activation,
            "candidate_activation": candidate_activation,
        },
    )
    return hidden, cell


def dynamic_gru(
    input: Variable,
    size: int,
    length: Variable = None,
    h_0: Variable = None,
    param_attr=None,
    bias_attr=None,
    is_reverse: bool = False,
    gate_activation: str = "sigmoid",
    candidate_activation: str = "tanh",
    dtype: str = "float32",
    name=None,
):
    """GRU over ``input`` [B, T, 3*H] (pre-projected gates); returns
    hidden [B, T, H]."""
    helper = LayerHelper("gru", name=name, param_attr=param_attr,
                         bias_attr=bias_attr)
    h = size
    weight = helper.create_parameter(
        param_attr, shape=[h, 3 * h], dtype=dtype
    )
    bias = helper.create_parameter(
        bias_attr, shape=[3 * h], dtype=dtype, is_bias=True
    )
    hidden = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": input, "Weight": weight}
    if bias is not None:
        inputs["Bias"] = bias
    if h_0 is not None:
        inputs["H0"] = h_0
    if length is not None:
        inputs["Length"] = length
    helper.append_op(
        "gru",
        inputs=inputs,
        outputs={"Hidden": hidden, "LastH": last_h},
        attrs={
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "activation": candidate_activation,
        },
    )
    return hidden
