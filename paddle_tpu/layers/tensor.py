"""Tensor creation layers (reference: python/paddle/fluid/layers/tensor.py)."""

from __future__ import annotations


import numpy as np

from paddle_tpu.framework import Variable, convert_np_dtype_to_dtype_
from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "create_tensor", "create_parameter", "create_global_var", "fill_constant",
    "assign", "zeros", "ones", "zeros_like", "ones_like", "range_",
    "linspace", "uniform_random", "gaussian_random", "shape", "slice",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.block.create_var(
        name=name or None, dtype=convert_np_dtype_to_dtype_(dtype),
        persistable=persistable,
    )


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from paddle_tpu.param_attr import ParamAttr

    helper = LayerHelper("create_parameter", name=name)
    attr = ParamAttr._to_attr(attr)
    if name and attr.name is None:
        attr.name = name
    return helper.create_parameter(
        attr, shape, dtype, is_bias=is_bias,
        default_initializer=default_initializer,
    )


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """A persistable var initialized in the startup program."""
    from paddle_tpu import unique_name
    from paddle_tpu.framework import default_startup_program, default_main_program

    name = name or unique_name.generate("global_var")
    dtype = convert_np_dtype_to_dtype_(dtype)
    sb = default_startup_program().global_block()
    sv = sb.create_var(name=name, shape=shape, dtype=dtype, persistable=persistable)
    sb.append_op(
        "fill_constant",
        outputs={"Out": name},
        attrs={"shape": list(shape), "dtype": dtype, "value": float(value)},
    )
    mb = default_main_program().global_block()
    return mb.create_var(name=name, shape=shape, dtype=dtype,
                         persistable=persistable)


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    dtype = convert_np_dtype_to_dtype_(dtype)
    out = out or helper.create_variable_for_type_inference(
        dtype=dtype, stop_gradient=True)
    helper.append_op(
        "fill_constant",
        outputs={"Out": out},
        attrs={"shape": list(shape), "dtype": dtype, "value": float(value)},
    )
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        output = output or helper.create_variable_for_type_inference(
            dtype=input.dtype)
        helper.append_op("assign", inputs={"X": input}, outputs={"Out": output})
    else:
        arr = np.asarray(input)
        output = output or helper.create_variable_for_type_inference(
            dtype=arr.dtype.name)
        helper.append_op(
            "assign_value",
            outputs={"Out": output},
            attrs={
                "shape": list(arr.shape),
                "dtype": arr.dtype.name,
                "values": [float(x) for x in arr.reshape(-1)],
            },
        )
    return output


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros_like(x, out=None):
    helper = LayerHelper("fill_zeros_like")
    out = out or helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("fill_zeros_like", inputs={"X": x}, outputs={"Out": out})
    return out


def ones_like(x, out=None):
    helper = LayerHelper("fill_any_like")
    out = out or helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("fill_any_like", inputs={"X": x}, outputs={"Out": out},
                     attrs={"value": 1.0})
    return out


def range_(start, end, step, dtype):
    helper = LayerHelper("range")
    dtype = convert_np_dtype_to_dtype_(dtype)
    out = helper.create_variable_for_type_inference(dtype=dtype, stop_gradient=True)
    helper.append_op(
        "range", outputs={"Out": out},
        attrs={"start": start, "end": end, "step": step, "dtype": dtype},
    )
    return out


def linspace(start, stop, num, dtype="float32"):
    step = (stop - start) / max(num - 1, 1)
    return range_(start, stop + step / 2, step, dtype)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(
        dtype=dtype, stop_gradient=True)
    helper.append_op(
        "uniform_random", outputs={"Out": out},
        attrs={"shape": list(shape), "dtype": dtype, "min": float(min),
               "max": float(max), "seed": seed},
    )
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(
        dtype=dtype, stop_gradient=True)
    helper.append_op(
        "gaussian_random", outputs={"Out": out},
        attrs={"shape": list(shape), "dtype": dtype, "mean": float(mean),
               "std": float(std), "seed": seed},
    )
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference(dtype="int64", stop_gradient=True)
    helper.append_op("shape", inputs={"X": input}, outputs={"Out": out})
    return out


def slice(input, axes, starts, ends):
    """Static slicing (reference: layers/nn.py slice / slice_op.cc)."""
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        "slice", inputs={"X": input}, outputs={"Out": out},
        attrs={"axes": list(axes), "starts": list(starts),
               "ends": list(ends)},
    )
    return out
