"""Python-side streaming metrics (reference: python/paddle/fluid/metrics.py).

Accumulate per-batch fetch results host-side; the per-batch values come
from metric ops (ops/nn_ops.py accuracy, auc, mean_iou).
"""

from __future__ import annotations

import numpy as np


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        self.value += float(value) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no samples accumulated")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """Precision/recall/F1 over chunk counts (reference: metrics.py
    ChunkEvaluator)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(num_infer_chunks)
        self.num_label_chunks += int(num_label_chunks)
        self.num_correct_chunks += int(num_correct_chunks)

    def eval(self):
        precision = (
            self.num_correct_chunks / self.num_infer_chunks
            if self.num_infer_chunks else 0.0
        )
        recall = (
            self.num_correct_chunks / self.num_label_chunks
            if self.num_label_chunks else 0.0
        )
        f1 = (
            2 * precision * recall / (precision + recall)
            if precision + recall else 0.0
        )
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances)
        self.total_distance += float(distances.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int((distances > 0).sum())

    def eval(self):
        avg = self.total_distance / max(self.seq_num, 1)
        err_rate = self.instance_error / max(self.seq_num, 1)
        return avg, err_rate


class Auc(MetricBase):
    """Streaming ROC-AUC via score histograms (reference: metrics.py Auc)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds + 1)
        self._stat_neg = np.zeros(self._num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        idx = np.clip(
            (pos_prob * self._num_thresholds).astype(int),
            0,
            self._num_thresholds,
        )
        n = self._num_thresholds + 1
        pos = labels.astype(bool)
        self._stat_pos += np.bincount(idx[pos], minlength=n)
        self._stat_neg += np.bincount(idx[~pos], minlength=n)

    def eval(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.5
        # trapezoid over thresholds, descending
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.asarray(preds).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        pred_pos = preds > 0.5
        self.tp += int(np.sum(pred_pos & (labels == 1)))
        self.fp += int(np.sum(pred_pos & (labels == 0)))

    def eval(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.asarray(preds).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        pred_pos = preds > 0.5
        self.tp += int(np.sum(pred_pos & (labels == 1)))
        self.fn += int(np.sum(~pred_pos & (labels == 1)))

    def eval(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]
