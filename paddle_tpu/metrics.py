"""Python-side streaming metrics (reference: python/paddle/fluid/metrics.py).

Accumulate per-batch fetch results host-side; the per-batch values come
from metric ops (ops/nn_ops.py accuracy, auc, mean_iou).
"""

from __future__ import annotations

import numpy as np


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        self.value += float(value) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no samples accumulated")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """Precision/recall/F1 over chunk counts (reference: metrics.py
    ChunkEvaluator)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(num_infer_chunks)
        self.num_label_chunks += int(num_label_chunks)
        self.num_correct_chunks += int(num_correct_chunks)

    def eval(self):
        precision = (
            self.num_correct_chunks / self.num_infer_chunks
            if self.num_infer_chunks else 0.0
        )
        recall = (
            self.num_correct_chunks / self.num_label_chunks
            if self.num_label_chunks else 0.0
        )
        f1 = (
            2 * precision * recall / (precision + recall)
            if precision + recall else 0.0
        )
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances)
        self.total_distance += float(distances.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int((distances > 0).sum())

    def eval(self):
        avg = self.total_distance / max(self.seq_num, 1)
        err_rate = self.instance_error / max(self.seq_num, 1)
        return avg, err_rate


class Auc(MetricBase):
    """Streaming ROC-AUC via score histograms (reference: metrics.py Auc)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds + 1)
        self._stat_neg = np.zeros(self._num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        idx = np.clip(
            (pos_prob * self._num_thresholds).astype(int),
            0,
            self._num_thresholds,
        )
        n = self._num_thresholds + 1
        pos = labels.astype(bool)
        self._stat_pos += np.bincount(idx[pos], minlength=n)
        self._stat_neg += np.bincount(idx[~pos], minlength=n)

    def eval(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.5
        # trapezoid over thresholds, descending
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.asarray(preds).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        pred_pos = preds > 0.5
        self.tp += int(np.sum(pred_pos & (labels == 1)))
        self.fp += int(np.sum(pred_pos & (labels == 0)))

    def eval(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.asarray(preds).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        pred_pos = preds > 0.5
        self.tp += int(np.sum(pred_pos & (labels == 1)))
        self.fn += int(np.sum(~pred_pos & (labels == 1)))

    def eval(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class DetectionMAP:
    """Detection mean average precision with cross-batch accumulation
    (reference: metrics.py DetectionMAP:687). Graph-building like the
    reference: the constructor appends a stateless per-batch
    ``detection_map`` op plus a stateful accumulated one; fetch both
    vars from ``get_map_var()`` every batch and ``reset(exe)`` between
    evaluation passes.

    TPU-native accumulation: the reference grows LoD state tensors
    batch by batch (dynamic shapes); here the states are FIXED-SIZE
    per-class score-binned TP/FP histograms plus positive counts
    (ops/detection_ops.py detection_map docstring), so the whole metric
    stays inside one static XLA program. ``detect_res`` rows are
    (label, score, x1, y1, x2, y2) with label < 0 padding — the dense
    analog of the reference's LoD detection output.
    """

    def __init__(self, input, gt_label, gt_box, gt_difficult=None,
                 class_num=None, background_label=0,
                 overlap_threshold=0.5, evaluate_difficult=True,
                 ap_version="integral", score_bins=1024):
        if class_num is None:
            raise ValueError("class_num is required")
        from paddle_tpu import layers
        from paddle_tpu.layers import tensor as tensor_layers
        from paddle_tpu import unique_name

        gt_label = layers.cast(gt_label, gt_box.dtype)
        if gt_difficult is not None:
            gt_difficult = layers.cast(gt_difficult, gt_box.dtype)
            label = layers.concat([gt_label, gt_difficult, gt_box], axis=-1)
        else:
            label = layers.concat([gt_label, gt_box], axis=-1)

        def state(suffix, shape):
            return tensor_layers.create_global_var(
                shape=shape, value=0.0, dtype="float32", persistable=True,
                name=unique_name.generate(f"detection_map_{suffix}"))

        states = (state("accum_pos_count", [class_num]),
                  state("accum_true_pos", [class_num, score_bins]),
                  state("accum_false_pos", [class_num, score_bins]))
        self.has_state = state("has_state", [1])
        # ONE stateful op computes both the batch and accumulated mAP
        # (the stateless+stateful pair would run the greedy matching
        # twice per step)
        self.cur_map, self.accum_map = layers.detection_map(
            input, label, class_num, background_label,
            overlap_threshold=overlap_threshold,
            evaluate_difficult=evaluate_difficult,
            has_state=self.has_state, input_states=states,
            out_states=states, ap_version=ap_version)
        # first accumulating batch after this ADDS to the (zero) states;
        # later ones add to the running totals (reference: metrics.py
        # fill_constant of has_state to 1 after the stateful op)
        layers.fill_constant(shape=[1], value=1.0, dtype="float32",
                             out=self.has_state)

    def get_map_var(self):
        """(current mini-batch mAP var, accumulated mAP var)."""
        return self.cur_map, self.accum_map

    def reset(self, executor, reset_program=None):
        """Zero the accumulation gate so the next batch restarts the
        running totals (the reference resets has_state only)."""
        from paddle_tpu import layers
        from paddle_tpu.framework import Program, program_guard

        if reset_program is None:
            reset_program = Program()
        with program_guard(reset_program):
            var = reset_program.global_block().create_var(
                name=self.has_state.name, shape=[1], dtype="float32",
                persistable=True)
            layers.fill_constant(shape=[1], value=0.0, dtype="float32",
                                 out=var)
        executor.run(reset_program)
