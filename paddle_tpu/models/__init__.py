"""Model zoo (reference: benchmark/fluid/models/ + tests/book models)."""

from paddle_tpu.models import (  # noqa: F401
    bert,
    deepfm,
    mnist,
    resnet,
    se_resnext,
    seq2seq,
    stacked_lstm,
    transformer,
    vgg,
)
