"""Model zoo (reference: benchmark/fluid/models/ + tests/book models)."""

from paddle_tpu.models import mnist, resnet, transformer, vgg  # noqa: F401
