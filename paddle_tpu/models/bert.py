"""BERT-base pretraining model (BASELINE.md row "BERT-base pretraining").

Encoder-only transformer with masked-LM + next-sentence heads. Reuses the
flagship transformer's encoder layer (models/transformer.py — fused QKV
projection, flash-attention sdpa op, TP-ready ``*_colp/_rowp`` parameter
naming), so the same sharding rules and AMP policy apply. The reference
has no in-tree BERT; this covers the layer_norm+matmul-heavy pretraining
capability the baseline targets.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.models import transformer as T
from paddle_tpu.param_attr import ParamAttr


class BertConfig:
    def __init__(
        self,
        vocab_size: int = 30522,
        max_position: int = 512,
        type_vocab_size: int = 2,
        d_model: int = 768,
        d_inner: int = 3072,
        n_head: int = 12,
        n_layer: int = 12,
        dropout: float = 0.1,
    ):
        self.vocab_size = vocab_size
        self.max_position = max_position
        self.type_vocab_size = type_vocab_size
        self.d_model = d_model
        self.d_inner = d_inner
        self.n_head = n_head
        self.n_layer = n_layer
        self.dropout = dropout

    def encoder_cfg(self) -> T.TransformerConfig:
        return T.TransformerConfig(
            src_vocab_size=self.vocab_size,
            trg_vocab_size=self.vocab_size,
            max_length=self.max_position,
            d_model=self.d_model,
            d_inner=self.d_inner,
            n_head=self.n_head,
            n_layer=self.n_layer,
            dropout=self.dropout,
            label_smooth_eps=0.0,
        )


def base() -> BertConfig:
    return BertConfig()


def build(cfg: Optional[BertConfig] = None, is_test: bool = False):
    """Pretraining graph. Feeds: input_ids [b, t], token_type_ids [b, t],
    pad_mask [b, t] (1 = real), mlm_labels [b, t] (-1 = unmasked
    position), nsp_labels [b, 1]."""
    cfg = cfg or base()
    ecfg = cfg.encoder_cfg()

    ids = layers.data("input_ids", shape=[-1], dtype="int64")
    type_ids = layers.data("token_type_ids", shape=[-1], dtype="int64")
    pad = layers.data("pad_mask", shape=[-1], dtype="float32")
    mlm_lbl = layers.data("mlm_labels", shape=[-1], dtype="int64")
    nsp_lbl = layers.data("nsp_labels", shape=[1], dtype="int64")

    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("bert")
    bias = helper.create_variable_for_type_inference("float32", True)
    helper.append_op("attn_bias", inputs={"PadMask": pad},
                     outputs={"Out": bias}, attrs={"causal": False})

    tok = layers.embedding(
        ids, size=[cfg.vocab_size, cfg.d_model],
        param_attr=ParamAttr(
            name="bert_tok_emb.w",
            initializer=fluid.initializer.NormalInitializer(0.0, 0.02)),
    )
    seg = layers.embedding(
        type_ids, size=[cfg.type_vocab_size, cfg.d_model],
        param_attr=ParamAttr(
            name="bert_seg_emb.w",
            initializer=fluid.initializer.NormalInitializer(0.0, 0.02)),
    )
    pos_ids = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("position_ids", inputs={"X": ids},
                     outputs={"Out": pos_ids})
    pos = layers.embedding(
        pos_ids, size=[cfg.max_position, cfg.d_model],
        param_attr=ParamAttr(
            name="bert_pos_emb.w",
            initializer=fluid.initializer.NormalInitializer(0.0, 0.02)),
    )
    x = layers.elementwise_add(layers.elementwise_add(tok, seg), pos)
    x = layers.layer_norm(
        x, begin_norm_axis=2,
        param_attr=ParamAttr(name="bert_emb_ln.scale"),
        bias_attr=ParamAttr(name="bert_emb_ln.bias"),
    )
    if cfg.dropout and not is_test:
        x = layers.dropout(x, cfg.dropout,
                           dropout_implementation="upscale_in_train")

    for i in range(cfg.n_layer):
        x = T.encoder_layer(x, bias, ecfg, i, is_test)
    x = T._ln(x, "enc_post")

    # MLM head: transform + vocab projection
    mlm = layers.fc(
        x, cfg.d_model, num_flatten_dims=2, act="gelu",
        param_attr=ParamAttr(name="mlm_tr_colp.w"),
        bias_attr=ParamAttr(name="mlm_tr_colp.b"),
    )
    mlm = layers.layer_norm(
        mlm, begin_norm_axis=2,
        param_attr=ParamAttr(name="mlm_ln.scale"),
        bias_attr=ParamAttr(name="mlm_ln.bias"),
    )
    mlm_logits = layers.fc(
        mlm, cfg.vocab_size, num_flatten_dims=2,
        param_attr=ParamAttr(name="mlm_proj_colp.w"), bias_attr=False,
    )

    # NSP head over the [CLS] (first) position
    cls = layers.squeeze(
        layers.slice(x, axes=[1], starts=[0], ends=[1]), [1])
    nsp_logits = layers.fc(
        cls, 2,
        param_attr=ParamAttr(name="nsp.w"),
        bias_attr=ParamAttr(name="nsp.b"),
    )

    # masked-LM loss over masked positions only (mlm_labels == -1 ignored)
    safe_lbl = layers.elementwise_max(
        mlm_lbl, layers.fill_constant_like(mlm_lbl, 0.0))
    ce = layers.softmax_with_cross_entropy(
        mlm_logits, layers.unsqueeze(safe_lbl, [2]))
    ce = layers.reshape(ce, [0, -1])
    is_masked = layers.cast(
        layers.greater_than(
            layers.cast(mlm_lbl, "float32"),
            layers.fill_constant_like(
                layers.cast(mlm_lbl, "float32"), -0.5)),
        "float32",
    )
    mlm_count = layers.elementwise_max(
        layers.reduce_sum(is_masked),
        layers.fill_constant([], "float32", 1.0))
    mlm_loss = layers.elementwise_div(
        layers.reduce_sum(layers.elementwise_mul(ce, is_masked)), mlm_count)

    nsp_loss = layers.mean(
        layers.softmax_with_cross_entropy(nsp_logits, nsp_lbl))
    loss = layers.elementwise_add(mlm_loss, nsp_loss)
    return {
        "feeds": [ids, type_ids, pad, mlm_lbl, nsp_lbl],
        "loss": loss,
        "mlm_loss": mlm_loss,
        "nsp_loss": nsp_loss,
        "mlm_logits": mlm_logits,
        "config": cfg,
    }


def make_batch(cfg: BertConfig, batch: int, seq_len: int,
               seed: int = 0) -> Dict[str, np.ndarray]:
    r = np.random.RandomState(seed)
    ids = r.randint(4, cfg.vocab_size, (batch, seq_len)).astype(np.int64)
    type_ids = np.zeros((batch, seq_len), np.int64)
    half = seq_len // 2
    type_ids[:, half:] = 1
    pad = np.ones((batch, seq_len), np.float32)
    mlm = np.full((batch, seq_len), -1, np.int64)
    n_mask = max(1, int(seq_len * 0.15))
    for row in range(batch):
        pos = r.choice(seq_len, n_mask, replace=False)
        mlm[row, pos] = ids[row, pos]
        ids[row, pos] = 3  # [MASK]
    nsp = r.randint(0, 2, (batch, 1)).astype(np.int64)
    return {"input_ids": ids, "token_type_ids": type_ids, "pad_mask": pad,
            "mlm_labels": mlm, "nsp_labels": nsp}
