"""DeepFM CTR model (sparse-embedding benchmark config, BASELINE.md).

The capability twin of the reference's distributed-lookup-table CTR path:
sparse feature embeddings served by row-sharded tables (reference:
operators/distributed/parameter_prefetch.cc, transpiler
distribute_transpiler.py:1317 — pserver-sharded rows prefetched by id over
RPC). Here ``layers.embedding(is_distributed=True)`` marks the tables; under
``CompiledProgram.with_strategy`` with a ``table_axis`` the rows shard over
the mesh and lookups combine with an ICI psum (parallel/embedding.py).

Model (DeepFM, Guo et al. 2017): y = sigmoid(first_order + FM pairwise
interactions + deep MLP over concatenated field embeddings).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from paddle_tpu import layers
from paddle_tpu.param_attr import ParamAttr


class DeepFMConfig:
    def __init__(
        self,
        num_fields: int = 26,
        vocab_size: int = 1024,
        embed_dim: int = 8,
        hidden: tuple = (64, 32),
    ):
        self.num_fields = num_fields
        self.vocab_size = vocab_size
        self.embed_dim = embed_dim
        self.hidden = tuple(hidden)


def build(cfg: Optional[DeepFMConfig] = None, is_distributed: bool = True,
          is_sparse: bool = True):
    """Builds the DeepFM graph in the current program.

    Feeds: feat_ids [b, F] int64 (one id per field), label [b, 1] f32.
    Returns {"feeds", "loss", "logit", "config"}.

    ``is_sparse``: row-sparse {rows, values} embedding gradients + lazy
    per-row optimizer updates instead of dense [V, D] scatter-adds — the
    CTR-scale capability the reference served with SelectedRows
    (ops/sparse_ops.py).
    """
    cfg = cfg or DeepFMConfig()
    f, k = cfg.num_fields, cfg.embed_dim
    ids = layers.data("feat_ids", shape=[f], dtype="int64")
    label = layers.data("label", shape=[1], dtype="float32")

    # first-order weights: [V, 1] table
    w1 = layers.embedding(
        ids, size=[cfg.vocab_size, 1], is_distributed=is_distributed,
        is_sparse=is_sparse,
        param_attr=ParamAttr(name="deepfm_first.w"),
    )  # [b, F, 1]
    first = layers.reduce_sum(w1, dim=1)  # [b, 1]

    # second-order factor table: [V, K]
    emb = layers.embedding(
        ids, size=[cfg.vocab_size, k], is_distributed=is_distributed,
        is_sparse=is_sparse,
        param_attr=ParamAttr(name="deepfm_factor.w"),
    )  # [b, F, K]
    summed = layers.reduce_sum(emb, dim=1)  # [b, K]
    sum_sq = layers.elementwise_mul(summed, summed)
    sq = layers.elementwise_mul(emb, emb)
    sq_sum = layers.reduce_sum(sq, dim=1)  # [b, K]
    fm = layers.scale(
        layers.reduce_sum(
            layers.elementwise_sub(sum_sq, sq_sum), dim=1, keep_dim=True
        ),
        scale=0.5,
    )  # [b, 1]

    # deep tower over the concatenated field embeddings
    deep = layers.reshape(emb, [-1, f * k])
    for i, h in enumerate(cfg.hidden):
        deep = layers.fc(
            deep, h, act="relu", num_flatten_dims=1,
            param_attr=ParamAttr(name=f"deepfm_mlp{i}.w"),
            bias_attr=ParamAttr(name=f"deepfm_mlp{i}.b"),
        )
    deep = layers.fc(
        deep, 1, num_flatten_dims=1,
        param_attr=ParamAttr(name="deepfm_out.w"),
        bias_attr=ParamAttr(name="deepfm_out.b"),
    )

    logit = layers.elementwise_add(layers.elementwise_add(first, fm), deep)
    loss = layers.mean(
        layers.sigmoid_cross_entropy_with_logits(logit, label)
    )
    return {"feeds": [ids, label], "loss": loss, "logit": logit,
            "config": cfg}


def make_batch(cfg: DeepFMConfig, batch: int, seed: int = 0) -> Dict[str, np.ndarray]:
    """Synthetic CTR batch: per-field ids hash into disjoint vocab ranges,
    labels from a fixed linear probe so the task is learnable."""
    r = np.random.RandomState(seed)
    per_field = cfg.vocab_size // cfg.num_fields
    ids = np.stack(
        [
            r.randint(i * per_field, (i + 1) * per_field, batch)
            for i in range(cfg.num_fields)
        ],
        axis=1,
    ).astype(np.int64)
    probe = np.sin(np.arange(cfg.vocab_size) * 0.7)
    score = probe[ids].sum(axis=1)
    label = (score > 0).astype(np.float32)[:, None]
    return {"feat_ids": ids, "label": label}
