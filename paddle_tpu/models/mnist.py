"""MNIST models (reference: benchmark/fluid/models/mnist.py and
tests/book/test_recognize_digits.py conv/mlp variants)."""

from __future__ import annotations

from paddle_tpu import layers


def mlp(img, label):
    h1 = layers.fc(img, 200, act="relu")
    h2 = layers.fc(h1, 200, act="relu")
    logits = layers.fc(h2, 10)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(logits, label)
    return loss, acc, logits


def conv_net(img, label):
    """LeNet-style conv net (reference: benchmark/fluid/models/mnist.py
    cnn_model)."""
    x = layers.reshape(img, [-1, 1, 28, 28])
    c1 = layers.conv2d(x, 20, 5, act="relu")
    p1 = layers.pool2d(c1, 2, "max", 2)
    c2 = layers.conv2d(p1, 50, 5, act="relu")
    p2 = layers.pool2d(c2, 2, "max", 2)
    logits = layers.fc(p2, 10)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(logits, label)
    return loss, acc, logits


def get_model(batch_size: int = 64, use_conv: bool = True):
    """benchmark-harness entry (reference: benchmark/fluid/models pattern:
    get_model returns (feeds, loss, acc))."""
    img = layers.data("pixel", shape=[784], dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    if use_conv:
        loss, acc, logits = conv_net(img, label)
    else:
        loss, acc, logits = mlp(img, label)
    return {"feeds": [img, label], "loss": loss, "acc": acc, "logits": logits}
