"""ResNet for ImageNet/cifar10 (reference: benchmark/fluid/models/resnet.py:171).

Bottleneck-v1 architecture matching the reference's conv_bn_layer /
shortcut / bottleneck_block composition; NCHW API, XLA picks TPU layouts.
"""

from __future__ import annotations

from paddle_tpu import layers
from paddle_tpu.param_attr import ParamAttr


def conv_bn(x, filters, size, stride=1, groups=1, act=None, is_test=False,
            prefix=""):
    c = layers.conv2d(
        x, filters, size, stride=stride, padding=(size - 1) // 2,
        groups=groups, bias_attr=False,
        param_attr=ParamAttr(name=f"{prefix}_conv.w") if prefix else None,
    )
    return layers.batch_norm(c, act=act, is_test=is_test)


def shortcut(x, filters, stride, is_test):
    if x.shape[1] != filters or stride != 1:
        return conv_bn(x, filters, 1, stride, is_test=is_test)
    return x


def bottleneck(x, filters, stride, is_test):
    c0 = conv_bn(x, filters, 1, act="relu", is_test=is_test)
    c1 = conv_bn(c0, filters, 3, stride=stride, act="relu", is_test=is_test)
    c2 = conv_bn(c1, filters * 4, 1, is_test=is_test)
    short = shortcut(x, filters * 4, stride, is_test)
    return layers.elementwise_add(c2, short, act="relu")


def basic_block(x, filters, stride, is_test):
    c0 = conv_bn(x, filters, 3, stride=stride, act="relu", is_test=is_test)
    c1 = conv_bn(c0, filters, 3, is_test=is_test)
    short = shortcut(x, filters, stride, is_test)
    return layers.elementwise_add(c1, short, act="relu")


_DEPTHS = {
    50: ([3, 4, 6, 3], bottleneck),
    101: ([3, 4, 23, 3], bottleneck),
    152: ([3, 8, 36, 3], bottleneck),
    18: ([2, 2, 2, 2], basic_block),
    34: ([3, 4, 6, 3], basic_block),
}


def resnet_imagenet(img, class_dim=1000, depth=50, is_test=False):
    stages, block = _DEPTHS[depth]
    x = conv_bn(img, 64, 7, stride=2, act="relu", is_test=is_test)
    x = layers.pool2d(x, 3, "max", 2, pool_padding=1)
    for s, n in enumerate(stages):
        for i in range(n):
            x = block(x, 64 * (2 ** s), 2 if i == 0 and s > 0 else 1, is_test)
    x = layers.pool2d(x, 7, "avg", global_pooling=True)
    logits = layers.fc(x, class_dim)
    return logits


def resnet_cifar10(img, class_dim=10, depth=32, is_test=False):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    x = conv_bn(img, 16, 3, act="relu", is_test=is_test)
    for s, filters in enumerate([16, 32, 64]):
        for i in range(n):
            x = basic_block(x, filters, 2 if i == 0 and s > 0 else 1, is_test)
    x = layers.pool2d(x, 8, "avg", global_pooling=True)
    logits = layers.fc(x, class_dim)
    return logits


def get_model(batch_size=32, data_shape=(3, 224, 224), class_dim=1000,
              depth=50, is_test=False):
    img = layers.data("data", shape=list(data_shape), dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    if data_shape[-1] == 32:
        logits = resnet_cifar10(img, class_dim, is_test=is_test)
    else:
        logits = resnet_imagenet(img, class_dim, depth, is_test=is_test)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(logits, label)
    return {"feeds": [img, label], "loss": loss, "acc": acc, "logits": logits}
