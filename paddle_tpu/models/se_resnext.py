"""SE-ResNeXt (reference: benchmark/fluid/models/se_resnext.py and
tests/unittests/test_parallel_executor_seresnext.py flavor).

ResNeXt bottlenecks (grouped 3x3, cardinality 32) with squeeze-excitation
channel gating. Everything maps onto MXU convs + tiny fcs; the SE block's
global pool + 2 fcs fuse into the surrounding graph under XLA.
"""

from __future__ import annotations

from typing import Sequence

from paddle_tpu import layers
from paddle_tpu.param_attr import ParamAttr


def conv_bn_layer(x, filters, size, stride=1, groups=1, act=None,
                  is_test=False, prefix=""):
    y = layers.conv2d(
        x, filters, size, stride=stride, padding=(size - 1) // 2,
        groups=groups, bias_attr=False,
        param_attr=ParamAttr(name=f"{prefix}_conv.w"),
    )
    return layers.batch_norm(
        y, act=act, is_test=is_test,
        param_attr=ParamAttr(name=f"{prefix}_bn.scale"),
        bias_attr=ParamAttr(name=f"{prefix}_bn.offset"),
        moving_mean_name=f"{prefix}_bn.mean",
        moving_variance_name=f"{prefix}_bn.var",
    )


def squeeze_excitation(x, num_channels, reduction_ratio, prefix=""):
    """SE gate (reference: se_resnext.py squeeze_excitation)."""
    pool = layers.pool2d(x, pool_type="avg", global_pooling=True)
    pool = layers.reshape(pool, [-1, num_channels])
    squeeze = layers.fc(
        pool, num_channels // reduction_ratio, act="relu",
        param_attr=ParamAttr(name=f"{prefix}_sqz.w"),
        bias_attr=ParamAttr(name=f"{prefix}_sqz.b"),
    )
    excite = layers.fc(
        squeeze, num_channels, act="sigmoid",
        param_attr=ParamAttr(name=f"{prefix}_exc.w"),
        bias_attr=ParamAttr(name=f"{prefix}_exc.b"),
    )
    scale = layers.reshape(excite, [-1, num_channels, 1, 1])
    return layers.elementwise_mul(x, scale)


def bottleneck_block(x, filters, stride, cardinality, reduction_ratio,
                     is_test, prefix):
    conv0 = conv_bn_layer(x, filters, 1, act="relu", is_test=is_test,
                          prefix=f"{prefix}_c0")
    conv1 = conv_bn_layer(conv0, filters, 3, stride=stride,
                          groups=cardinality, act="relu", is_test=is_test,
                          prefix=f"{prefix}_c1")
    conv2 = conv_bn_layer(conv1, filters * 2, 1, is_test=is_test,
                          prefix=f"{prefix}_c2")
    scale = squeeze_excitation(conv2, filters * 2, reduction_ratio,
                               prefix=f"{prefix}_se")
    c_in = x.shape[1]
    if c_in == filters * 2 and stride == 1:
        short = x
    else:
        short = conv_bn_layer(x, filters * 2, 1, stride=stride,
                              is_test=is_test, prefix=f"{prefix}_sc")
    return layers.relu(layers.elementwise_add(short, scale))


def se_resnext_imagenet(
    img,
    class_dim: int = 1000,
    depth: int = 50,
    cardinality: int = 32,
    reduction_ratio: int = 16,
    is_test: bool = False,
):
    """SE-ResNeXt-50/101 backbone + classifier head."""
    supported = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3]}
    stages = supported[depth]
    filters_list = [128, 256, 512, 1024]

    x = conv_bn_layer(img, 64, 7, stride=2, act="relu", is_test=is_test,
                      prefix="stem")
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1,
                      pool_type="max")
    for block, (n, filters) in enumerate(zip(stages, filters_list)):
        for i in range(n):
            x = bottleneck_block(
                x, filters, stride=2 if i == 0 and block != 0 else 1,
                cardinality=cardinality, reduction_ratio=reduction_ratio,
                is_test=is_test, prefix=f"b{block}_{i}",
            )
    pool = layers.pool2d(x, pool_type="avg", global_pooling=True)
    pool = layers.reshape(pool, [-1, pool.shape[1]])
    drop = layers.dropout(pool, 0.2, is_test=is_test)
    return layers.fc(
        drop, class_dim,
        param_attr=ParamAttr(name="fc_out.w"),
        bias_attr=ParamAttr(name="fc_out.b"),
    )


def get_model(data_shape: Sequence[int] = (3, 224, 224),
              class_dim: int = 1000, depth: int = 50,
              is_test: bool = False):
    img = layers.data("data", shape=list(data_shape), dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    logits = se_resnext_imagenet(img, class_dim, depth, is_test=is_test)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(logits, label)
    return {"feeds": [img, label], "loss": loss, "acc": acc,
            "logits": logits}
