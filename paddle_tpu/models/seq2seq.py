"""LSTM NMT seq2seq with attention (reference:
benchmark/fluid/models/machine_translation.py — the bi-LSTM
encoder/attention-decoder from tests/book/test_machine_translation.py —
and stacked_dynamic_lstm.py's LM flavor).

TPU-first shape: padded [B, T] batches + length masks instead of LoD;
the recurrences are the fused ``lstm`` scan op (ops/rnn_ops.py) whose
input projections are batched MXU matmuls; Luong dot attention over the
encoder states is a pair of batched matmuls + masked softmax (no
per-step Python).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.layers import rnn as rnn_layers
from paddle_tpu.param_attr import ParamAttr


class Seq2SeqConfig:
    def __init__(
        self,
        src_vocab_size: int = 2000,
        trg_vocab_size: int = 2000,
        embed_dim: int = 128,
        hidden_dim: int = 256,
        num_layers: int = 2,
    ):
        self.src_vocab_size = src_vocab_size
        self.trg_vocab_size = trg_vocab_size
        self.embed_dim = embed_dim
        self.hidden_dim = hidden_dim
        self.num_layers = num_layers


def _embed(ids, vocab, dim, name):
    return layers.embedding(
        ids, size=[vocab, dim],
        param_attr=ParamAttr(
            name=name,
            initializer=fluid.initializer.NormalInitializer(0.0, 0.1)),
    )


def _lstm_stack(x, cfg, length, prefix, num_layers):
    """Stacked LSTM: fc gate projection (one [B*T, D]x[D, 4H] MXU matmul
    per layer) + fused scan recurrence."""
    h = cfg.hidden_dim
    for i in range(num_layers):
        gates = layers.fc(
            x, 4 * h, num_flatten_dims=2,
            param_attr=ParamAttr(name=f"{prefix}_l{i}_ih.w"),
            bias_attr=ParamAttr(name=f"{prefix}_l{i}_ih.b"),
        )
        x, _ = rnn_layers.dynamic_lstm(
            gates, 4 * h, length=length,
            param_attr=ParamAttr(name=f"{prefix}_l{i}_hh.w"),
            bias_attr=ParamAttr(name=f"{prefix}_l{i}_hh.b"),
        )
    return x


def _dot_attention(dec_h, enc_h, src_pad):
    """Luong dot attention, fully batched: scores [B, Tt, Ts] in one
    matmul, masked softmax over source positions, context in a second
    matmul."""
    scores = layers.matmul(dec_h, enc_h, transpose_y=True)
    neg = layers.scale(
        layers.unsqueeze(layers.elementwise_sub(
            layers.fill_constant_like(src_pad, 1.0), src_pad), [1]),
        scale=-1e9,
    )  # [B, 1, Ts]
    scores = layers.elementwise_add(scores, neg)
    weights = layers.softmax(scores)
    return layers.matmul(weights, enc_h)  # [B, Tt, H]


def build(cfg: Optional[Seq2SeqConfig] = None):
    """Training graph. Feeds: src_ids [b, ts], trg_ids [b, tt],
    lbl_ids [b, tt], src_pad_mask [b, ts], trg_pad_mask [b, tt],
    src_len [b], trg_len [b]."""
    cfg = cfg or Seq2SeqConfig()
    src = layers.data("src_ids", shape=[-1], dtype="int64")
    trg = layers.data("trg_ids", shape=[-1], dtype="int64")
    lbl = layers.data("lbl_ids", shape=[-1], dtype="int64")
    src_pad = layers.data("src_pad_mask", shape=[-1], dtype="float32")
    trg_pad = layers.data("trg_pad_mask", shape=[-1], dtype="float32")
    src_len = layers.data("src_len", shape=[], dtype="int64")
    trg_len = layers.data("trg_len", shape=[], dtype="int64")

    enc_in = _embed(src, cfg.src_vocab_size, cfg.embed_dim, "src_emb.w")
    enc_h = _lstm_stack(enc_in, cfg, src_len, "enc", cfg.num_layers)

    dec_in = _embed(trg, cfg.trg_vocab_size, cfg.embed_dim, "trg_emb.w")
    dec_h = _lstm_stack(dec_in, cfg, trg_len, "dec", cfg.num_layers)

    ctx = _dot_attention(dec_h, enc_h, src_pad)
    merged = layers.fc(
        layers.concat([dec_h, ctx], axis=-1), cfg.hidden_dim,
        num_flatten_dims=2, act="tanh",
        param_attr=ParamAttr(name="attn_merge.w"),
        bias_attr=ParamAttr(name="attn_merge.b"),
    )
    logits = layers.fc(
        merged, cfg.trg_vocab_size, num_flatten_dims=2,
        param_attr=ParamAttr(name="proj.w"), bias_attr=False,
    )

    ce = layers.softmax_with_cross_entropy(logits, layers.unsqueeze(lbl, [2]))
    ce = layers.reshape(ce, [0, -1])
    masked = layers.elementwise_mul(ce, trg_pad)
    tokens = layers.elementwise_max(
        layers.reduce_sum(trg_pad),
        layers.fill_constant([], "float32", 1.0))
    loss = layers.elementwise_div(layers.reduce_sum(masked), tokens)
    return {
        "feeds": [src, trg, lbl, src_pad, trg_pad, src_len, trg_len],
        "loss": loss,
        "logits": logits,
        "config": cfg,
    }


def make_batch(cfg: Seq2SeqConfig, batch: int, src_len: int, trg_len: int,
               seed: int = 0) -> Dict[str, np.ndarray]:
    """Synthetic copy-ish task batch (labels derived from source so the
    model has signal to learn)."""
    r = np.random.RandomState(seed)
    src = r.randint(2, cfg.src_vocab_size, (batch, src_len)).astype(np.int64)
    trg = r.randint(2, cfg.trg_vocab_size, (batch, trg_len)).astype(np.int64)
    # labels derive from the source (cycled when trg is longer) so the
    # attention has signal to learn
    reps = -(-trg_len // src_len)  # ceil
    src_cycled = np.tile(src, (1, reps))[:, :trg_len]
    lbl = (src_cycled % (cfg.trg_vocab_size - 2) + 2).astype(np.int64)
    s_lens = r.randint(max(src_len // 2, 1), src_len + 1, batch)
    t_lens = r.randint(max(trg_len // 2, 1), trg_len + 1, batch)
    return {
        "src_ids": src,
        "trg_ids": trg,
        "lbl_ids": lbl,
        "src_pad_mask": (np.arange(src_len)[None] < s_lens[:, None]
                         ).astype(np.float32),
        "trg_pad_mask": (np.arange(trg_len)[None] < t_lens[:, None]
                         ).astype(np.float32),
        "src_len": s_lens.astype(np.int64),
        "trg_len": t_lens.astype(np.int64),
    }
