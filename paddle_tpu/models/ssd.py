"""SSD-style single-shot detector (reference: layers/detection.py
multi_box_head/ssd_loss composition; model family reference:
PaddleCV SSD on the Fluid 1.4 API).

Small configurable backbone (conv+BN blocks) with two detection feature
maps, the multi_box_head, and the fused ssd_loss. Ground truth arrives
densely padded: gt_box [N, G, 4] xyxy normalized to [0, 1] with
zero-area padding rows, gt_label [N, G] int64.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu import layers
from paddle_tpu.layers import detection


def _block(x, filters, stride, is_test):
    c = layers.conv2d(x, filters, 3, stride=stride, padding=1,
                      bias_attr=False)
    return layers.batch_norm(c, act="relu", is_test=is_test)


def ssd_net(img, image_shape=(3, 64, 64), num_classes=7, gt_capacity=8,
            is_test=False):
    """Build the detector. Returns dict with feeds + loss + heads."""
    x = _block(img, 16, 2, is_test)      # 32x32
    x = _block(x, 32, 2, is_test)        # 16x16
    f1 = _block(x, 32, 1, is_test)       # 16x16 feature map
    f2 = _block(f1, 64, 2, is_test)      # 8x8 feature map
    locs, confs, boxes, variances = detection.multi_box_head(
        [f1, f2], img, base_size=image_shape[-1],
        num_classes=num_classes,
        aspect_ratios=[[1.0, 2.0], [1.0, 2.0]],
        min_sizes=[image_shape[-1] * 0.2, image_shape[-1] * 0.5],
        max_sizes=[image_shape[-1] * 0.5, image_shape[-1] * 0.9],
        flip=True, clip=True)
    return locs, confs, boxes, variances


def get_model(batch_size=8, image_shape=(3, 64, 64), num_classes=7,
              gt_capacity=8, is_test=False):
    img = layers.data("image", shape=list(image_shape), dtype="float32")
    gt_box = layers.data("gt_box", shape=[gt_capacity, 4], dtype="float32")
    gt_label = layers.data("gt_label", shape=[gt_capacity], dtype="int64")
    locs, confs, boxes, variances = ssd_net(
        img, image_shape, num_classes, gt_capacity, is_test)
    # priors are normalized [0,1]; gt likewise
    loss = detection.ssd_loss(locs, confs, gt_box, gt_label, boxes,
                              variances)
    loss = layers.mean(loss)
    nmsed = detection.detection_output(
        locs, layers.softmax(confs), boxes, variances,
        keep_top_k=16, nms_top_k=32)
    return {
        "feeds": [img, gt_box, gt_label],
        "loss": loss,
        "locs": locs,
        "confs": confs,
        "detection": nmsed,
    }


def synthetic_batch(batch_size=8, image_shape=(3, 64, 64), num_classes=7,
                    gt_capacity=8, seed=0):
    """One synthetic batch: images with bright rectangles whose position
    defines the label (learnable signal), plus dense gt boxes."""
    r = np.random.RandomState(seed)
    imgs = r.normal(0, 0.1, (batch_size,) + tuple(image_shape)).astype(
        np.float32)
    boxes = np.zeros((batch_size, gt_capacity, 4), np.float32)
    labels = np.zeros((batch_size, gt_capacity), np.int64)
    for i in range(batch_size):
        n_obj = r.randint(1, 3)
        for j in range(n_obj):
            cx, cy = r.uniform(0.25, 0.75, 2)
            w, h = r.uniform(0.2, 0.4, 2)
            x1, y1 = max(cx - w / 2, 0.0), max(cy - h / 2, 0.0)
            x2, y2 = min(cx + w / 2, 1.0), min(cy + h / 2, 1.0)
            boxes[i, j] = [x1, y1, x2, y2]
            labels[i, j] = 1 + r.randint(num_classes - 1)
            hh, ww = image_shape[1], image_shape[2]
            imgs[i, :, int(y1 * hh):int(y2 * hh),
                 int(x1 * ww):int(x2 * ww)] += labels[i, j] / num_classes
    return {"image": imgs, "gt_box": boxes, "gt_label": labels}


# --------------------------------------------------------------------------
# SSD-300 (real scale): VGG16 backbone, 6 feature maps, 8732 priors
# (reference architecture: Liu et al. 2016; reference API surface:
# layers/detection.py multi_box_head/ssd_loss)
# --------------------------------------------------------------------------


def _vgg_block(x, filters, n, prefix):
    for i in range(n):
        x = layers.conv2d(x, filters, 3, padding=1, act="relu",
                          name=f"{prefix}_{i}")
    return x


def ssd300_net(img, num_classes=21):
    """VGG16-SSD300: maps at 38/19/10/5/3/1 -> 8732 priors."""
    x = _vgg_block(img, 64, 2, "conv1")
    x = layers.pool2d(x, 2, "max", 2)
    x = _vgg_block(x, 128, 2, "conv2")
    x = layers.pool2d(x, 2, "max", 2)
    x = _vgg_block(x, 256, 3, "conv3")
    x = layers.pool2d(x, 2, "max", 2, pool_padding=1)   # ceil: 38
    conv4 = _vgg_block(x, 512, 3, "conv4")              # 38x38
    x = layers.pool2d(conv4, 2, "max", 2)
    x = _vgg_block(x, 512, 3, "conv5")
    x = layers.pool2d(x, 3, "max", 1, pool_padding=1)
    x = layers.conv2d(x, 1024, 3, padding=6, dilation=6, act="relu",
                      name="fc6")                       # 19x19
    fc7 = layers.conv2d(x, 1024, 1, act="relu", name="fc7")
    x = layers.conv2d(fc7, 256, 1, act="relu", name="conv8_1")
    conv8 = layers.conv2d(x, 512, 3, stride=2, padding=1, act="relu",
                          name="conv8_2")               # 10x10
    x = layers.conv2d(conv8, 128, 1, act="relu", name="conv9_1")
    conv9 = layers.conv2d(x, 256, 3, stride=2, padding=1, act="relu",
                          name="conv9_2")               # 5x5
    x = layers.conv2d(conv9, 128, 1, act="relu", name="conv10_1")
    conv10 = layers.conv2d(x, 256, 3, act="relu", name="conv10_2")  # 3x3
    x = layers.conv2d(conv10, 128, 1, act="relu", name="conv11_1")
    conv11 = layers.conv2d(x, 256, 3, act="relu", name="conv11_2")  # 1x1

    maps = [conv4, fc7, conv8, conv9, conv10, conv11]
    return detection.multi_box_head(
        maps, img, base_size=300, num_classes=num_classes,
        aspect_ratios=[[2.0], [2.0, 3.0], [2.0, 3.0], [2.0, 3.0],
                       [2.0], [2.0]],
        min_sizes=[30.0, 60.0, 111.0, 162.0, 213.0, 264.0],
        max_sizes=[60.0, 111.0, 162.0, 213.0, 264.0, 315.0],
        steps=[8.0, 16.0, 32.0, 64.0, 100.0, 300.0],
        flip=True, clip=False)


def get_ssd300_model(num_classes=21, gt_capacity=50):
    """Real-scale SSD-300 training graph (8732 priors, VOC-sized class
    count, 50-row dense-padded gt) — the load-scale validation of the
    dense-padded detection design (BASELINE.md detection row)."""
    img = layers.data("image", shape=[3, 300, 300], dtype="float32")
    gt_box = layers.data("gt_box", shape=[gt_capacity, 4], dtype="float32")
    gt_label = layers.data("gt_label", shape=[gt_capacity], dtype="int64")
    locs, confs, boxes, variances = ssd300_net(img, num_classes)
    loss = layers.mean(detection.ssd_loss(
        locs, confs, gt_box, gt_label, boxes, variances))
    return {"feeds": [img, gt_box, gt_label], "loss": loss,
            "locs": locs, "confs": confs, "priors": boxes}
