"""Stacked dynamic-LSTM sentiment classifier.

The reference's ``stacked_dynamic_lstm`` benchmark model (reference:
benchmark/fluid/models/stacked_dynamic_lstm.py — IMDB sentiment, an
embedding into ``stacked_num`` fc+lstm blocks, elementwise-max pooled into
softmax). LoD sequences become padded [b, t] ids + a length mask
(SURVEY.md section 5); the recurrences are the fused ``lstm`` scan op.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from paddle_tpu import layers
from paddle_tpu.layers import rnn as rnn_layers
from paddle_tpu.param_attr import ParamAttr


class StackedLSTMConfig:
    def __init__(self, vocab_size: int = 5148, embed_dim: int = 128,
                 hidden_dim: int = 128, stacked_num: int = 3,
                 num_classes: int = 2, max_len: int = 128):
        self.vocab_size = vocab_size
        self.embed_dim = embed_dim
        self.hidden_dim = hidden_dim
        self.stacked_num = stacked_num
        self.num_classes = num_classes
        self.max_len = max_len


def build(cfg: Optional[StackedLSTMConfig] = None):
    """Feeds: words [b, t] int64, seq_len [b] int64, label [b, 1] int64."""
    cfg = cfg or StackedLSTMConfig()
    words = layers.data("words", shape=[cfg.max_len], dtype="int64")
    seq_len = layers.data("seq_len", shape=[], dtype="int64")
    label = layers.data("label", shape=[1], dtype="int64")

    x = layers.embedding(
        words, size=[cfg.vocab_size, cfg.embed_dim],
        param_attr=ParamAttr(name="slstm_emb.w"))
    for i in range(cfg.stacked_num):
        proj = layers.fc(x, cfg.hidden_dim * 4, num_flatten_dims=2,
                         param_attr=ParamAttr(name=f"slstm_fc{i}.w"),
                         bias_attr=ParamAttr(name=f"slstm_fc{i}.b"))
        h, _c = rnn_layers.dynamic_lstm(
            proj, cfg.hidden_dim * 4, length=seq_len,
            param_attr=ParamAttr(name=f"slstm_lstm{i}.w"),
            bias_attr=ParamAttr(name=f"slstm_lstm{i}.b"))
        x = h
    # masked max-pool over time (padding rows cannot win the max)
    pooled = layers.sequence_pool(x, "max", length=seq_len)
    logits = layers.fc(pooled, cfg.num_classes, num_flatten_dims=1,
                       param_attr=ParamAttr(name="slstm_out.w"),
                       bias_attr=ParamAttr(name="slstm_out.b"))
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(logits, label)
    return {"feeds": [words, seq_len, label], "loss": loss, "acc": acc,
            "logits": logits, "config": cfg}


def make_batch(cfg: StackedLSTMConfig, batch: int,
               seed: int = 0) -> Dict[str, np.ndarray]:
    """Synthetic batch with the imdb reader's hi/lo token signal."""
    r = np.random.RandomState(seed)
    half = cfg.vocab_size // 2
    words = np.zeros((batch, cfg.max_len), np.int64)
    lens = r.randint(cfg.max_len // 4, cfg.max_len, batch)
    labels = r.randint(0, 2, (batch, 1)).astype(np.int64)
    for i in range(batch):
        p_hi = 0.7 if labels[i, 0] else 0.3
        n = int(lens[i])
        hi = r.randint(half, cfg.vocab_size, n)
        lo = r.randint(2, half, n)
        pick = r.rand(n) < p_hi
        words[i, :n] = np.where(pick, hi, lo)
    return {"words": words, "seq_len": lens.astype(np.int64),
            "label": labels}
